"""Noise-aware perf regression gate over the BENCH trajectory.

Compares a fresh `bench.py` JSON against the repo's `BENCH_r*.json`
history (each an envelope whose `parsed` field holds the bench line):
for every metric present in both, the fresh value must not fall more
than `--tolerance` below the MEDIAN of its history — median-of-history
because single rounds on shared boxes are noisy, a tolerance because
even medians wobble, and per-metric because the experiments regress
independently.

Roofline-aware: when both the fresh run and the history carry a
roofline `fraction` (achieved bytes/s over the machine's calibrated
memory bandwidth, obs/roofline.py), the gate compares FRACTIONS instead
of raw MB/s — a slower machine then doesn't read as a code regression,
and a faster machine doesn't mask one (the decode-throughput-law view,
arxiv 2606.22423).

    python tools/benchgate.py fresh.json                # gate a run
    python tools/benchgate.py fresh.json --tolerance 0.3
    python tools/benchgate.py --smoke                   # self-check

Exit 0 = no regression (or not enough history to judge); 1 = at least
one metric regressed past tolerance; 2 = bad input.
"""
from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import statistics
import sys
from typing import Dict, List, Optional

DEFAULT_TOLERANCE = 0.25   # the 2-core CI box swings ±15-20% run to run
DEFAULT_MIN_HISTORY = 2
# absolute floor for exp_pushdown's speedup-vs-full-decode (the ISSUE 13
# acceptance claim: select 3-of-110 + ~1% filter must be >= 3x) — gated
# even with NO history, unlike the noise-relative metrics
DEFAULT_PUSHDOWN_FLOOR = 3.0
# round-trip parity (exp_roundtrip: decode->re-encode byte equality on
# the synthetic corpus) is a correctness bit, not a throughput: any run
# that RAN the experiment and lost parity fails outright, history-free
DEFAULT_PARITY_FLOOR = 1.0
# absolute floor for exp_stats' warm zone-map-skipped scan vs the plain
# pushdown scan of the SAME selective filter (the ISSUE 19 acceptance
# claim: skipping whole chunks before framing must be >= 2x on top of
# what PR 13's record-level pushdown already delivers) — history-free
DEFAULT_STATS_FLOOR = 2.0
# absolute floor for exp3's end-to-end/decode-only ratio (ISSUE 17: the
# one-fused-pass claim — ISSUE 15's native assembly lifted the honest
# e2e from ~0.15 of decode-only to ~0.6; the fused frame+segid scan,
# SIMD transcode, and take-elision push it past 0.8 against an HONEST
# fully-materialized decode-only denominator. A run that collapses back
# into the multi-pass shape fails this with no history needed)
DEFAULT_E2E_RATIO_FLOOR = 0.7


def load_bench_doc(path: str) -> Optional[dict]:
    """One bench JSON: either the raw line bench.py prints or the
    BENCH_r*.json envelope ({"parsed": <line>, "rc": ...})."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"benchgate: cannot read {path}: {exc}", file=sys.stderr)
        return None
    if not isinstance(doc, dict):
        return None
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        if doc.get("rc", 0) not in (0, None):
            return None  # a failed round's numbers are not a baseline
        return parsed
    return doc


def extract_metrics(doc: dict) -> Dict[str, dict]:
    """{metric name -> {'value': float, 'fraction': float|None}} for
    every throughput metric a bench doc carries (headline, decode_only,
    and the named side experiments). Metrics are keyed by their OWN
    `metric` name, so a renamed/retired experiment simply stops
    matching instead of comparing apples to oranges."""
    out: Dict[str, dict] = {}

    def add(sub) -> None:
        if not isinstance(sub, dict):
            return
        name = sub.get("metric")
        value = sub.get("value")
        if not name or not isinstance(value, (int, float)):
            return
        roof = sub.get("roofline")
        fraction = None
        if isinstance(roof, dict):
            fraction = roof.get("fraction")
        out[str(name)] = {"value": float(value),
                          "fraction": (float(fraction)
                                       if fraction else None)}

    add(doc)
    add(doc.get("decode_only"))
    for key in ("exp1", "exp2", "hierarchical", "exp_serve",
                "exp_pushdown", "exp_roundtrip", "exp_stats",
                "exp_compressed"):
        add(doc.get(key))
    # the fleet-mode serve experiment nests under exp_serve (it shares
    # that experiment's dataset); its aggregate-scaling metric gates on
    # its own history series like any top-level experiment
    serve = doc.get("exp_serve")
    if isinstance(serve, dict):
        add(serve.get("fleet"))
    # the pushdown experiment's speedup vs full decode gates as its own
    # metric: the >=3x claim must hold run over run, not just once. A
    # doc that RAN the experiment but produced no speedup (it raised —
    # incl. the in-run parity assertion) gates as value 0: the
    # acceptance claim must not go unenforced exactly when the
    # experiment is broken
    pd = doc.get("exp_pushdown")
    if isinstance(pd, dict):
        speedup = pd.get("speedup")
        out["exp_pushdown_speedup"] = {
            "value": (float(speedup)
                      if isinstance(speedup, (int, float)) else 0.0),
            "fraction": None}
    # the stats experiment's speedup vs the plain pushdown scan gates
    # the chunk-skipping claim the same way: ran-but-raised (no
    # speedup field — incl. the in-run parity assertion) gates as 0
    st = doc.get("exp_stats")
    if isinstance(st, dict):
        speedup = st.get("speedup_vs_pushdown")
        out["exp_stats_speedup"] = {
            "value": (float(speedup)
                      if isinstance(speedup, (int, float)) else 0.0),
            "fraction": None}
    # round-trip parity gates as its own metric whenever the doc ran
    # the exp_roundtrip experiment: parity lost (or the experiment
    # erroring — no parity field) gates as 0 against the absolute 1.0
    # floor. Docs predating the experiment are simply not gated
    rt = doc.get("exp_roundtrip")
    if isinstance(rt, dict):
        parity = rt.get("roundtrip_parity")
        out["exp_roundtrip_parity"] = {
            "value": 1.0 if parity is True else 0.0,
            "fraction": None}
    # compressed-feed parity gates identically: a doc that ran
    # exp_compressed must have decoded every compressed leg
    # byte-identical to the raw file (or it erred — also 0). The warm
    # re-scan nests under the experiment and gates on its own series
    ce = doc.get("exp_compressed")
    if isinstance(ce, dict):
        add(ce.get("warm"))
        parity = ce.get("compressed_parity")
        out["exp_compressed_parity"] = {
            "value": 1.0 if parity is True else 0.0,
            "fraction": None}
    # the assembly-overhead ratio: present whenever the doc carries BOTH
    # exp3 measurements (decode_only merged under an e2e headline), or
    # when the e2e experiment errored (`to_arrow` error record) — the
    # latter gates as 0 so a broken e2e cannot dodge the floor. Docs
    # predating the metric (neither key) are simply not comparable, and
    # a run that HONESTLY reports native_assembly=false (no .so on this
    # host — bench emits the flag for exactly this) is a fallback-only
    # environment whose ratio is not the native-assembly claim: the
    # floor abstains there; the ordinary history-median gating on the
    # raw e2e metric still catches real slowdowns
    if (isinstance(doc.get("decode_only"), dict)
            or isinstance(doc.get("to_arrow"), dict)) \
            and doc.get("native_assembly") is not False:
        ratio = doc.get("e2e_vs_decode_only")
        out["e2e_vs_decode_only"] = {
            "value": (float(ratio)
                      if isinstance(ratio, (int, float)) else 0.0),
            "fraction": None}
    return out


def gate(fresh: Dict[str, dict], history: List[Dict[str, dict]],
         tolerance: float, min_history: int,
         pushdown_floor: float = DEFAULT_PUSHDOWN_FLOOR,
         e2e_ratio_floor: float = DEFAULT_E2E_RATIO_FLOOR,
         parity_floor: float = DEFAULT_PARITY_FLOOR,
         stats_floor: float = DEFAULT_STATS_FLOOR) -> List[dict]:
    """Evaluate every fresh metric against its history series; returns
    one row per comparable metric with verdict 'ok' | 'regression' |
    'insufficient_history'. `exp_pushdown_speedup`,
    `e2e_vs_decode_only`, `exp_roundtrip_parity`, and
    `exp_stats_speedup` additionally gate against ABSOLUTE floors —
    the 3x pushdown claim, the native-assembly-overhead claim,
    encode/decode byte parity, and the 2x chunk-skipping claim need
    no history to be falsifiable."""
    floors = {"exp_pushdown_speedup": pushdown_floor,
              "e2e_vs_decode_only": e2e_ratio_floor,
              "exp_roundtrip_parity": parity_floor,
              "exp_compressed_parity": parity_floor,
              "exp_stats_speedup": stats_floor}
    rows: List[dict] = []
    for name, entry in sorted(fresh.items()):
        floor = floors.get(name, 0.0)
        if floor > 0:
            value = entry["value"]
            rows.append({
                "metric": name, "basis": "absolute_floor",
                "value": round(value, 3), "floor": floor,
                "history_n": 0,
                "verdict": ("ok" if value >= floor
                            else "regression")})
            continue
        series_frac = [h[name]["fraction"] for h in history
                       if name in h and h[name]["fraction"]]
        series_raw = [h[name]["value"] for h in history if name in h]
        use_fraction = (entry["fraction"] is not None
                        and len(series_frac) >= min_history)
        series = series_frac if use_fraction else series_raw
        value = entry["fraction"] if use_fraction else entry["value"]
        row = {"metric": name,
               "basis": "roofline_fraction" if use_fraction else "raw",
               "value": round(value, 4) if value else value,
               "history_n": len(series)}
        if len(series) < min_history:
            row["verdict"] = "insufficient_history"
            rows.append(row)
            continue
        med = statistics.median(series)
        floor = med * (1.0 - tolerance)
        row["median"] = round(med, 4)
        row["floor"] = round(floor, 4)
        row["ratio"] = round(value / med, 3) if med else None
        row["verdict"] = "regression" if value < floor else "ok"
        rows.append(row)
    return rows


def run_gate(fresh_path: str, history_glob: str, tolerance: float,
             min_history: int) -> int:
    fresh_doc = load_bench_doc(fresh_path)
    if fresh_doc is None:
        print(f"benchgate: unreadable fresh bench JSON: {fresh_path}",
              file=sys.stderr)
        return 2
    history_docs = []
    for p in sorted(_glob.glob(history_glob)):
        if os.path.abspath(p) == os.path.abspath(fresh_path):
            continue  # the run under test must not be its own baseline
        doc = load_bench_doc(p)
        if doc is not None:
            history_docs.append(extract_metrics(doc))
    fresh = extract_metrics(fresh_doc)
    if not fresh:
        print("benchgate: fresh JSON carries no comparable metrics",
              file=sys.stderr)
        return 2
    rows = gate(fresh, history_docs, tolerance, min_history)
    bad = [r for r in rows if r["verdict"] == "regression"]
    for r in rows:
        mark = {"ok": "OK  ", "regression": "FAIL",
                "insufficient_history": "--  "}[r["verdict"]]
        line = (f"{mark} {r['metric']:<36} {r['basis']:<17} "
                f"value={r['value']}")
        if r["basis"] == "absolute_floor":
            line += f" floor={r['floor']}"
        elif "median" in r:
            line += (f" median={r['median']} floor={r['floor']} "
                     f"x{r['ratio']}")
        else:
            line += f" (history n={r['history_n']} < {min_history})"
        print(line)
    if bad:
        print(f"benchgate: {len(bad)} metric(s) regressed more than "
              f"{tolerance * 100:.0f}% below the history median")
        return 1
    print("benchgate: no regression "
          f"({len(rows)} metric(s), tolerance {tolerance * 100:.0f}%)")
    return 0


# ---------------------------------------------------------------------------
# --smoke: self-check on synthetic history (what tier-1 runs)
# ---------------------------------------------------------------------------

def _doc(headline: float, exp1: float, fraction: Optional[float] = None):
    d = {"metric": "exp3_to_arrow", "value": headline, "unit": "MB/s",
         "exp1": {"metric": "exp1_to_arrow", "value": exp1,
                  "unit": "MB/s"}}
    if fraction is not None:
        d["roofline"] = {"bandwidth_GBps": 10.0, "fraction": fraction}
    return d


def _smoke() -> int:
    ok = True

    def check(label: str, cond: bool) -> None:
        nonlocal ok
        print(f"  {'ok' if cond else 'FAILED'}: {label}")
        ok &= cond

    hist = [extract_metrics(_doc(100.0, 50.0, 0.10)),
            extract_metrics(_doc(110.0, 52.0, 0.11)),
            extract_metrics(_doc(90.0, 48.0, 0.09))]

    rows = gate(extract_metrics(_doc(98.0, 49.0, 0.10)), hist, 0.25, 2)
    check("steady run passes",
          all(r["verdict"] == "ok" for r in rows))

    rows = gate(extract_metrics(_doc(40.0, 50.0, 0.04)), hist, 0.25, 2)
    check("50% headline drop is caught",
          any(r["metric"] == "exp3_to_arrow"
              and r["verdict"] == "regression" for r in rows))

    # slower machine: raw MB/s halves but the roofline fraction holds —
    # the fraction basis must keep this green
    rows = gate(extract_metrics(_doc(50.0, 25.0, 0.10)), hist, 0.25, 2)
    headline = next(r for r in rows if r["metric"] == "exp3_to_arrow")
    check("machine change rides the fraction basis",
          headline["basis"] == "roofline_fraction"
          and headline["verdict"] == "ok")

    # exp1 carries no fraction -> raw basis -> the drop IS a regression
    check("fraction-less metric still gates on raw",
          any(r["metric"] == "exp1_to_arrow"
              and r["verdict"] == "regression" for r in rows))

    # one-round history: not enough to judge, never a false failure
    rows = gate(extract_metrics(_doc(40.0, 20.0)), hist[:1], 0.25, 2)
    check("thin history abstains",
          all(r["verdict"] == "insufficient_history" for r in rows))

    # exp_pushdown speedup gates on the absolute 3x floor, history-free
    pd_doc = {"metric": "exp3_to_arrow", "value": 100.0, "unit": "MB/s",
              "exp_pushdown": {"metric": "exp_pushdown_to_arrow",
                               "value": 900.0, "unit": "MB/s",
                               "speedup": 4.5}}
    rows = gate(extract_metrics(pd_doc), [], 0.25, 2)
    check("pushdown speedup >= floor passes with no history",
          any(r["metric"] == "exp_pushdown_speedup"
              and r["verdict"] == "ok" for r in rows))
    pd_doc["exp_pushdown"]["speedup"] = 1.4
    rows = gate(extract_metrics(pd_doc), [], 0.25, 2)
    check("pushdown speedup below the 3x floor is caught",
          any(r["metric"] == "exp_pushdown_speedup"
              and r["verdict"] == "regression" for r in rows))

    # an errored experiment (no speedup field) must gate as a failure,
    # not silently skip the floor
    pd_doc["exp_pushdown"] = {"metric": "exp_pushdown_to_arrow",
                              "error": "boom"}
    rows = gate(extract_metrics(pd_doc), [], 0.25, 2)
    check("errored pushdown experiment fails the floor",
          any(r["metric"] == "exp_pushdown_speedup"
              and r["verdict"] == "regression" for r in rows))

    # exp_stats' speedup over the plain pushdown scan gates on the
    # absolute 2x floor, history-free
    st_doc = {"metric": "exp3_to_arrow", "value": 100.0, "unit": "MB/s",
              "exp_stats": {"metric": "exp_stats_to_arrow",
                            "value": 2400.0, "unit": "MB/s",
                            "speedup_vs_pushdown": 3.1}}
    rows = gate(extract_metrics(st_doc), [], 0.25, 2)
    check("stats chunk-skip speedup >= floor passes with no history",
          any(r["metric"] == "exp_stats_speedup"
              and r["verdict"] == "ok" for r in rows))
    st_doc["exp_stats"]["speedup_vs_pushdown"] = 1.2
    rows = gate(extract_metrics(st_doc), [], 0.25, 2)
    check("stats speedup below the 2x floor is caught",
          any(r["metric"] == "exp_stats_speedup"
              and r["verdict"] == "regression" for r in rows))
    st_doc["exp_stats"] = {"metric": "exp_stats_to_arrow",
                           "error": "boom"}
    rows = gate(extract_metrics(st_doc), [], 0.25, 2)
    check("errored stats experiment fails the floor",
          any(r["metric"] == "exp_stats_speedup"
              and r["verdict"] == "regression" for r in rows))
    check("docs predating exp_stats are not gated on it",
          "exp_stats_speedup" not in extract_metrics(
              _doc(100.0, 50.0)))

    # e2e_vs_decode_only gates on its absolute floor, history-free
    ratio_doc = {"metric": "exp3_to_arrow", "value": 500.0,
                 "unit": "MB/s",
                 "decode_only": {"metric": "exp3_decode", "value": 800.0},
                 "e2e_vs_decode_only": 0.82}
    rows = gate(extract_metrics(ratio_doc), [], 0.25, 2)
    check("e2e/decode ratio above the floor passes",
          any(r["metric"] == "e2e_vs_decode_only"
              and r["verdict"] == "ok" for r in rows))
    ratio_doc["e2e_vs_decode_only"] = 0.12
    rows = gate(extract_metrics(ratio_doc), [], 0.25, 2)
    check("collapsed e2e/decode ratio is caught",
          any(r["metric"] == "e2e_vs_decode_only"
              and r["verdict"] == "regression" for r in rows))
    del ratio_doc["e2e_vs_decode_only"]
    rows = gate(extract_metrics(ratio_doc), [], 0.25, 2)
    check("missing ratio with decode_only present fails the floor",
          any(r["metric"] == "e2e_vs_decode_only"
              and r["verdict"] == "regression" for r in rows))
    err_doc = {"metric": "exp3_decode", "value": 800.0,
               "to_arrow": {"metric": "exp3_to_arrow", "error": "boom"}}
    rows = gate(extract_metrics(err_doc), [], 0.25, 2)
    check("errored e2e experiment fails the ratio floor",
          any(r["metric"] == "e2e_vs_decode_only"
              and r["verdict"] == "regression" for r in rows))
    check("docs predating the ratio are not gated on it",
          "e2e_vs_decode_only" not in extract_metrics(_doc(100.0, 50.0)))
    ratio_doc["native_assembly"] = False
    ratio_doc["e2e_vs_decode_only"] = 0.15
    check("fallback-only host (native_assembly=false) abstains",
          "e2e_vs_decode_only" not in extract_metrics(ratio_doc))

    # round-trip parity gates as a hard, history-free failure; the
    # encode throughput rides the ordinary history-median gate
    rt_doc = {"metric": "exp3_to_arrow", "value": 100.0, "unit": "MB/s",
              "exp_roundtrip": {"metric": "exp_roundtrip_encode",
                                "value": 13.0, "unit": "MB/s",
                                "decode_mbps": 190.0,
                                "roundtrip_parity": True}}
    rows = gate(extract_metrics(rt_doc), [], 0.25, 2)
    check("round-trip parity passes with no history",
          any(r["metric"] == "exp_roundtrip_parity"
              and r["verdict"] == "ok" for r in rows))
    rt_hist = [extract_metrics(rt_doc) for _ in range(3)]
    rt_doc["exp_roundtrip"]["roundtrip_parity"] = False
    rows = gate(extract_metrics(rt_doc), rt_hist, 0.25, 2)
    check("lost round-trip parity is a hard failure",
          any(r["metric"] == "exp_roundtrip_parity"
              and r["verdict"] == "regression" for r in rows))
    rt_doc["exp_roundtrip"] = {"metric": "exp_roundtrip_encode",
                               "error": "boom"}
    rows = gate(extract_metrics(rt_doc), rt_hist, 0.25, 2)
    check("errored round-trip experiment fails the parity floor",
          any(r["metric"] == "exp_roundtrip_parity"
              and r["verdict"] == "regression" for r in rows))
    rt_doc["exp_roundtrip"] = {"metric": "exp_roundtrip_encode",
                               "value": 6.0, "unit": "MB/s",
                               "roundtrip_parity": True}
    rows = gate(extract_metrics(rt_doc), rt_hist, 0.25, 2)
    check("encode throughput drop gates on history",
          any(r["metric"] == "exp_roundtrip_encode"
              and r["verdict"] == "regression" for r in rows))
    check("docs predating exp_roundtrip are not gated on parity",
          "exp_roundtrip_parity" not in extract_metrics(
              _doc(100.0, 50.0)))

    # compressed-feed parity gates hard and history-free; the cold
    # headline and the nested warm re-scan gate on their own series
    ce_doc = {"metric": "exp3_to_arrow", "value": 100.0, "unit": "MB/s",
              "exp_compressed": {"metric": "exp_compressed_e2e",
                                 "value": 80.0, "unit": "MB/s",
                                 "compressed_parity": True,
                                 "warm": {"metric":
                                          "exp_compressed_warm",
                                          "value": 160.0,
                                          "unit": "MB/s"}}}
    rows = gate(extract_metrics(ce_doc), [], 0.25, 2)
    check("compressed parity passes with no history",
          any(r["metric"] == "exp_compressed_parity"
              and r["verdict"] == "ok" for r in rows))
    check("warm compressed re-scan metric is extracted",
          "exp_compressed_warm" in extract_metrics(ce_doc))
    ce_hist = [extract_metrics(ce_doc) for _ in range(3)]
    ce_doc["exp_compressed"]["compressed_parity"] = False
    rows = gate(extract_metrics(ce_doc), ce_hist, 0.25, 2)
    check("lost compressed parity is a hard failure",
          any(r["metric"] == "exp_compressed_parity"
              and r["verdict"] == "regression" for r in rows))
    ce_doc["exp_compressed"] = {"metric": "exp_compressed_e2e",
                                "error": "boom"}
    rows = gate(extract_metrics(ce_doc), ce_hist, 0.25, 2)
    check("errored compressed experiment fails the parity floor",
          any(r["metric"] == "exp_compressed_parity"
              and r["verdict"] == "regression" for r in rows))
    ce_doc["exp_compressed"] = {"metric": "exp_compressed_e2e",
                                "value": 30.0, "unit": "MB/s",
                                "compressed_parity": True,
                                "warm": {"metric": "exp_compressed_warm",
                                         "value": 40.0, "unit": "MB/s"}}
    rows = gate(extract_metrics(ce_doc), ce_hist, 0.25, 2)
    check("warm compressed re-scan drop gates on history",
          any(r["metric"] == "exp_compressed_warm"
              and r["verdict"] == "regression" for r in rows))
    check("docs predating exp_compressed are not gated on it",
          "exp_compressed_parity" not in extract_metrics(
              _doc(100.0, 50.0)))

    # the fleet aggregate nests under exp_serve and must gate on its
    # own history series like a top-level experiment
    fleet_doc = {"metric": "exp3_to_arrow", "value": 100.0,
                 "unit": "MB/s",
                 "exp_serve": {
                     "metric": "exp_serve_streamed_to_arrow",
                     "value": 60.0, "unit": "MB/s",
                     "fleet": {"metric": "exp_serve_fleet_aggregate",
                               "value": 200.0, "unit": "MB/s"}}}
    fleet_hist = [extract_metrics(fleet_doc) for _ in range(3)]
    check("fleet aggregate metric is extracted",
          "exp_serve_fleet_aggregate" in extract_metrics(fleet_doc))
    fleet_doc["exp_serve"]["fleet"]["value"] = 80.0
    rows = gate(extract_metrics(fleet_doc), fleet_hist, 0.25, 2)
    check("fleet aggregate-scaling drop is caught",
          any(r["metric"] == "exp_serve_fleet_aggregate"
              and r["verdict"] == "regression" for r in rows))

    # envelope parsing: failed rounds are excluded from the baseline
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump({"parsed": _doc(1.0, 1.0), "rc": 1}, f)
        p = f.name
    try:
        check("rc!=0 envelope yields no baseline",
              load_bench_doc(p) is None)
    finally:
        os.unlink(p)

    print("OK: benchgate smoke passed" if ok
          else "FAILED: benchgate smoke")
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("fresh", nargs="?",
                    help="fresh bench.py JSON (line or BENCH envelope)")
    ap.add_argument("--history", default=None,
                    help="glob of history files "
                         "(default: BENCH_r*.json next to this repo)")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="allowed drop below the history median "
                         "(fraction, default 0.25)")
    ap.add_argument("--min-history", type=int,
                    default=DEFAULT_MIN_HISTORY,
                    help="series length required before gating")
    ap.add_argument("--smoke", action="store_true",
                    help="self-check the gate on synthetic history")
    args = ap.parse_args()
    if args.smoke:
        return _smoke()
    if not args.fresh:
        ap.error("a fresh bench JSON (or --smoke) is required")
    history = args.history
    if history is None:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        history = os.path.join(repo, "BENCH_r*.json")
    return run_gate(args.fresh, history, args.tolerance,
                    args.min_history)


if __name__ == "__main__":
    sys.exit(main())
