"""Benchmark: exp3 multisegment-wide scan throughput (MB/s).

Reproduces the reference's north-star workload (BASELINE.md exp3:
RDW variable-length multisegment file; wide 'C' segments with
STRATEGY-DETAIL OCCURS 2000 of COMP + COMP-3, 16,068-byte records,
interleaved with 64-byte 'P' contact segments). Reference single-core
throughput is ~8.0 MB/s (performance/exp3_multiseg_wide.csv); the
vs_baseline field is measured MB/s / 8.0.

The HEADLINE is the honest end-to-end conversion: file -> RDW framing
-> segment split -> kernel decode -> Arrow table, timed exactly like
the reference job produced Parquet columns. The kernel-only framing +
decode measurement (no Arrow assembly; the number earlier rounds
headlined) stays alongside as `decode_only` — comparing IT against the
full-conversion baseline overstates, so `vs_baseline` uses the
end-to-end value. Data generation and jit warmup are excluded.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_MBPS = 8.0  # exp3, 1 executor (BASELINE.md)


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _calibrate_roofline():
    """One-time host memory-bandwidth calibration (obs/roofline.py;
    cached on disk so later rounds and per-read metrics reuse it).
    Returns bytes/s or None — a failed calibration must never sink the
    bench."""
    try:
        from cobrix_tpu.obs.roofline import measured_bandwidth

        t0 = time.perf_counter()
        bw = measured_bandwidth()
        _log(f"roofline: host memory bandwidth {bw / 1e9:.1f} GB/s "
             f"({time.perf_counter() - t0:.1f}s; cached)")
        return bw
    except Exception as exc:
        _log(f"roofline calibration failed: {exc}")
        return None


def _roofline_field(mbps) -> dict:
    """{'calibrated_GBps', 'fraction'} anchoring a measured MB/s against
    the cached calibration — the decode-throughput-law view (arxiv
    2606.22423): regressions visible as a fraction of the hardware
    limit, not just MB/s. None when uncalibrated."""
    try:
        from cobrix_tpu.obs.roofline import cached_bandwidth

        bw = cached_bandwidth()
        if not bw or not mbps:
            return None
        return {"calibrated_GBps": round(bw / 1e9, 2),
                "fraction": round(mbps * 1024 * 1024 / bw, 4)}
    except Exception:
        return None


def _top_fields_profile(path, kw, n=5):
    """Top-N per-field costs from ONE attribution-enabled read of the
    same workload (cobrix_tpu.obs.fieldcost). Run SEPARATELY from the
    timed runs so the headline numbers never carry attribution
    overhead; the table makes the BENCH trajectory self-describing
    about WHICH columns the time goes to."""
    try:
        from cobrix_tpu import read_cobol
        from cobrix_tpu.obs.fieldcost import top_fields

        out = read_cobol(path, field_costs="true", **kw)
        out.to_arrow()
        costs = out.metrics.field_costs if out.metrics else None
        return top_fields(costs, n) if costs else None
    except Exception as exc:
        _log(f"field-cost profile failed: {exc}")
        return None


def _axon_relay_down():
    """Fast dead-tunnel detection: under the loopback-relay axon setup,
    jax rides local TCP relay ports — when none accept a connection, the
    jax init can only hang, so the escalating subprocess probes (5 min of
    timeouts) are pointless. Only applies to the loopback-relay
    configuration; any other device setup takes the normal probe."""
    import socket

    if "axon" not in os.environ.get("JAX_PLATFORMS", ""):
        return False
    if os.environ.get("PALLAS_AXON_POOL_IPS") != "127.0.0.1":
        return False
    for port in (8082, 8083, 8087, 8092):
        s = socket.socket()
        s.settimeout(2)
        try:
            s.connect(("127.0.0.1", port))
            s.close()
            return False  # a relay listener is alive
        except OSError:
            s.close()
    return True


# the device-probe contract (ROADMAP item 3 first step): ONE bounded
# subprocess attempt under a HARD deadline — the 60/90/150s escalation
# burned 5 minutes per round once the tunnel wedged permanently
# (BENCH_r04/r05 "timed out after 30s" was actually this ladder) — plus
# a small on-disk cache so platform detection survives ACROSS bench
# runs: a cached success answers instantly, a cached failure skips the
# wait entirely (with the original reason preserved) until its TTL
# lapses. Every no-device outcome carries a structured `skip_reason`
# in the BENCH JSON so CI shows WHY the device is unmeasured.
PROBE_DEADLINE_S = float(os.environ.get("BENCH_JAX_PROBE_DEADLINE_S",
                                        "45"))
PROBE_FAIL_TTL_S = float(os.environ.get("BENCH_JAX_PROBE_FAIL_TTL_S",
                                        "1800"))
PROBE_OK_TTL_S = float(os.environ.get("BENCH_JAX_PROBE_OK_TTL_S",
                                      "86400"))


def _probe_cache_path() -> str:
    return os.environ.get("COBRIX_JAX_PROBE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "cobrix_tpu",
        "jax_probe.json")


def _probe_env_fingerprint() -> str:
    """Cache key: anything that changes which device jax would find.
    A different interpreter, platform pin, or relay pool must never
    reuse another configuration's answer."""
    import hashlib

    parts = [sys.executable,
             os.environ.get("JAX_PLATFORMS", ""),
             os.environ.get("PALLAS_AXON_POOL_IPS", ""),
             os.environ.get("COBRIX_TPU_TESTS", "")]
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def _probe_cache_load() -> dict:
    try:
        with open(_probe_cache_path(), encoding="utf-8") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else {}
    except (OSError, ValueError):
        return {}


def _probe_cache_store(entry: dict) -> None:
    try:
        from cobrix_tpu.utils.atomic import write_atomic

        doc = _probe_cache_load()
        doc[_probe_env_fingerprint()] = entry
        path = _probe_cache_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        write_atomic(path, json.dumps(doc, sort_keys=True))
    except OSError:
        pass  # an unwritable cache just means re-probing next run


def _probe_jax(deadline_s=None, use_cache=True):
    """Bounded device detection: returns ``(platform | None, probe |
    None)``. `probe` is None when a device answered; otherwise ONE
    structured dict — ``{"skip_reason", "error", "deadline_s",
    "cached", "attempts"}`` — embedded in the BENCH JSON as
    ``jax_probe`` so WHY the device path did not run survives as data.

    skip_reason vocabulary: ``relay_down`` (loopback relay ports
    closed — no probe can succeed), ``init_timeout`` (jax init blew the
    hard deadline and was killed), ``init_error`` (init failed fast),
    ``cached_failure`` (a previous run's failure is still inside its
    TTL — the original reason rides along in ``error``)."""
    if os.environ.get("BENCH_FORCE_CPU"):
        return "cpu", None
    deadline = (PROBE_DEADLINE_S if deadline_s is None
                else max(1.0, float(deadline_s)))
    if use_cache:
        entry = _probe_cache_load().get(_probe_env_fingerprint())
        if isinstance(entry, dict) and "ts" in entry:
            age = time.time() - float(entry.get("ts") or 0)
            if entry.get("platform") and age < PROBE_OK_TTL_S:
                _log(f"jax platform '{entry['platform']}' from probe "
                     f"cache ({age:.0f}s old)")
                return entry["platform"], None
            if not entry.get("platform") and age < PROBE_FAIL_TTL_S:
                probe = {
                    "skip_reason": "cached_failure",
                    "error": (f"cached {entry.get('skip_reason')} "
                              f"{age:.0f}s ago: "
                              f"{entry.get('error') or ''}").strip(),
                    "deadline_s": deadline, "cached": True,
                    "attempts": []}
                _log(f"jax probe skipped: {probe['error']} "
                     f"(retry after {PROBE_FAIL_TTL_S - age:.0f}s or "
                     "clear the probe cache)")
                return None, probe
    if _axon_relay_down():
        # no relay listener can possibly answer; probing would only
        # burn the deadline — record the reason and move on
        probe = {"skip_reason": "relay_down",
                 "error": "axon loopback relay ports closed "
                          "(no TPU tunnel listener)",
                 "deadline_s": deadline, "cached": False,
                 "attempts": []}
        _probe_cache_store({"skip_reason": "relay_down",
                            "error": probe["error"],
                            "ts": time.time()})
        _log(f"jax probe skipped: {probe['error']}")
        return None, probe
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=deadline, capture_output=True, text=True)
        if proc.returncode == 0 and proc.stdout.strip():
            platform = proc.stdout.strip().splitlines()[-1]
            _probe_cache_store({"platform": platform,
                                "ts": time.time()})
            return platform, None
        skip_reason = "init_error"
        err = (proc.stderr or "jax init failed").strip()[-400:]
    except subprocess.TimeoutExpired:
        # subprocess.run killed the child at the deadline — the HARD
        # bound: the bench never waits longer than this, ever
        skip_reason = "init_timeout"
        err = f"jax device init exceeded the {deadline:.0f}s deadline"
    probe = {"skip_reason": skip_reason, "error": err,
             "deadline_s": deadline, "cached": False,
             "attempts": [{"timeout_s": deadline, "error": err}]}
    _probe_cache_store({"skip_reason": skip_reason, "error": err,
                        "ts": time.time()})
    _log(f"jax probe failed ({skip_reason}): {err}")
    return None, probe


def run_device_query(mb_target: float, platform: str) -> dict:
    """The device-resident query benchmark: decode + aggregate the exp3
    wide-segment numeric plane ON the device; only scalar aggregates cross
    the link back (parallel/query.py — the architectural answer to the
    ~20 MB/s D2H tunnel wall; the pipeline the reference needs a whole
    Spark stage after the Cobrix scan to express).

    Phases reported separately: host RDW framing + [n, extent] pack,
    H2D streaming (link-bound, ~1.1 GB/s budget), device decode+reduce,
    and the pipelined end-to-end rate over the total file bytes.
    """
    from cobrix_tpu import native
    from cobrix_tpu.parallel import DeviceAggregator, merge_aggregates
    from cobrix_tpu.reader.parameters import (
        MultisegmentParameters,
        ReaderParameters,
    )
    from cobrix_tpu.reader.var_len_reader import VarLenReader
    from cobrix_tpu.testing.generators import EXP3_COPYBOOK, generate_exp3

    import jax

    params = ReaderParameters(
        is_record_sequence=True,
        multisegment=MultisegmentParameters(
            segment_id_field="SEGMENT-ID",
            segment_id_redefine_map={"C": "STATIC_DETAILS",
                                     "P": "CONTACTS"}))
    reader = VarLenReader(EXP3_COPYBOOK, params)
    # backend resolves per platform: fused Pallas kernel on TPU, the XLA
    # gather path elsewhere (parallel/sharded.resolve_device_backend)
    agg = DeviceAggregator(reader.copybook, columns=["NUM1", "NUM2"],
                           active_segment="STATIC_DETAILS")
    _log(f"device query decode backend: {agg.decoder.backend}")

    est_per_record = 16072 * 0.33 + 68 * 0.67
    n_records = max(64, int(mb_target * 1024 * 1024 / est_per_record))
    raw = generate_exp3(n_records, seed=100)
    total_mb = len(raw) / (1024 * 1024)
    rs = agg.record_extent
    # ~32MB blocks: the tunnel link's measured rate roughly doubles from
    # 8MB transfers to 32-64MB ones (fixed per-transfer overhead)
    block = int(os.environ.get(
        "BENCH_DEVICE_BLOCK", str(max(512, (32 * 1024 * 1024 // rs + 255)
                                      // 256 * 256))))

    def frame_and_pack():
        """RDW scan + gather the wide 'C' records into fixed [block, rs]
        matrices (host side of the pipeline)."""
        offsets, lengths = native.rdw_scan(raw, big_endian=False)
        pos = np.nonzero(lengths >= 1000)[0]
        coffs = offsets[pos]
        buf = np.frombuffer(raw, dtype=np.uint8)
        mats = []
        for i in range(0, len(coffs), block):
            o = coffs[i:i + block]
            mats.append(buf[o[:, None] + np.arange(rs)[None, :]])
        return mats

    # warmup: compile the aggregate program on one block shape
    t0 = time.perf_counter()
    mats = frame_and_pack()
    pack_s = time.perf_counter() - t0
    x, n = agg.put(mats[0], block=block)
    agg.aggregate_device(x, n)
    _log(f"device query warmup (incl. compile): "
         f"{time.perf_counter() - t0:.1f}s; {len(mats)} blocks of {block}")

    c_bytes = sum(m.nbytes for m in mats)

    # phase timing (synchronized per block)
    h2d_s = comp_s = 0.0
    for m in mats:
        t0 = time.perf_counter()
        x, n = agg.put(m, block=block)
        # force completion of EVERY shard's transfer: a one-column slice
        # touches all rows, so the gather waits on the whole mesh
        # (block_until_ready is unreliable on tunneled devices)
        jax.device_get(x[:, :1])
        h2d_s += time.perf_counter() - t0
        t0 = time.perf_counter()
        agg.aggregate_device(x, n)
        comp_s += time.perf_counter() - t0

    # end-to-end (pipelined: submit all blocks, fetch at the end)
    times = []
    for _ in range(2):
        t0 = time.perf_counter()
        pend = []
        for m in frame_and_pack():
            x, n = agg.put(m, block=block)
            pend.append(agg.submit(x, n))
        parts = [agg.fetch(p) for p in pend]
        merged = merge_aggregates(parts)
        times.append(time.perf_counter() - t0)
    e2e = min(times)
    d2h_bytes = len(parts) * sum(28 + len(k) for k in parts[0]) + 4

    # one profiler trace artifact of a single aggregate step (SURVEY.md §5
    # tracing row): loadable in TensorBoard/XProf; recorded in the JSON
    trace_dir = os.environ.get("BENCH_TRACE_DIR", "bench_trace")
    trace_status = trace_dir
    try:
        from cobrix_tpu.profiling import profile_trace

        with profile_trace(trace_dir):
            x, n = agg.put(mats[0], block=block)
            agg.aggregate_device(x, n)
    except Exception as exc:  # the trace must never sink the bench
        trace_status = f"unavailable: {str(exc)[:200]}"
        _log(f"profiler trace failed: {exc}")

    # projected single-column variant: the NUM1-only query byte-projects
    # to ~half the record (DeviceAggregator._build_byte_projection), so
    # the link-bound end-to-end rate scales with the projection ratio —
    # the measurable payoff of `select` on a remote-attached device
    proj = None
    try:
        agg1 = DeviceAggregator(reader.copybook, columns=["NUM1"],
                                active_segment="STATIC_DETAILS")
        x, n1 = agg1.put(mats[0], block=block)
        agg1.aggregate_device(x, n1)  # compile
        times1 = []
        for _ in range(2):
            t0 = time.perf_counter()
            pend = [agg1.submit(*agg1.put(m, block=block)) for m in mats]
            parts1 = [agg1.fetch(p) for p in pend]
            times1.append(time.perf_counter() - t0)
        proj_bytes = (len(agg1.gather_index)
                      if agg1.gather_index is not None else rs)
        proj = {
            "end_to_end_MBps": round(total_mb / min(times1), 1),
            "projection_ratio": round(rs / proj_bytes, 2),
            "num1_sum": merge_aggregates(parts1)["NUM1"]["sum"],
        }
        _log(f"projected NUM1-only query: {proj}")
    except Exception as exc:
        _log(f"projected query failed: {exc}")

    result = {
        "metric": f"exp3_device_aggregate_{agg.decoder.backend}",
        "platform": platform,
        "backend": agg.decoder.backend,
        "fused": agg.decoder.backend == "pallas",
        "end_to_end_MBps": round(total_mb / e2e, 1),
        "vs_baseline": round(total_mb / e2e / BASELINE_MBPS, 1),
        "h2d_MBps": round(c_bytes / (1024 * 1024) / h2d_s, 1),
        "device_compute_MBps": round(c_bytes / (1024 * 1024) / comp_s, 1),
        "host_pack_MBps": round(total_mb / pack_s, 1),
        "d2h_bytes": d2h_bytes,
        "records": int(sum(p["NUM1"]["count"] for p in parts) / 2000),
        "total_MB": round(total_mb, 1),
        "block_records": block,
        "projected_num1": proj,
        "trace": trace_status,
    }
    _log(f"device query: {result}")
    _log(f"aggregate sample: NUM1 sum={merged['NUM1']['sum']:.0f} "
         f"count={merged['NUM1']['count']}")
    return result


def run_device_pipeline(mb_target: float, platform: str) -> dict:
    """The on-HBM end-to-end pipeline: ONE H2D transfer of the raw exp3
    file image, then frame (pointer-doubling RDW scan) -> select wide
    records -> pack -> fused decode -> aggregate, all inside device
    programs — zero host round trips until the scalar fetch. Reports the
    h2d / device-compute split so the link-bound tunnel rate and the
    chip's own throughput are never conflated (VERDICT r4 weak #6: this
    pipeline existed but had no recorded perf number)."""
    import jax

    from cobrix_tpu.ops.device_framing import build_wide_pipeline
    from cobrix_tpu.parallel import DeviceAggregator
    from cobrix_tpu.reader.parameters import (
        MultisegmentParameters,
        ReaderParameters,
    )
    from cobrix_tpu.reader.var_len_reader import VarLenReader
    from cobrix_tpu.testing.generators import EXP3_COPYBOOK, generate_exp3

    params = ReaderParameters(
        is_record_sequence=True,
        multisegment=MultisegmentParameters(
            segment_id_field="SEGMENT-ID",
            segment_id_redefine_map={"C": "STATIC_DETAILS",
                                     "P": "CONTACTS"}))
    reader = VarLenReader(EXP3_COPYBOOK, params)
    agg = DeviceAggregator(reader.copybook, columns=["NUM1", "NUM2"],
                           active_segment="STATIC_DETAILS")

    est_per_record = 16072 * 0.33 + 68 * 0.67
    n_records = max(64, int(mb_target * 1024 * 1024 / est_per_record))
    raw = generate_exp3(n_records, seed=100)
    buf = np.frombuffer(raw, dtype=np.uint8)
    total_mb = buf.nbytes / (1024 * 1024)
    # static wide-record bound: wide records dominate the bytes
    cap = -(-int(buf.nbytes / 16072 * 1.25 + 8) // 256) * 256
    cols = agg.gather_index  # byte projection when the query is sparse
    fn = build_wide_pipeline(agg.record_extent, cap=cap, columns=cols)

    t0 = time.perf_counter()
    x = jax.device_put(buf)
    jax.device_get(x[:1])  # force transfer completion
    h2d_s = time.perf_counter() - t0

    # warmup: compile the framing pipeline + the aggregate program (the
    # device count scalar flows into submit unsynced — zero host round
    # trips between framing and aggregate)
    t0 = time.perf_counter()
    packed, count = fn(x)
    agg.fetch(agg.submit(packed, count))
    _log(f"device pipeline warmup (incl. compile): "
         f"{time.perf_counter() - t0:.1f}s; cap={cap}")

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        packed, count = fn(x)
        res = agg.fetch(agg.submit(packed, count))
        times.append(time.perf_counter() - t0)
    compute_s = min(times)
    result = {
        "metric": f"exp3_onhbm_pipeline_{agg.decoder.backend}",
        "platform": platform,
        "backend": agg.decoder.backend,
        "fused": agg.decoder.backend == "pallas",
        "total_MB": round(total_mb, 1),
        "h2d_MBps": round(total_mb / h2d_s, 1),
        "device_pipeline_MBps": round(total_mb / compute_s, 1),
        "end_to_end_MBps": round(total_mb / (h2d_s + compute_s), 1),
        "wide_records": int(res["NUM1"]["count"] / 2000),
        "num1_sum": res["NUM1"]["sum"],
    }
    _log(f"device on-HBM pipeline: {result}")
    return result


def run_exp1_device_stats(mb_target: float, platform: str) -> dict:
    """Fused device compute on the heterogeneous exp1 profile (195 fields,
    irregular offsets): decode + per-codec validity reduction entirely on
    device, timed on a device-resident batch so the number is the chip's
    decode throughput, not the tunnel's (the judge's ask: beat the 925
    MB/s host-numpy path on-chip)."""
    import jax

    from cobrix_tpu import parse_copybook
    from cobrix_tpu.parallel import ShardedColumnarDecoder
    from cobrix_tpu.testing.generators import EXP1_COPYBOOK, generate_exp1

    cb = parse_copybook(EXP1_COPYBOOK)
    dec = ShardedColumnarDecoder(cb)  # backend auto: pallas on TPU
    n_records = max(256, int(mb_target * 1024 * 1024) // 1493)
    data = generate_exp1(n_records, seed=100)
    mb = data.nbytes / (1024 * 1024)

    t0 = time.perf_counter()
    dec.decode_stats(data)  # compiles; includes the H2D
    _log(f"exp1 device stats warmup (incl. compile): "
         f"{time.perf_counter() - t0:.1f}s; backend={dec.backend}")

    x, n = dec.put(data)  # device-resident: time the chip, not the link
    jax.device_get(x[:1, :1])
    times = []
    out = None
    for _ in range(3):
        t0 = time.perf_counter()
        out = dec.decode_stats(x, n)
        times.append(time.perf_counter() - t0)
    result = {
        "metric": f"exp1_device_stats_{dec.backend}",
        "platform": platform,
        "backend": dec.backend,
        "fused": dec.backend == "pallas",
        "total_MB": round(mb, 1),
        "device_compute_MBps": round(mb / min(times), 1),
        "records_per_s": int(n / min(times)),
        "valid_values": int(out["valid_values"]),
    }
    _log(f"exp1 device stats: {result}")
    return result


def run(backend: str, mb_target: float) -> dict:
    from cobrix_tpu.reader.parameters import (
        MultisegmentParameters,
        ReaderParameters,
    )
    from cobrix_tpu.reader.var_len_reader import VarLenReader
    from cobrix_tpu.testing.generators import EXP3_COPYBOOK, generate_exp3

    # same reader configuration as the reference exp3 run (SparkCobolApp
    # with redefine-segment-id-map): the copybook is parsed with
    # STATIC-DETAILS / CONTACTS marked as segment redefines
    params = ReaderParameters(
        is_record_sequence=True,
        multisegment=MultisegmentParameters(
            segment_id_field="SEGMENT-ID",
            segment_id_redefine_map={"C": "STATIC_DETAILS", "P": "CONTACTS"}))
    reader = VarLenReader(EXP3_COPYBOOK, params)

    # ~1/3 of records are 16 KB 'C' segments, the rest 64-byte contacts
    est_per_record = 16072 * 0.33 + 68 * 0.67
    n_records = max(64, int(mb_target * 1024 * 1024 / est_per_record))
    t0 = time.perf_counter()
    raw = generate_exp3(n_records, seed=100)
    _log(f"generated {len(raw) / 1e6:.1f} MB, {n_records} records "
         f"in {time.perf_counter() - t0:.1f}s")

    from cobrix_tpu import native

    total_mb = len(raw) / (1024 * 1024)
    _log(f"native framing: {native.available()}")

    def decode_all():
        # native RDW scan (VRLRecordReader loop in C++) + in-place decode
        # of numeric groups from the file image (decode_raw skips the
        # wide-record pack copy; only the narrow string prefix is packed)
        offsets, lengths = native.rdw_scan(raw, big_endian=False)
        out = []
        for seg_len in np.unique(lengths):
            # segment discrimination by record length (C records carry the
            # 2000-element strategy block; P contacts are 60 bytes)
            pos = np.nonzero(lengths == seg_len)[0]
            active = "CONTACTS" if seg_len < 1000 else "STATIC_DETAILS"
            dec = reader._decoder_for_segment(active, backend)
            d = dec.decode_raw(raw, offsets[pos], lengths[pos])
            # decode_raw DEFERS numeric and string groups as lazy
            # markers (the Arrow path emits them straight into Arrow
            # buffers); a decode-only number must force every plane to
            # actually materialize or it times pointer shuffling and
            # the e2e/decode-only ratio denominator is fiction
            d.materialize_numeric_all()
            for col, col_out in list(d._out.items()):
                if "lazy_string" in col_out:
                    d.column_arrays(col)
            out.append(d)
        return out

    # warmup (jit compile; excluded from timing)
    t0 = time.perf_counter()
    decode_all()
    _log(f"warmup (incl. compile): {time.perf_counter() - t0:.1f}s")

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        decoded = decode_all()
        times.append(time.perf_counter() - t0)
    best = min(times)
    n_rows = sum(d.n_records for d in decoded)
    mbps = total_mb / best
    _log(f"runs: {[f'{t:.2f}s' for t in times]}; {n_rows} records; "
         f"{mbps:.1f} MB/s; {n_rows / best:.0f} rec/s")
    return {
        "metric": f"exp3_multiseg_wide_decode_{backend}",
        "value": round(mbps, 2),
        "unit": "MB/s",
        "vs_baseline": round(mbps / BASELINE_MBPS, 2),
        "roofline": _roofline_field(mbps),
    }


def _assert_native_assembly_parity(kw: dict) -> bool:
    """In-run guard for the fused native assembly: a small exp3 sample
    read with native dispatch ON must be byte-identical to the
    pure-Python fallback. The diff itself is tools/asmcheck.py's
    check_profile (rows + tables + schema metadata + diagnostics
    ledgers) — ONE harness for bench, tests, and the smoke tool, so
    they cannot drift apart. A wrong-bytes fast path would RAISE the
    throughput numbers, so a mismatch must fail the bench, never ride
    along as data. Returns True when the native path was actually
    exercised (False = no .so, the numbers are pure-Python and the
    parity claim is vacuous)."""
    from cobrix_tpu import native
    from cobrix_tpu.testing.generators import generate_exp3

    if not native.available():
        return False
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import asmcheck

    asmcheck.check_profile("bench_exp3_parity",
                           generate_exp3(256, seed=100), kw)
    return True


def run_exp3_to_arrow(mb_target: float) -> dict:
    """exp3 multiseg-wide END-TO-END: file -> RDW framing -> segment
    split -> decode -> Arrow table, the same span the reference's
    8.0 MB/s covers (its job wrote Parquet columns, not raw decodes).
    Best of pipelined and sequential, like exp1/exp2. Native-vs-Python
    assembly parity is asserted in-run BEFORE any number is emitted."""
    import tempfile

    from cobrix_tpu.testing.generators import EXP3_COPYBOOK, generate_exp3

    est_per_record = 16072 * 0.33 + 68 * 0.67
    n_records = max(64, int(mb_target * 1024 * 1024 / est_per_record))
    raw = generate_exp3(n_records, seed=100)
    mb = len(raw) / (1024 * 1024)
    kw = dict(copybook_contents=EXP3_COPYBOOK, is_record_sequence="true",
              segment_field="SEGMENT-ID",
              redefine_segment_id_map="STATIC-DETAILS => C",
              redefine_segment_id_map_1="CONTACTS => P")
    # wrong bytes must fail the bench here, not pass it faster
    native_exercised = _assert_native_assembly_parity(kw)
    path = None
    try:
        with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
            f.write(raw)
            path = f.name
        # either variant alone carries the metric: one failing must not
        # drop the honest headline back to the decode-only comparison
        seq_best = pipe_best = None
        table = None
        try:
            seq_best, table, _ = _best_to_arrow(path, kw)
        except Exception as exc:
            _log(f"exp3 sequential to_arrow failed: {exc}")
        try:
            pipe_best, table, _ = _best_to_arrow(
                path, dict(kw, **_pipeline_kw()))
        except Exception as exc:
            _log(f"exp3 pipelined to_arrow failed: {exc}")
        top = _top_fields_profile(path, kw)
    finally:
        if path:
            os.unlink(path)
    if table is None:
        raise RuntimeError("both exp3 to_arrow variants failed")
    best = min(t for t in (seq_best, pipe_best) if t)
    mbps = mb / best
    result = {
        "metric": "exp3_multiseg_wide_to_arrow",
        "value": round(mbps, 2),
        "unit": "MB/s",
        "vs_baseline": round(mbps / BASELINE_MBPS, 2),
        "rows_per_s": int(table.num_rows / best),
        "pipelined_MBps": (round(mb / pipe_best, 1) if pipe_best else None),
        "sequential_MBps": (round(mb / seq_best, 1) if seq_best else None),
        "native_assembly": native_exercised,
        "roofline": _roofline_field(mbps),
        "top_fields": top,
    }
    _log(f"exp3 end-to-end to_arrow: {result}")
    return result


def run_exp_pushdown(mb_target: float) -> dict:
    """Query-pushdown end-to-end: the exp3 wide copybook read with
    `select` of 3 columns and a ~1%-selective COMPANY-ID filter,
    against the full decode of the same input. The value is the
    pushed-down read's effective MB/s (input bytes over wall time);
    `speedup` is the claim tools/benchgate.py gates (>= 3x, ISSUE 13
    acceptance): plan pruning must make the untouched columns actually
    free, and the pre-decode drop must keep pruned records away from
    the wide decode. Parity is asserted in-run: the pushed-down table
    must equal post-hoc filter+null-projection of the full table."""
    import tempfile

    import pyarrow.compute as pc

    from cobrix_tpu import read_cobol
    from cobrix_tpu.testing.generators import EXP3_COPYBOOK, generate_exp3

    est_per_record = 16072 * 0.33 + 68 * 0.67
    n_records = max(256, int(mb_target * 1024 * 1024 / est_per_record))
    raw = generate_exp3(n_records, seed=100)
    mb = len(raw) / (1024 * 1024)
    kw = dict(copybook_contents=EXP3_COPYBOOK, is_record_sequence="true",
              segment_field="SEGMENT-ID",
              schema_retention_policy="collapse_root",
              redefine_segment_id_map="STATIC-DETAILS => C",
              redefine_segment_id_map_1="CONTACTS => P")

    def best_of(read_kw):
        """Best of sequential and pipelined, like run_exp3_to_arrow —
        a heavily-pruned scan finishes under the pipeline's scheduling
        tick, so sequential often wins it while pipelined wins the
        full decode."""
        best = None
        for variant in (read_kw, dict(read_kw, **_pipeline_kw())):
            try:
                t, table, metrics = _best_to_arrow(path, variant)
            except Exception as exc:
                _log(f"exp_pushdown variant failed: {exc}")
                continue
            if best is None or t < best[0]:
                best = (t, table, metrics)
        if best is None:
            raise RuntimeError("every exp_pushdown variant failed")
        return best

    path = None
    try:
        with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
            f.write(raw)
            path = f.name
        full_best, full_table, _ = best_of(kw)
        # a ~1%-selective predicate from the data itself: enough
        # distinct COMPANY-IDs to cover ~1% of records
        ids = full_table["COMPANY_ID"].to_pylist()
        import collections

        counts = collections.Counter(i for i in ids if i)
        target = max(1, len(ids) // 100)
        chosen, covered = [], 0
        for value, cnt in counts.most_common():
            if covered >= target:
                break
            chosen.append(value)
            covered += cnt
        filt = "COMPANY_ID in (%s)" % ", ".join(
            "'%s'" % v for v in chosen)
        select = "SEGMENT-ID,COMPANY-ID,COMPANY-NAME"
        push_kw = dict(kw, select=select, filter=filt)
        push_best, push_table, push_metrics = best_of(push_kw)
        # parity: pushed-down == post-hoc filter of the full table on
        # the selected columns, byte-identical
        mask = pc.fill_null(pc.is_in(
            full_table["COMPANY_ID"],
            value_set=__import__("pyarrow").array(chosen)), False)
        expect = full_table.filter(mask)
        sel_cols = ["SEGMENT_ID", "COMPANY_ID"]
        name_of = (lambda t: pc.struct_field(
            t["STATIC_DETAILS"], "COMPANY_NAME").combine_chunks())
        parity = (push_table.num_rows == expect.num_rows
                  and push_table.select(sel_cols).equals(
                      expect.select(sel_cols))
                  and name_of(push_table).equals(name_of(expect)))
        if not parity:
            # a wrong-rows pushdown would otherwise RAISE the speedup
            # (fewer rows decoded) and sail through the gate — parity
            # failure must fail the experiment, not ride along as data
            raise RuntimeError(
                f"exp_pushdown parity violation: pushed-down "
                f"{push_table.num_rows} rows vs post-hoc "
                f"{expect.num_rows}")
    finally:
        if path:
            os.unlink(path)
    full_mbps = mb / full_best
    push_mbps = mb / push_best
    pushdown = push_metrics.get("pushdown") or {}
    result = {
        "metric": "exp_pushdown_to_arrow",
        "value": round(push_mbps, 2),
        "unit": "MB/s",
        "full_MBps": round(full_mbps, 2),
        "speedup": round(push_mbps / full_mbps, 2),
        "rows_pruned": pushdown.get("records_pruned"),
        "bytes_skipped": pushdown.get("bytes_skipped"),
        "selectivity": pushdown.get("selectivity"),
        "parity": bool(parity),
        "roofline": _roofline_field(push_mbps),
    }
    _log(f"exp_pushdown: {result}")
    return result


def run_exp_stats(mb_target: float) -> dict:
    """Statistics chunk-skipping end-to-end: a key-sorted fixed-length
    input (disjoint per-chunk zone maps) profiled once with
    `collect_stats`, then a ~1-chunk-selective equality scan measured
    warm with `use_stats` against the SAME scan answered by PR 13's
    record-level pushdown alone. The value is the warm skipped scan's
    effective MB/s (input bytes over wall time); `speedup_vs_pushdown`
    is the claim tools/benchgate.py gates (>= 2x, ISSUE 19
    acceptance): dropping proven-no-match chunks BEFORE framing must
    beat framing + stage-1-deciding every record. Parity is asserted
    in-run (stats table == pushdown table, byte-identical), and the
    aggregate path is timed beside its decode ground truth."""
    import tempfile

    from cobrix_tpu import read_cobol
    from cobrix_tpu.query import dataset
    from cobrix_tpu.stats.aggregate import parse_specs

    copybook = """
       01  REC.
           05  KEY-ID    PIC 9(8).
           05  NAME      PIC X(8).
    """
    n = max(4096, int(mb_target * 1024 * 1024) // 16)
    raw = bytearray()
    for i in range(n):
        raw += bytes(0xF0 + int(d) for d in f"{i:08d}")
        raw += bytes((0xC1 + i % 3,)) * 8
    mb = len(raw) / (1024 * 1024)
    kw = dict(copybook_contents=copybook)
    flt = f"KEY_ID == {n // 2}"
    path = cache = None
    try:
        with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
            f.write(bytes(raw))
            path = f.name
        cache = tempfile.mkdtemp(prefix="bench_stats_")
        t0 = time.perf_counter()
        read_cobol(path, cache_dir=cache, collect_stats="true",
                   stats_chunk_mb="0.25", **kw)
        profile_build_s = time.perf_counter() - t0
        push_best, push_table, _ = _best_to_arrow(
            path, dict(kw, filter=flt))
        warm_kw = dict(kw, filter=flt, cache_dir=cache,
                       use_stats="true", stats_chunk_mb="0.25")
        warm_best, warm_table, warm_metrics = _best_to_arrow(
            path, warm_kw)
        if not warm_table.equals(push_table):
            # a wrong skip would RAISE the speedup (fewer chunks read)
            # and sail through the gate — parity failure must fail the
            # experiment, not ride along as data
            raise RuntimeError(
                f"exp_stats parity violation: skipped scan "
                f"{warm_table.num_rows} rows vs pushdown "
                f"{push_table.num_rows}")
        aggs = ["count", "min:KEY_ID", "max:KEY_ID", "sum:KEY_ID"]
        ds = dataset(path, cache_dir=cache, use_stats="true", **kw)
        t0 = time.perf_counter()
        fast = ds._aggregate_from_stats(parse_specs(aggs))
        agg_stats_ms = (time.perf_counter() - t0) * 1000
        t0 = time.perf_counter()
        plain = dataset(path, **kw).aggregate(aggs)
        agg_decode_ms = (time.perf_counter() - t0) * 1000
        if fast is None or fast != plain:
            raise RuntimeError(
                f"exp_stats aggregate divergence: {fast} != {plain}")
    finally:
        if path:
            os.unlink(path)
        if cache:
            import shutil

            shutil.rmtree(cache, ignore_errors=True)
    push_mbps = mb / push_best
    warm_mbps = mb / warm_best
    pushdown = warm_metrics.get("pushdown") or {}
    result = {
        "metric": "exp_stats_to_arrow",
        "value": round(warm_mbps, 2),
        "unit": "MB/s",
        "pushdown_MBps": round(push_mbps, 2),
        "speedup_vs_pushdown": round(warm_mbps / push_mbps, 2),
        "profile_build_s": round(profile_build_s, 3),
        "chunks_skipped": pushdown.get("chunks_skipped"),
        "chunks_considered": pushdown.get("chunks_considered"),
        "aggregate_from_stats_ms": round(agg_stats_ms, 2),
        "aggregate_decode_ms": round(agg_decode_ms, 2),
        "parity": True,
        "roofline": _roofline_field(warm_mbps),
    }
    _log(f"exp_stats: {result}")
    return result


def _headline(decode_only: dict, e2e: dict) -> dict:
    """Merge the two exp3 measurements into the emitted headline: the
    honest end-to-end number carries `value`/`vs_baseline`; the
    kernel-only number rides along as `decode_only`, and their ratio is
    emitted as `e2e_vs_decode_only` — the assembly-overhead metric
    tools/benchgate.py gates against an absolute floor (ROADMAP item 1:
    end-to-end trending toward decode-only). A failed e2e run falls
    back to the decode headline with the error recorded (and NO ratio,
    which the gate treats as a floor failure, not a free pass)."""
    if "value" not in e2e:
        out = dict(decode_only)
        out["to_arrow"] = e2e  # the error record — never silently lost
        return out
    out = dict(e2e)
    out["decode_only"] = decode_only
    dv = decode_only.get("value")
    if isinstance(dv, (int, float)) and dv > 0:
        out["e2e_vs_decode_only"] = round(e2e["value"] / dv, 4)
    # the HEADLINE line: the roofline fraction leads (the claim that
    # survives machine swaps — arxiv 2606.22423's throughput-law view),
    # the absolute MB/s and the assembly-overhead ratio follow
    roof = e2e.get("roofline") or {}
    frac = roof.get("fraction")
    _log("HEADLINE exp3 e2e: "
         + (f"{frac:.1%} of calibrated memory bandwidth "
            f"({roof.get('calibrated_GBps')} GB/s), "
            if frac is not None else "roofline uncalibrated, ")
         + f"{e2e['value']} MB/s, e2e/decode-only "
         + f"{out.get('e2e_vs_decode_only', 'n/a')}")
    return out


def _pipeline_kw() -> dict:
    """Pipeline knobs for the bench: auto worker count, chunks sized so
    the default 40MB inputs split ~10 ways (overridable via env)."""
    return dict(
        pipeline_workers=os.environ.get("BENCH_PIPELINE_WORKERS", "-1"),
        chunk_size_mb=os.environ.get("BENCH_CHUNK_MB", "8"))


def _best_to_arrow(path: str, kw: dict, runs: int = 3):
    """(best seconds, table, metrics dict) over `runs` timed reads."""
    from cobrix_tpu import read_cobol

    read_cobol(path, **kw).to_arrow()  # warmup
    times = []
    out = None
    for _ in range(runs):
        t0 = time.perf_counter()
        out = read_cobol(path, **kw)
        table = out.to_arrow()
        times.append(time.perf_counter() - t0)
    return min(times), table, out.metrics.as_dict()


def run_exp1_side_metric(mb_target: float) -> dict:
    """exp1 fixed-length type-variety profile (195 fields / 1,493 B per
    record, data/test6_copybook.cob layout): the string/DISPLAY-heaviest
    baseline workload. Reference single-core: ~6.3 MB/s
    (performance/exp1_raw_records.csv). Timed end-to-end like the
    reference job: file -> record matrix -> kernels -> Arrow columns
    (decode alone would under-count now that string transcode is lazy).

    Headline value is the BEST of the pipelined and sequential
    configurations (both reported separately; `pipeline_on_vs_off`
    attributes the difference honestly — on few-core machines the
    pipeline's thread overhead can lose to the sequential OpenMP
    kernels), plus the per-stage busy breakdown so a pipeline win or
    regression is attributable (read/frame/decode/assemble + overlap)."""
    import tempfile

    from cobrix_tpu.testing.generators import EXP1_COPYBOOK, generate_exp1

    baseline = 6.3
    n_records = max(64, int(mb_target * 1024 * 1024) // 1493)
    t0 = time.perf_counter()
    data = generate_exp1(n_records, seed=100)
    mb = data.nbytes / (1024 * 1024)
    _log(f"exp1: generated {mb:.1f} MB, {n_records} records "
         f"in {time.perf_counter() - t0:.1f}s")
    path = None
    try:
        with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
            f.write(data.tobytes())
            path = f.name
        kw = dict(copybook_contents=EXP1_COPYBOOK)
        seq_best, _, _ = _best_to_arrow(path, kw)
        pipe_best, table, pipe_metrics = _best_to_arrow(
            path, dict(kw, **_pipeline_kw()))
        top = _top_fields_profile(path, dict(kw, **_pipeline_kw()))
    finally:
        if path:
            os.unlink(path)
    best = min(pipe_best, seq_best)  # headline: the faster configuration
    result = {
        "metric": "exp1_fixed_length_to_arrow",
        "value": round(mb / best, 1),
        "unit": "MB/s",
        "vs_baseline": round(mb / best / baseline, 1),
        "records_per_s": int(table.num_rows / best),
        "pipelined_MBps": round(mb / pipe_best, 1),
        "sequential_MBps": round(mb / seq_best, 1),
        "pipeline_on_vs_off": round(seq_best / pipe_best, 2),
        "roofline": _roofline_field(mb / best),
        "top_fields": top,
        # the read's FULL structured metrics (timings, stage busy,
        # pipeline overlap, plan_cache) so the perf trajectory carries
        # attributable stage breakdowns, not just headline MB/s
        "read_metrics": pipe_metrics,
    }
    _log(f"side metric exp1_fixed_length: {result}")
    return result


def run_exp2_side_metric(mb_target: float) -> dict:
    """exp2 narrow-record profile (64-68 B/rec): the FULL pipeline — file
    -> RDW framing -> segment split -> decode -> Arrow table — not just
    the decode step. Uses the multi-host (process) scan when the machine
    has cores for it (parallel/hosts.py; cpu_count=1 runs single-process).
    Reference exp2 single-core baseline: ~9.4 MB/s (BASELINE.md)."""
    import tempfile

    from cobrix_tpu import read_cobol
    from cobrix_tpu.testing.generators import EXP2_COPYBOOK, generate_exp2

    baseline = 9.4
    n_records = max(1000, int(mb_target * 1024 * 1024 / 66))
    raw = generate_exp2(n_records, seed=100)
    mb = len(raw) / (1024 * 1024)
    cores = os.cpu_count() or 1
    kw = dict(copybook_contents=EXP2_COPYBOOK, is_record_sequence="true",
              segment_field="SEGMENT-ID",
              redefine_segment_id_map="STATIC-DETAILS => C",
              redefine_segment_id_map_1="CONTACTS => P",
              segment_id_prefix="BENCH")
    if cores > 1:
        kw["hosts"] = str(min(cores, 16))
        kw["input_split_size_mb"] = str(
            max(4, int(mb / (2 * min(cores, 16)))))
    path = None
    try:
        with tempfile.NamedTemporaryFile(suffix=".dat",
                                         delete=False) as f:
            f.write(raw)
            path = f.name
        def best_of_3(options):
            read_cobol(path, **options).to_arrow()  # warmup
            times = []
            for _ in range(3):
                t0 = time.perf_counter()
                tbl = read_cobol(path, **options).to_arrow()
                times.append(time.perf_counter() - t0)
            return min(times), tbl

        best, table = best_of_3(kw)
        # the reference's exp2 app also generates Seg_Id0/Seg_Id1
        # (SparkCobolApp); measure that configuration too — its failure
        # must not discard the base metric
        with_ids = None
        try:
            with_ids, _ = best_of_3(
                dict(kw, segment_id_level0="C", segment_id_level1="P"))
        except Exception as exc:
            _log(f"exp2 seg-id variant failed: {exc}")
        # pipeline on/off, single-process (hosts stripped): attributes the
        # thread-pipeline win separately from the process executor's
        pipe_on = pipe_off = None
        pipe_metrics = None
        base_kw = {k: v for k, v in kw.items()
                   if k not in ("hosts", "input_split_size_mb")}
        try:
            pipe_off, _ = best_of_3(base_kw)
            pipe_on, _, pipe_metrics = _best_to_arrow(
                path, dict(base_kw, **_pipeline_kw()))
        except Exception as exc:
            _log(f"exp2 pipeline variant failed: {exc}")
        top = _top_fields_profile(path, base_kw)
    finally:
        if path:
            os.unlink(path)
    result = {
        "metric": "exp2_multiseg_narrow_to_arrow",
        "value": round(mb / best, 1),
        "unit": "MB/s",
        "vs_baseline": round(mb / best / baseline, 1),
        "roofline": _roofline_field(mb / best),
        "top_fields": top,
        "with_seg_ids_MBps": (round(mb / with_ids, 1)
                              if with_ids else None),
        "rows_per_s": int(table.num_rows / best),
        "hosts": int(kw.get("hosts", 1)),
        "pipelined_MBps": (round(mb / pipe_on, 1) if pipe_on else None),
        "sequential_MBps": (round(mb / pipe_off, 1) if pipe_off else None),
        "pipeline_on_vs_off": (round(pipe_off / pipe_on, 2)
                               if pipe_on and pipe_off else None),
        "read_metrics": pipe_metrics,
    }
    _log(f"side metric exp2_multiseg_narrow: {result} "
         f"(baseline {baseline} MB/s)")
    return result


def _device_metrics(mb_target: float, platform: str) -> dict:
    """Every device-path measurement, each individually guarded: the
    query (decode+aggregate, blocks streamed over the link), the on-HBM
    framing pipeline, and the exp1 fused device-stats compute number."""
    out = {}
    dev_mb = min(mb_target, float(os.environ.get("BENCH_DEVICE_MB", "64")))
    try:
        out["device_query"] = run_device_query(dev_mb, platform)
    except Exception as exc:  # record, never mask the headline
        _log(f"device query failed: {exc}")
        out["device_query"] = {"metric": "exp3_device_aggregate",
                               "platform": platform,
                               "error": str(exc)[:400]}
    try:
        out["device_pipeline"] = run_device_pipeline(
            min(dev_mb, 32.0), platform)
    except Exception as exc:
        _log(f"device pipeline failed: {exc}")
        out["device_pipeline"] = {"metric": "exp3_onhbm_pipeline",
                                  "platform": platform,
                                  "error": str(exc)[:400]}
    try:
        out["exp1_device_stats"] = run_exp1_device_stats(
            min(dev_mb, 16.0), platform)
    except Exception as exc:
        _log(f"exp1 device stats failed: {exc}")
        out["exp1_device_stats"] = {"metric": "exp1_device_stats",
                                    "platform": platform,
                                    "error": str(exc)[:400]}
    return out


def main():
    mb_target = float(os.environ.get("BENCH_MB", "64"))
    backend = os.environ.get("BENCH_BACKEND", "")
    # anchor every experiment against the machine's memory bandwidth
    # (one-time; cached across rounds) BEFORE any timing runs
    _calibrate_roofline()
    if os.environ.get("BENCH_FORCE_CPU"):
        # validation mode: run the jax paths on host CPU (honestly labeled)
        import jax

        jax.config.update("jax_platforms", "cpu")

    # with an explicit backend the operator wants the number NOW — use
    # a shorter hard deadline (the cache usually answers instantly)
    platform, probe = _probe_jax(
        deadline_s=(20 if backend else None))
    device_status = platform if platform else "unavailable"
    if not platform:
        _log(f"WARNING: jax unavailable: {probe['error']}")

    # the device-resident measurements — the metrics that must exist even
    # when the full-decode headline favors the host kernels (the decoded
    # columns never cross the link; scalars do)
    device = _device_metrics(mb_target, platform) if platform else {}

    result = None
    if not backend:
        # calibrate: time both backends on a small slice and run the full
        # benchmark on the faster one. On hosts with a locally-attached TPU
        # the jax path wins; over a remote/tunneled device the transfer
        # link caps it and the native host kernels win.
        candidates = ["numpy"] + (["jax"] if platform else [])
        if len(candidates) == 1:
            backend = candidates[0]
        else:
            cal_mb = min(mb_target, 16.0)
            scores, results = {}, {}
            for cand in candidates:
                try:
                    results[cand] = run(cand, cal_mb)
                    scores[cand] = results[cand]["value"]
                except Exception as exc:  # pragma: no cover
                    _log(f"calibration {cand} failed: {exc}")
                    scores[cand] = 0.0
            backend = max(scores, key=scores.get)
            _log(f"calibration: {scores}; running full bench on {backend}")
            if cal_mb == mb_target and backend in results:
                result = results[backend]
    side = _side_metrics(mb_target)
    if result is None:
        result = run(backend, mb_target)
    # the honest headline: end-to-end Arrow conversion of the same
    # workload (the decode-only number overstates vs the full-conversion
    # baseline — VERDICT flagged the comparison)
    try:
        e2e = run_exp3_to_arrow(mb_target)
    except Exception as exc:
        _log(f"exp3 to_arrow timing failed: {exc}")
        e2e = {"metric": "exp3_multiseg_wide_to_arrow",
               "error": str(exc)[:400]}
    result = _headline(result, e2e)

    if not platform:
        # the tunnel was down at bench start — re-probe now that the CPU
        # work has burned several minutes: a transient outage at probe
        # time must not forfeit the round's only chance at TPU evidence
        _log("re-probing the device at end of run")
        # fresh probe, cache bypassed: a transient outage at bench
        # start must not forfeit the round's only chance at evidence
        platform, retry_probe = _probe_jax(use_cache=False)
        if platform:
            device_status = platform
            probe = None
            device = _device_metrics(mb_target, platform)
        else:
            probe["retry"] = retry_probe
    _emit(result, device_status, probe, device, side)


def _emit(result: dict, device_status: str, probe, device: dict,
          side_metrics: dict):
    result = dict(result)
    result["device"] = device_status
    # ONE structured field for the whole probe story (attempts + errors);
    # null when the device came up
    result["jax_probe"] = probe
    result["device_query"] = device.get("device_query")
    result["device_pipeline"] = device.get("device_pipeline")
    result["exp1_device_stats"] = device.get("exp1_device_stats")
    result.update(side_metrics)
    print(json.dumps(result), flush=True)


def run_hierarchical_side_metric(mb_target: float) -> dict:
    """Hierarchical (IMS-style) 7-segment profile through the span-based
    columnar Arrow assembly (TestDataGen17Hierarchical layout). No
    reference CSV exists for this shape — reported informationally."""
    import tempfile

    from cobrix_tpu import read_cobol
    from cobrix_tpu.reader.hierarchical_arrow import assembly_stats
    from cobrix_tpu.testing import generators as g

    assembly_stats(reset=True)
    n_companies = max(50, int(mb_target * 1024 * 1024 / 1350))
    raw = g.generate_hierarchical(n_companies, seed=100)
    mb = len(raw) / (1024 * 1024)
    seg_opts = {f"redefine_segment_id_map:{i}": f"{name} => {sid}"
                for i, (sid, name) in enumerate(
                    g.HIERARCHICAL_SEGMENT_MAP.items())}
    child_opts = {f"segment-children:{i}": f"{parent} => {child}"
                  for i, (child, parent) in enumerate(
                      g.HIERARCHICAL_PARENT_MAP.items())}
    kw = dict(copybook_contents=g.HIERARCHICAL_COPYBOOK,
              is_record_sequence="true", segment_field="SEGMENT-ID",
              **seg_opts, **child_opts)
    path = None
    try:
        with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
            f.write(raw)
            path = f.name
        table = read_cobol(path, **kw).to_arrow()  # warmup
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            table = read_cobol(path, **kw).to_arrow()
            times.append(time.perf_counter() - t0)
    finally:
        if path:
            os.unlink(path)
    stats = assembly_stats(reset=True)
    result = {
        "metric": "hierarchical_7seg_to_arrow",
        "value": round(mb / min(times), 1),
        "unit": "MB/s",
        "vs_exp3_bar": round(mb / min(times) / 160.0, 2),  # 20x exp3 bar
        "roots_per_s": int(table.num_rows / min(times)),
        "roofline": _roofline_field(mb / min(times)),
        "assembly": stats,  # columnar builds vs row-path bails
    }
    _log(f"side metric hierarchical: {result}")
    return result


def run_serve_side_metric(mb_target: float) -> dict:
    """exp_serve: the streaming serving tier (cobrix_tpu.serve) vs the
    in-process read, same exp1 input. Two numbers matter: streamed
    end-to-end MB/s (decode + Arrow IPC framing + TCP loopback + client
    reassembly — the tax a serving client pays over `to_arrow()`), and
    time-to-first-batch, which must land BELOW the one-shot latency:
    that gap is the whole point of streaming delivery (a client renders
    after one chunk decodes, not after the whole table exists)."""
    import tempfile

    from cobrix_tpu.serve import ScanServer, stream_scan
    from cobrix_tpu.testing.generators import EXP1_COPYBOOK, generate_exp1

    n_records = max(64, int(mb_target * 1024 * 1024) // 1493)
    data = generate_exp1(n_records, seed=100)
    mb = data.nbytes / (1024 * 1024)
    path = None
    try:
        with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
            f.write(data.tobytes())
            path = f.name
        # both sides run the SAME pipelined config, chunked ~8 ways:
        # streaming only wins first-batch latency when the scan has
        # several chunks to deliver incrementally, and the one-shot
        # reference must not differ in anything but delivery
        kw = dict(copybook_contents=EXP1_COPYBOOK,
                  pipeline_workers=os.environ.get(
                      "BENCH_PIPELINE_WORKERS", "-1"),
                  chunk_size_mb=os.environ.get(
                      "BENCH_SERVE_CHUNK_MB", str(max(1, round(mb / 8)))))
        # in-process reference; its warmup also warms the compile caches
        # the server shares, so neither side pays the parse
        one_shot_s, table, _ = _best_to_arrow(path, kw)
        srv = ScanServer(enable_http=False).start()
        errors = []
        try:
            # rows/batches come from the best-total run so throughput
            # fields all describe ONE run; first-batch is best-of-runs
            # like every other latency in this file
            best = None  # (total, rows, batches)
            best_first = None
            for _ in range(3):
                t0 = time.perf_counter()
                first = None
                rows = batches = 0
                with stream_scan(srv.address, path, tenant="bench",
                                 **kw) as stream:
                    for batch in stream:
                        if first is None:
                            first = time.perf_counter() - t0
                        rows += batch.num_rows
                        batches += 1
                total = time.perf_counter() - t0
                if rows != table.num_rows:
                    errors.append(f"streamed {rows} rows != in-process "
                                  f"{table.num_rows}")
                if best is None or total < best[0]:
                    best = (total, rows, batches)
                if first is not None and (best_first is None
                                          or first < best_first):
                    best_first = first
        finally:
            srv.stop()
    finally:
        if path:
            os.unlink(path)
    best_total, rows, batches = best
    if best_first is None:
        best_first = best_total
    result = {
        "metric": "exp_serve_streamed_to_arrow",
        "value": round(mb / best_total, 1),
        "unit": "MB/s",
        "roofline": _roofline_field(mb / best_total),
        "rows": rows,
        "batches": batches,
        "one_shot_s": round(one_shot_s, 4),
        "stream_total_s": round(best_total, 4),
        "stream_vs_in_process": round(one_shot_s / best_total, 2),
        "first_batch_s": round(best_first, 4),
        # >1.0 = the stream's first batch beat the whole one-shot read
        # (the acceptance bar; asserted hard in tools/servecheck.py)
        "first_batch_speedup": round(one_shot_s / best_first, 2),
    }
    if best_first >= one_shot_s:
        errors.append(f"first batch at {best_first:.3f}s did NOT beat "
                      f"the {one_shot_s:.3f}s one-shot read")
    if errors:  # every failure survives into the JSON, none overwritten
        result["error"] = "; ".join(errors)
    _log(f"side metric exp_serve: {result}")
    return result


def run_serve_fleet_metric(mb_target: float) -> dict:
    """exp_serve fleet mode: aggregate routed throughput as the fleet
    scales N=1 -> 2 -> 4 replicas behind the routing front. Four files
    spread across the fleet by cache affinity (each file's scans pin to
    the replica whose caches are warm for it), so the aggregate MB/s of
    a concurrent scan mix should GROW with N while the warm-affinity
    hit rate stays high — that pair is the scaling claim PR 16's router
    exists to earn. Served from ``memory://`` so the io cache planes
    (and the peer tier's wire path) engage exactly as they would
    against object storage."""
    import shutil
    import tempfile
    import threading

    import fsspec

    from cobrix_tpu.fleet.router import RoutingFront, route_scan
    from cobrix_tpu.serve import ScanServer
    from cobrix_tpu.testing.generators import EXP1_COPYBOOK, generate_exp1

    n_files = 4
    per_file = max(64, int(mb_target * 1024 * 1024 / n_files) // 1493)
    fs = fsspec.filesystem("memory")
    paths = []
    for i in range(n_files):
        data = generate_exp1(per_file, seed=200 + i)
        with fs.open(f"/bench-fleet/f{i}.dat", "wb") as f:
            f.write(data.tobytes())
        paths.append((f"memory://bench-fleet/f{i}.dat",
                      data.nbytes / (1024 * 1024)))
    total_mb = sum(mb for _, mb in paths)
    kw = dict(copybook_contents=EXP1_COPYBOOK)
    hb_s = 0.2
    work = tempfile.mkdtemp(prefix="bench-fleet-")
    errors = []
    per_n = {}
    try:
        for n in (1, 2, 4):
            fleet_dir = os.path.join(work, f"fleet-{n}")
            servers = [
                ScanServer(
                    port=0, enable_http=False,
                    server_options={"cache_dir": os.path.join(
                        work, f"cache-{n}-{i}")},
                    fleet=True, replica_id=f"bench-{n}-{i}",
                    heartbeat_interval_s=hb_s,
                    fleet_dir=fleet_dir).start()
                for i in range(n)]
            front = RoutingFront(fleet_dir, slo_aware=False)
            try:
                deadline = time.monotonic() + 15
                while (len(front.registry.read()) < n
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
                # warm pass: every file scanned once (caches + heat)
                for path, _mb in paths:
                    route_scan(front, path, tenant="bench",
                               **kw).table()
                time.sleep(hb_s * 2)  # heat rides the next heartbeat
                base = front.state()
                threads, rows = [], []

                def one(path):
                    t = route_scan(front, path, tenant="bench",
                                   **kw).table()
                    rows.append(t.num_rows)

                for _round in range(2):
                    for path, _mb in paths:
                        threads.append(threading.Thread(
                            target=one, args=(path,)))
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=300)
                wall = time.perf_counter() - t0
                if len(rows) != len(threads) \
                        or sum(rows) != per_file * len(threads):
                    errors.append(f"N={n}: row mismatch {sum(rows)}")
                st = front.state()
                decisions = st["decisions"] - base["decisions"]
                hits = st["affinity_hits"] - base["affinity_hits"]
                per_n[str(n)] = {
                    "aggregate_MBps": round(total_mb * 2 / wall, 1),
                    "affinity_hit_rate": round(
                        hits / max(1, decisions), 2),
                    "routed": st["routed"],
                }
            finally:
                for srv in servers:
                    srv.stop()
    finally:
        shutil.rmtree(work, ignore_errors=True)
        try:
            fs.rm("/bench-fleet", recursive=True)
        except Exception:
            pass
    agg4 = per_n.get("4", {}).get("aggregate_MBps", 0.0)
    agg1 = per_n.get("1", {}).get("aggregate_MBps", 0.0)
    result = {
        "metric": "exp_serve_fleet_aggregate",
        "value": agg4,
        "unit": "MB/s",
        "scaling_4x": round(agg4 / agg1, 2) if agg1 else None,
        "warm_affinity_hit_rate": per_n.get("4", {}).get(
            "affinity_hit_rate"),
        "per_n": per_n,
    }
    if errors:
        result["error"] = "; ".join(errors)
    _log(f"side metric exp_serve fleet: {result}")
    return result


def run_roundtrip_side_metric(mb_target: float) -> dict:
    """exp_roundtrip: the write half (cobrix_tpu.encode) measured beside
    the read half it must mirror. Three numbers: encode MB/s (the
    vectorized BatchEncoder streaming a >=1M-record synthetic TXN
    corpus to disk, testing/corpus.py), decode MB/s of that same corpus
    end to end (read_cobol -> Arrow, the exp3-style e2e view of
    encoder-built data), and `roundtrip_parity` — decode->re-encode
    byte equality on a sample file, which tools/benchgate.py gates as a
    HARD failure with no history needed: fast encode of wrong bytes is
    worthless."""
    import shutil
    import tempfile

    from cobrix_tpu import read_cobol
    from cobrix_tpu.testing import corpus

    n_records = max(1_000_000, int(mb_target * 1024 * 1024) // 35)
    tmpdir = tempfile.mkdtemp(prefix="bench_rt_")
    path = os.path.join(tmpdir, "txn.dat")
    try:
        t0 = time.perf_counter()
        info = corpus.write_fixed_corpus(path, n_records, seed=100)
        encode_s = time.perf_counter() - t0
        mb = info["bytes"] / (1024 * 1024)
        times = []
        rows = 0
        for _ in range(2):
            t0 = time.perf_counter()
            table = read_cobol(path,
                               **corpus.fixed_read_options()).to_arrow()
            times.append(time.perf_counter() - t0)
            rows = table.num_rows
        # parity: a separate small corpus re-encoded byte-for-byte (the
        # record-at-a-time write path; full-corpus parity is rtcheck's
        # job, here it is a cheap in-run guard)
        sample = 20_000
        spath = os.path.join(tmpdir, "sample.dat")
        corpus.write_fixed_corpus(spath, sample, seed=100)
        with open(spath, "rb") as f:
            sample_bytes = f.read()
        out = read_cobol(spath, **corpus.fixed_read_options())
        parity = out.to_ebcdic(framing="fixed") == sample_bytes
        result = {
            "metric": "exp_roundtrip_encode",
            "value": round(mb / encode_s, 1),
            "unit": "MB/s",
            "records": rows,
            "mb": round(mb, 1),
            "decode_mbps": round(mb / min(times), 1),
            "roundtrip_parity": bool(parity),
            "parity_sample_records": sample,
        }
        _log(f"side metric exp_roundtrip: {result}")
        return result
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def run_compressed_side_metric(mb_target: float) -> dict:
    """exp_compressed: the streaming decompression plane measured end
    to end. Two gzip feeds of the SAME synthetic TXN corpus at
    different compression ratios — the corpus writer's member-per-chunk
    level-1 stream (restartable, the production shape) and a solid
    level-9 single member — decode through read_cobol with a cache_dir.
    The headline is cold member-feed e2e MB/s of DECOMPRESSED bytes;
    `warm` re-scans the cache the cold pass populated (zero inflate
    work) as its own gated metric; `compressed_parity` asserts every
    leg byte-identical to the raw file's decode, which
    tools/benchgate.py gates as a HARD failure with no history needed:
    a fast inflate of wrong bytes is worthless."""
    import gzip as _gzip
    import shutil
    import tempfile

    from cobrix_tpu import read_cobol
    from cobrix_tpu.testing import corpus

    n_records = max(50_000, int(mb_target * 1024 * 1024) // 35)
    work = tempfile.mkdtemp(prefix="bench-comp-")
    try:
        raw = os.path.join(work, "txn.dat")
        chunk = max(1, n_records // 8)
        info = corpus.write_fixed_corpus(raw, n_records, seed=55,
                                         chunk_records=chunk)
        mb = info["bytes"] / (1024 * 1024)
        kw = corpus.fixed_read_options()
        base = read_cobol(raw, **kw).to_arrow()

        def matches(t) -> bool:
            return (t.num_rows == base.num_rows
                    and all(t.column(c).equals(base.column(c))
                            for c in base.column_names
                            if "File_Name" not in c))

        members = os.path.join(work, "txn.dat.gz")
        minfo = corpus.write_fixed_corpus(members, n_records, seed=55,
                                          chunk_records=chunk,
                                          compression="gzip")
        solid = os.path.join(work, "solid", "txn.dat.gz")
        os.makedirs(os.path.dirname(solid))
        with open(raw, "rb") as f:
            solid_wire = _gzip.compress(f.read(), compresslevel=9)
        with open(solid, "wb") as f:
            f.write(solid_wire)

        def timed(path, cache):
            t0 = time.perf_counter()
            out = read_cobol(path, cache_dir=cache,
                             compress_block_mb="2", **kw)
            table = out.to_arrow()
            return (time.perf_counter() - t0, table,
                    out.metrics.as_dict()["io"])

        parity = True
        cold_s, cold_table, _ = timed(members, os.path.join(work, "c1"))
        parity &= matches(cold_table)
        warm_times, warm_io = [], {}
        for _ in range(2):
            s, t, warm_io = timed(members, os.path.join(work, "c1"))
            parity &= matches(t)
            warm_times.append(s)
        solid_s, solid_table, _ = timed(solid, os.path.join(work, "c2"))
        parity &= matches(solid_table)
        warm_s = min(warm_times)
        result = {
            "metric": "exp_compressed_e2e",
            "value": round(mb / cold_s, 1),
            "unit": "MB/s",
            "roofline": _roofline_field(mb / cold_s),
            "mb": round(mb, 1),
            "records": base.num_rows,
            "ratio": round(info["bytes"] / minfo["wire_bytes"], 2),
            "solid_cold_MBps": round(mb / solid_s, 1),
            "solid_ratio": round(info["bytes"] / len(solid_wire), 2),
            "compressed_parity": bool(parity),
            "warm": {
                "metric": "exp_compressed_warm",
                "value": round(mb / warm_s, 1),
                "unit": "MB/s",
                "zero_inflate":
                    warm_io.get("decompressed_bytes_out", 0) == 0,
                "speedup_vs_cold": round(cold_s / warm_s, 2),
            },
        }
        _log(f"side metric exp_compressed: {result}")
        return result
    finally:
        shutil.rmtree(work, ignore_errors=True)


def run_sink_side_metric(mb_target: float) -> dict:
    """exp_sink: the transactional lakehouse sink (cobrix_tpu.sink) vs
    bare streaming decode, same exp1 input tailed from a static file.
    Two numbers matter: sink end-to-end MB/s (tail + decode + Parquet
    serialization + staged write + fsync'd manifest commit + durable
    checkpoint ack per batch — the whole exactly-once protocol), and
    the overhead fraction vs a consumer that decodes the identical
    batches and throws them away: that gap is the price of the
    durability guarantee, and it should stay a modest multiple, not an
    order of magnitude."""
    import shutil
    import tempfile

    from cobrix_tpu.sink import read_dataset, sink_cobol
    from cobrix_tpu.streaming import tail_cobol
    from cobrix_tpu.testing.generators import EXP1_COPYBOOK, generate_exp1

    n_records = max(64, int(mb_target * 1024 * 1024) // 1493)
    data = generate_exp1(n_records, seed=77)
    mb = data.nbytes / (1024 * 1024)
    work = tempfile.mkdtemp(prefix="bench-sink-")
    errors = []
    try:
        path = os.path.join(work, "feed.dat")
        with open(path, "wb") as f:
            f.write(data.tobytes())
        # both sides pay exactly ONE idle_timeout_s wait by
        # construction (the tail drains the static file, then idles
        # once before finalize) — subtract that constant so MB/s
        # measures the work, not the poll clock
        idle_s = 0.2
        kw = dict(copybook_contents=EXP1_COPYBOOK,
                  poll_interval_s=0.02, idle_timeout_s=idle_s,
                  finalize_on_idle=True)

        def stream_only() -> float:
            t0 = time.perf_counter()
            rows = 0
            for batch in tail_cobol(path, **kw):
                rows += len(batch.to_arrow())
            if rows != n_records:
                errors.append(f"stream decoded {rows} rows "
                              f"!= {n_records}")
            return time.perf_counter() - t0 - idle_s

        def sink_run() -> float:
            ckpt = os.path.join(work, "ck")
            dataset = os.path.join(work, "dataset")
            for stale in (ckpt, dataset):
                shutil.rmtree(stale, ignore_errors=True)
            t0 = time.perf_counter()
            result = sink_cobol(
                tail_cobol(path, checkpoint_dir=ckpt, **kw), dataset)
            elapsed = time.perf_counter() - t0 - idle_s
            if result.records != n_records:
                errors.append(f"sink committed {result.records} rows "
                              f"!= {n_records}")
            if not read_dataset(dataset).num_rows == n_records:
                errors.append("sink read-back row count diverged")
            return elapsed

        stream_s = min(stream_only() for _ in range(2))
        sink_s = min(sink_run() for _ in range(2))
    finally:
        shutil.rmtree(work, ignore_errors=True)
    result = {
        "metric": "exp_sink_e2e",
        "value": round(mb / sink_s, 1),
        "unit": "MB/s",
        "roofline": _roofline_field(mb / sink_s),
        "rows": n_records,
        "stream_decode_MBps": round(mb / stream_s, 1),
        "sink_total_s": round(sink_s, 4),
        "stream_total_s": round(stream_s, 4),
        # >1.0 = the durable commit protocol costs this factor over
        # decode-and-discard streaming of the same batches
        "sink_overhead_x": round(sink_s / stream_s, 2),
    }
    if errors:
        result["error"] = "; ".join(errors)
    _log(f"side metric exp_sink: {result}")
    return result


def _side_metrics(mb_target: float) -> dict:
    """exp1/exp2/hierarchical/serving profiles as named JSON fields; a
    side-metric failure must never break the headline bench."""
    side = {}
    try:
        side["exp1"] = run_exp1_side_metric(min(mb_target, 40.0))
    except Exception as exc:
        _log(f"exp1 side metric failed: {exc}")
    try:
        side["exp2"] = run_exp2_side_metric(min(mb_target, 40.0))
    except Exception as exc:
        _log(f"exp2 side metric failed: {exc}")
    try:
        side["hierarchical"] = run_hierarchical_side_metric(
            min(mb_target, 16.0))
    except Exception as exc:
        _log(f"hierarchical side metric failed: {exc}")
    try:
        side["exp_serve"] = run_serve_side_metric(min(mb_target, 24.0))
    except Exception as exc:
        _log(f"exp_serve side metric failed: {exc}")
    if isinstance(side.get("exp_serve"), dict):
        try:
            side["exp_serve"]["fleet"] = run_serve_fleet_metric(
                min(mb_target, 8.0))
        except Exception as exc:
            _log(f"exp_serve fleet metric failed: {exc}")
    try:
        side["exp_sink"] = run_sink_side_metric(min(mb_target, 16.0))
    except Exception as exc:
        _log(f"exp_sink side metric failed: {exc}")
    try:
        side["exp_pushdown"] = run_exp_pushdown(min(mb_target, 40.0))
    except Exception as exc:
        _log(f"exp_pushdown side metric failed: {exc}")
        side["exp_pushdown"] = {"metric": "exp_pushdown_to_arrow",
                                "error": str(exc)[:400]}
    try:
        side["exp_stats"] = run_exp_stats(min(mb_target, 24.0))
    except Exception as exc:
        _log(f"exp_stats side metric failed: {exc}")
        side["exp_stats"] = {"metric": "exp_stats_to_arrow",
                             "error": str(exc)[:400]}
    try:
        side["exp_roundtrip"] = run_roundtrip_side_metric(
            min(mb_target, 40.0))
    except Exception as exc:
        _log(f"exp_roundtrip side metric failed: {exc}")
        side["exp_roundtrip"] = {"metric": "exp_roundtrip_encode",
                                 "error": str(exc)[:400]}
    try:
        side["exp_compressed"] = run_compressed_side_metric(
            min(mb_target, 16.0))
    except Exception as exc:
        _log(f"exp_compressed side metric failed: {exc}")
        side["exp_compressed"] = {"metric": "exp_compressed_e2e",
                                  "error": str(exc)[:400]}
    return side


if __name__ == "__main__":
    main()
