"""The autoscale actuator: `desired_replicas` becomes replica lifecycle.

PR 13's `derive_signals` deliberately stopped at a *recommendation
record* — it computes ``desired_replicas`` and actuates nothing. This
module is the opt-in other half: a supervisor that owns a set of
serving-replica **subprocesses** (spawned as ``python -m
cobrix_tpu.serve --fleet ...``, the same entry point an operator runs)
and reconciles the running count toward the recommendation.

Strictly bounded authority — the actuator will only ever touch
processes IT spawned:

* it never signals, drains, or counts replicas an operator started by
  hand, even when they register in the same fleet directory (they
  contribute to the *desired* math via the registry, but scale-down
  only ever picks from the actuator's own children)
* scale-down is graceful: SIGTERM, which the serve entry point maps to
  `drain()` (PR 8 semantics — stop accepting, finish in-flight scans,
  flush audit) with a bounded grace before SIGKILL
* `stop()` tears down every child the same way; the zero-orphan
  guarantee is `stop()` returning with every child's exit code reaped.

Stability machinery, because raw `desired_replicas` oscillates:

* **hysteresis** — a new desired value must persist for ``hold_beats``
  consecutive polls before the actuator acts on it
* **flap damping** — at least ``flap_damp_s`` between scale events,
  and at most ONE replica added or removed per event
* **crash restart with backoff** — a child that exits uninvited is
  respawned immediately the first time (a crashed replica must be back
  inside two heartbeat intervals), then with exponential backoff
  (``backoff_base_s`` doubling to ``backoff_max_s``) while it keeps
  crashing; a child that stayed up long enough resets its backoff.

Every decision is appended to ``<fleet_dir>/actuator/events.jsonl``
and the current world to ``state.json`` (CRC-stamped) — that is what
`tools/fleetview.py` renders next to the replica table.
"""
from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from .registry import ReplicaRegistry

_ADDR = re.compile(r"serving scans on \('([^']+)', (\d+)\), "
                   r"obs on \('([^']+)', (\d+)\)")

# a child that survived this many heartbeat intervals earns its backoff
# reset — the crash loop is over
STABLE_BEATS = 10.0


class _Child:
    """One actuator-owned replica subprocess."""

    def __init__(self, slot: int, replica_id: str,
                 proc: subprocess.Popen):
        self.slot = slot
        self.replica_id = replica_id
        self.proc = proc
        self.started_at = time.monotonic()
        self.scan_address: Optional[tuple] = None
        self.http_address: Optional[tuple] = None
        self.restarts = 0
        self.backoff_s = 0.0       # next respawn delay if it crashes
        self.respawn_at = 0.0      # monotonic; 0 = not pending
        self.stopping = False      # we sent SIGTERM on purpose
        self.stop_deadline = 0.0
        self._reader = threading.Thread(
            target=self._drain_stdout, name=f"cobrix-actuator-{slot}",
            daemon=True)
        self._reader.start()

    def _drain_stdout(self) -> None:
        # parse the serve banner for addresses, then keep draining so
        # the child never blocks on a full pipe
        try:
            for line in self.proc.stdout:
                m = _ADDR.search(line)
                if m:
                    self.scan_address = (m.group(1), int(m.group(2)))
                    self.http_address = (m.group(3), int(m.group(4)))
        except (OSError, ValueError):
            pass


class FleetActuator:
    """Reconcile running actuator-owned replicas toward a desired
    count. `start()` runs the loop in a daemon thread; `step()` is one
    reconciliation pass (tests drive it directly for determinism)."""

    def __init__(self, cache_dir: str,
                 fleet_dir: str = "",
                 min_replicas: int = 1,
                 max_replicas: int = 4,
                 poll_interval_s: float = 0.5,
                 hold_beats: int = 3,
                 flap_damp_s: float = 10.0,
                 backoff_base_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 heartbeat_interval_s: float = 2.0,
                 drain_grace_s: float = 20.0,
                 replica_prefix: str = "auto-",
                 host: str = "127.0.0.1",
                 server_args: Optional[List[str]] = None,
                 desired_fn: Optional[Callable[[], int]] = None,
                 env: Optional[dict] = None):
        self.cache_dir = cache_dir
        self.fleet_dir = fleet_dir or os.path.join(cache_dir, "fleet")
        self.registry = ReplicaRegistry(self.fleet_dir,
                                        interval_s=heartbeat_interval_s)
        self.min_replicas = max(0, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.poll_interval_s = max(0.05, float(poll_interval_s))
        self.hold_beats = max(1, int(hold_beats))
        self.flap_damp_s = max(0.0, float(flap_damp_s))
        self.backoff_base_s = max(0.05, float(backoff_base_s))
        self.backoff_max_s = max(self.backoff_base_s,
                                 float(backoff_max_s))
        self.heartbeat_interval_s = max(0.05,
                                        float(heartbeat_interval_s))
        self.drain_grace_s = max(0.0, float(drain_grace_s))
        self.replica_prefix = replica_prefix
        self.host = host
        self.server_args = list(server_args or [])
        self.desired_fn = desired_fn
        self.env = env
        self._children: Dict[int, _Child] = {}
        self._next_slot = 0
        self._desired_seen: Optional[int] = None
        self._desired_streak = 0
        self._last_scale_at = 0.0
        self._federator = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(os.path.join(self.fleet_dir, "actuator"),
                    exist_ok=True)

    # -- spawning ---------------------------------------------------------

    def _spawn_cmd(self, replica_id: str) -> List[str]:
        cmd = [sys.executable, "-m", "cobrix_tpu.serve",
               "--host", self.host, "--port", "0", "--http-port", "0",
               "--cache-dir", self.cache_dir, "--fleet",
               "--replica-id", replica_id,
               "--heartbeat-interval", str(self.heartbeat_interval_s),
               "--drain-timeout", str(self.drain_grace_s)]
        if self.fleet_dir:
            cmd += ["--fleet-dir", self.fleet_dir]
        return cmd + self.server_args

    def _spawn(self, slot: int, restarts: int = 0,
               backoff_s: float = 0.0) -> _Child:
        replica_id = f"{self.replica_prefix}{slot}"
        env = dict(self.env if self.env is not None else os.environ)
        # the child must import this package from the same tree
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep
                             + env.get("PYTHONPATH", ""))
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            self._spawn_cmd(replica_id), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env)
        child = _Child(slot, replica_id, proc)
        child.restarts = restarts
        child.backoff_s = backoff_s
        self._children[slot] = child
        self._event("spawn", replica_id, pid=proc.pid,
                    restarts=restarts)
        return child

    # -- the reconciliation pass ------------------------------------------

    def step(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._reap(now)
            self._respawn_due(now)
            self._finish_stops(now)
            self._reconcile(now)
            self._write_state()

    def _reap(self, now: float) -> None:
        for child in list(self._children.values()):
            rc = child.proc.poll()
            if rc is None or child.respawn_at:
                continue
            if child.stopping:
                # the scale-down (or stop()) we asked for completed
                self._event("stopped", child.replica_id, code=rc)
                del self._children[child.slot]
                continue
            uptime = now - child.started_at
            if uptime > STABLE_BEATS * self.heartbeat_interval_s:
                child.backoff_s = 0.0  # it had recovered; start fresh
            # first crash respawns immediately — the fleet must be
            # whole again within two heartbeat intervals
            delay = child.backoff_s
            child.backoff_s = min(
                self.backoff_max_s,
                max(self.backoff_base_s, child.backoff_s * 2.0))
            child.respawn_at = now + delay
            self._event("crash", child.replica_id, code=rc,
                        uptime_s=round(uptime, 3),
                        respawn_in_s=round(delay, 3))

    def _respawn_due(self, now: float) -> None:
        for child in list(self._children.values()):
            if child.respawn_at and now >= child.respawn_at:
                backoff = child.backoff_s
                restarts = child.restarts + 1
                del self._children[child.slot]
                self._spawn(child.slot, restarts=restarts,
                            backoff_s=backoff)

    def _finish_stops(self, now: float) -> None:
        for child in list(self._children.values()):
            if (child.stopping and child.proc.poll() is None
                    and now >= child.stop_deadline):
                # drain grace exhausted: the hard line
                try:
                    child.proc.kill()
                except OSError:
                    pass
                self._event("killed", child.replica_id)

    def _desired(self) -> int:
        if self.desired_fn is not None:
            want = int(self.desired_fn())
        else:
            want = self._signals_desired()
        return max(self.min_replicas, min(self.max_replicas, want))

    def _signals_desired(self) -> int:
        """Default policy: PR 13's recommendation over the live fleet
        view. Unreachable sidecars degrade to 'hold current'."""
        from .federate import FleetFederator
        from .signals import derive_signals

        if self._federator is None:
            self._federator = FleetFederator(self.registry,
                                             timeout_s=1.0)
        try:
            view = self._federator.view()
            doc = derive_signals(view,
                                 min_replicas=self.min_replicas,
                                 max_replicas=self.max_replicas)
            return int(doc.get("desired_replicas",
                               len(self._children)))
        except Exception:
            return len(self._children) or self.min_replicas

    def _reconcile(self, now: float) -> None:
        active = [c for c in self._children.values()
                  if not c.stopping]
        current = len(active)
        want = self._desired()
        if want == self._desired_seen:
            self._desired_streak += 1
        else:
            self._desired_seen = want
            self._desired_streak = 1
        if want == current:
            return
        # below the floor is not a scale decision, it is repair: the
        # hold/damp gates exist to stop flapping, not to leave the
        # fleet short-handed
        repairing = current < self.min_replicas
        if not repairing:
            if self._desired_streak < self.hold_beats:
                return
            if now - self._last_scale_at < self.flap_damp_s:
                return
        self._last_scale_at = now
        if want > current:
            self._spawn(self._next_slot)
            self._next_slot += 1
            self._event("scale_up", "", toward=want, running=current)
        else:
            # newest first: the longest-lived caches stay
            victim = max(active, key=lambda c: c.started_at)
            victim.stopping = True
            victim.stop_deadline = now + self.drain_grace_s + 5.0
            try:
                victim.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            self._event("scale_down", victim.replica_id,
                        toward=want, running=current)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "FleetActuator":
        # bring the floor up before the first poll tick
        with self._lock:
            while len(self._children) < self.min_replicas:
                self._spawn(self._next_slot)
                self._next_slot += 1
            self._write_state()
        self._thread = threading.Thread(target=self._run,
                                        name="cobrix-actuator",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.step()
            except Exception:
                # the supervisor outlives any single bad pass
                pass

    def stop(self, grace_s: Optional[float] = None) -> None:
        """Tear down every child: SIGTERM (graceful drain), bounded
        wait, SIGKILL stragglers, reap all. No orphans survive this
        returning."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_interval_s * 4 + 5)
            self._thread = None
        grace = (self.drain_grace_s + 5.0 if grace_s is None
                 else max(0.0, float(grace_s)))
        with self._lock:
            children = list(self._children.values())
            for child in children:
                child.stopping = True
                if child.proc.poll() is None:
                    try:
                        child.proc.send_signal(signal.SIGTERM)
                    except OSError:
                        pass
            deadline = time.monotonic() + grace
            for child in children:
                left = deadline - time.monotonic()
                try:
                    child.proc.wait(timeout=max(0.0, left))
                except subprocess.TimeoutExpired:
                    try:
                        child.proc.kill()
                    except OSError:
                        pass
                    child.proc.wait()
                self._event("stopped", child.replica_id,
                            code=child.proc.returncode)
            self._children.clear()
            self._write_state()

    def replicas(self) -> List[dict]:
        with self._lock:
            return [self._child_doc(c)
                    for c in self._children.values()]

    def _child_doc(self, c: _Child) -> dict:
        rc = c.proc.poll()
        if c.respawn_at:
            state = "backoff"
        elif c.stopping:
            state = "draining"
        elif rc is not None:
            state = "exited"
        else:
            state = "running"
        return {"replica_id": c.replica_id, "slot": c.slot,
                "pid": c.proc.pid, "state": state,
                "restarts": c.restarts,
                "scan_address": list(c.scan_address or ()) or None,
                "uptime_s": round(time.monotonic() - c.started_at, 3)}

    # -- the paper trail (fleetview reads these) --------------------------

    def _event(self, event: str, replica_id: str, **detail) -> None:
        doc = {"ts": time.time(), "event": event,
               "replica_id": replica_id}
        doc.update(detail)
        path = os.path.join(self.fleet_dir, "actuator",
                            "events.jsonl")
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(doc, sort_keys=True) + "\n")
        except OSError:
            pass

    def _write_state(self) -> None:
        from ..io.integrity import stamp_json_payload
        from ..utils.atomic import write_atomic

        doc = stamp_json_payload({
            "generated_at": time.time(),
            "pid": os.getpid(),
            "desired": self._desired_seen,
            "running": sum(1 for c in self._children.values()
                           if not c.stopping
                           and c.proc.poll() is None),
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "replicas": [self._child_doc(c)
                         for c in self._children.values()],
        })
        try:
            write_atomic(
                os.path.join(self.fleet_dir, "actuator", "state.json"),
                json.dumps(doc, sort_keys=True))
        except OSError:
            pass

    def __enter__(self) -> "FleetActuator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def read_actuator_state(fleet_dir: str) -> Optional[dict]:
    """The actuator's stamped state.json, or None (absent/torn)."""
    from ..io.integrity import verify_json_payload

    path = os.path.join(fleet_dir, "actuator", "state.json")
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(doc, dict) and verify_json_payload(doc):
        doc.pop("payload_crc32", None)
        return doc
    return None


def read_actuator_events(fleet_dir: str, tail: int = 50) -> List[dict]:
    """The last `tail` events from events.jsonl (torn lines skipped)."""
    path = os.path.join(fleet_dir, "actuator", "events.jsonl")
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return []
    out: List[dict] = []
    for line in lines[-tail:]:
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            out.append(doc)
    return out
