"""The profile data model: per-chunk, per-field scan statistics.

A :class:`FileProfile` describes ONE version of one input file under
ONE decode configuration: an ordered list of :class:`ChunkStats`
covering the file's byte range, each carrying per-field
:class:`FieldStats` (min/max zone maps, null counts, exact sums where
the type allows, a bounded distinct-value sketch for low-cardinality
strings), a segment-id histogram, and a record-length histogram.

Values serialize by the field's declared kind so the JSON round-trip
is lossless: ints and strings natively, Decimals as strings (exact),
floats as floats. A field whose chunk carried NaNs drops its zone map
and sum for that chunk (``min``/``max``/``sum`` = None) — consumers
treat None as "unknown", never as "empty".
"""
from __future__ import annotations

from decimal import Decimal
from typing import Dict, List, Optional

# payload layout version: old entries become clean store misses
PROFILE_FORMAT = 1

# distinct-value sketch bound: above this many distinct non-null values
# the sketch overflows to None ("high cardinality, no membership info")
SKETCH_LIMIT = 32

# record-length histogram bound: above this many distinct lengths the
# remainder folds into the "other" bucket (zone maps stay exact)
LENGTH_HISTOGRAM_LIMIT = 64

_NUMERIC_KINDS = ("int", "float", "decimal")
_EXACT_SUM_KINDS = ("int", "decimal")


def _encode_value(kind: str, value):
    if value is None:
        return None
    if kind == "decimal":
        return str(value)
    return value


def _decode_value(kind: str, raw):
    if raw is None:
        return None
    if kind == "decimal":
        return Decimal(raw)
    return raw


class FieldStats:
    """One field's statistics over one chunk's records."""

    __slots__ = ("kind", "min", "max", "null_count", "sum", "distinct")

    def __init__(self, kind: str, min=None, max=None, null_count: int = 0,
                 sum=None, distinct=None):
        self.kind = kind            # int | float | decimal | string | bool
        self.min = min              # None = unknown (all-null or NaN-tainted)
        self.max = max
        self.null_count = int(null_count)
        self.sum = sum              # exact sum; None = unknown/inexact
        # tuple of distinct non-null values, or None (overflowed / not
        # sketched for this kind)
        self.distinct = tuple(distinct) if distinct is not None else None

    def to_row(self) -> list:
        return [
            _encode_value(self.kind, self.min),
            _encode_value(self.kind, self.max),
            self.null_count,
            _encode_value(self.kind, self.sum),
            (list(self.distinct) if self.distinct is not None else None),
        ]

    @classmethod
    def from_row(cls, kind: str, row) -> "FieldStats":
        vmin, vmax, nulls, total, distinct = row
        return cls(kind,
                   min=_decode_value(kind, vmin),
                   max=_decode_value(kind, vmax),
                   null_count=int(nulls),
                   sum=_decode_value(kind, total),
                   distinct=distinct)

    def merge(self, other: "FieldStats") -> "FieldStats":
        """Fold two chunks' stats into one (file-level rollups, drift)."""
        if self.kind != other.kind:
            raise ValueError(
                f"cannot merge field kinds {self.kind!r}/{other.kind!r}")
        # a None zone map means "all null" for the exactly-summable and
        # string kinds (fold skips it), but for floats it can also mean
        # NaN taint — there None must poison the merged map, because the
        # tainted chunk may carry values outside the other side's range
        if self.kind == "float" and (self.min is None
                                     or other.min is None):
            vmin = vmax = None
        else:
            pairs = [(self.min, self.max), (other.min, other.max)]
            known = [(lo, hi) for lo, hi in pairs if lo is not None]
            vmin = min((lo for lo, _ in known), default=None)
            vmax = max((hi for _, hi in known), default=None)
        total = (None if self.sum is None or other.sum is None
                 else self.sum + other.sum)
        if self.distinct is None or other.distinct is None:
            distinct = None
        else:
            merged = tuple(dict.fromkeys(self.distinct + other.distinct))
            distinct = merged if len(merged) <= SKETCH_LIMIT else None
        return FieldStats(self.kind, vmin, vmax,
                          self.null_count + other.null_count, total,
                          distinct)


class ChunkStats:
    """Statistics over one record-aligned byte range of one file."""

    __slots__ = ("offset", "nbytes", "records", "fields", "segments",
                 "lengths")

    def __init__(self, offset: int, nbytes: int, records: int,
                 fields: Dict[str, FieldStats],
                 segments: Optional[Dict[str, int]] = None,
                 lengths: Optional[Dict[int, int]] = None):
        self.offset = int(offset)
        self.nbytes = int(nbytes)
        self.records = int(records)
        self.fields = dict(fields)
        self.segments = dict(segments or {})
        # {record length -> count}; the overflow bucket keys on -1
        self.lengths = dict(lengths or {})

    @property
    def end(self) -> int:
        return self.offset + self.nbytes

    def to_payload(self) -> dict:
        return {
            "offset": self.offset,
            "nbytes": self.nbytes,
            "records": self.records,
            "fields": {name: fs.to_row()
                       for name, fs in sorted(self.fields.items())},
            "segments": dict(sorted(self.segments.items())),
            "lengths": {str(k): v
                        for k, v in sorted(self.lengths.items())},
        }

    @classmethod
    def from_payload(cls, doc: dict,
                     field_kinds: Dict[str, str]) -> "ChunkStats":
        fields = {name: FieldStats.from_row(field_kinds[name], row)
                  for name, row in doc["fields"].items()
                  if name in field_kinds}
        return cls(doc["offset"], doc["nbytes"], doc["records"], fields,
                   {str(k): int(v)
                    for k, v in (doc.get("segments") or {}).items()},
                   {int(k): int(v)
                    for k, v in (doc.get("lengths") or {}).items()})


class FileProfile:
    """The persisted per-file statistics artifact."""

    __slots__ = ("url", "record_kind", "record_size", "total_records",
                 "total_bytes", "field_kinds", "chunks")

    def __init__(self, url: str, record_kind: str, record_size: int,
                 total_records: int, total_bytes: int,
                 field_kinds: Dict[str, str],
                 chunks: List[ChunkStats]):
        self.url = url
        self.record_kind = record_kind      # "fixed" | "vrl"
        self.record_size = int(record_size)  # 0 for vrl
        self.total_records = int(total_records)
        self.total_bytes = int(total_bytes)
        self.field_kinds = dict(field_kinds)
        self.chunks = sorted(chunks, key=lambda c: c.offset)

    def to_payload(self) -> dict:
        return {
            "profile_format": PROFILE_FORMAT,
            "url": self.url,
            "record_kind": self.record_kind,
            "record_size": self.record_size,
            "total_records": self.total_records,
            "total_bytes": self.total_bytes,
            "field_kinds": dict(sorted(self.field_kinds.items())),
            "chunks": [c.to_payload() for c in self.chunks],
        }

    @classmethod
    def from_payload(cls, doc: dict) -> "FileProfile":
        if doc.get("profile_format") != PROFILE_FORMAT:
            raise ValueError("unsupported profile format")
        kinds = {str(k): str(v)
                 for k, v in (doc.get("field_kinds") or {}).items()}
        return cls(doc["url"], doc["record_kind"],
                   doc.get("record_size", 0), doc["total_records"],
                   doc["total_bytes"], kinds,
                   [ChunkStats.from_payload(c, kinds)
                    for c in doc["chunks"]])

    # -- rollups (drift detection, /stats, explain) ---------------------

    def merged_field(self, name: str) -> Optional[FieldStats]:
        """File-level fold of one field's chunk stats; None when no
        chunk carries the field."""
        out: Optional[FieldStats] = None
        for chunk in self.chunks:
            fs = chunk.fields.get(name)
            if fs is None:
                continue
            out = fs if out is None else out.merge(fs)
        return out

    def segment_totals(self) -> Dict[str, int]:
        totals: Dict[str, int] = {}
        for chunk in self.chunks:
            for seg, count in chunk.segments.items():
                totals[seg] = totals.get(seg, 0) + count
        return totals

    def length_totals(self) -> Dict[int, int]:
        totals: Dict[int, int] = {}
        for chunk in self.chunks:
            for length, count in chunk.lengths.items():
                totals[length] = totals.get(length, 0) + count
        return totals

    def summary(self) -> dict:
        """The compact /stats + explain view (no per-chunk detail)."""
        fields = {}
        for name in sorted(self.field_kinds):
            fs = self.merged_field(name)
            if fs is None:
                continue
            row = {"kind": fs.kind, "nulls": fs.null_count}
            if fs.min is not None:
                row["min"] = _encode_value(fs.kind, fs.min)
                row["max"] = _encode_value(fs.kind, fs.max)
            if fs.distinct is not None:
                row["distinct"] = len(fs.distinct)
            fields[name] = row
        out = {
            "url": self.url,
            "record_kind": self.record_kind,
            "chunks": len(self.chunks),
            "records": self.total_records,
            "bytes": self.total_bytes,
            "fields": fields,
        }
        segments = self.segment_totals()
        if segments:
            out["segments"] = dict(sorted(segments.items()))
        return out
