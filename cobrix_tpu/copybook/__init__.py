from .copybook import Copybook, merge_copybooks, parse_copybook

__all__ = ["Copybook", "parse_copybook", "merge_copybooks"]
