"""Supervised distributed execution: crash recovery, shard re-dispatch,
stragglers, deadlines — the Spark task-supervision semantics
(task retry / speculation / partial results) for both distributed paths:

* the multi-host process scheduler (parallel/supervisor.py): injected
  worker crashes, hangs past the shard deadline, stragglers, poison
  shards, and whole-scan deadlines, under fail_fast and partial policies
  on both fixed-length and variable-length inputs, asserting full row
  parity with a clean single-process read wherever recovery is promised;
* the pipeline executor watchdog (engine/pipeline.py): re-queue-once,
  per-chunk and whole-scan deadlines, stuck-stage reporting, bounded
  shutdown.

Every test runs under a hard SIGALRM deadline (tests/util.hard_timeout):
a supervision bug can fail these tests but can never hang CI.
"""
import os
import tempfile
import threading
import time

import pytest

from cobrix_tpu import read_cobol
from cobrix_tpu.engine.pipeline import (PipelineExecutor,
                                        PipelineTimeoutError)
from cobrix_tpu.parallel.supervisor import (ScanDeadlineError,
                                            ShardSupervisionError)
from cobrix_tpu.reader.diagnostics import (ReadDiagnostics,
                                           ShardErrorPolicy,
                                           ShardFailureInfo)
from cobrix_tpu.testing.faults import ShardFaultPlan
from cobrix_tpu.testing.generators import (EXP1_COPYBOOK, EXP2_COPYBOOK,
                                           generate_exp1, generate_exp2)

from util import hard_timeout


@pytest.fixture(autouse=True)
def _no_hang(request):
    limit = 900 if request.node.get_closest_marker("slow") else 120
    with hard_timeout(limit, request.node.name):
        yield


@pytest.fixture
def state_dir(tmp_path):
    return str(tmp_path / "fault_state")


@pytest.fixture
def plan(state_dir):
    os.makedirs(state_dir, exist_ok=True)
    return ShardFaultPlan(state_dir)


VARLEN_BASE = dict(copybook_contents=EXP2_COPYBOOK,
                   is_record_sequence="true",
                   segment_field="SEGMENT-ID",
                   redefine_segment_id_map="STATIC-DETAILS => C",
                   redefine_segment_id_map_1="CONTACTS => P",
                   segment_id_prefix="SUP",
                   generate_record_id="true")


@pytest.fixture(scope="module")
def varlen_files():
    d = tempfile.mkdtemp(prefix="supervision_")
    for i, (n, seed) in enumerate([(1200, 13), (800, 14)]):
        with open(os.path.join(d, f"part{i}.dat"), "wb") as f:
            f.write(generate_exp2(n, seed=seed))
    return os.path.join(d, "*.dat")


@pytest.fixture(scope="module")
def varlen_clean(varlen_files):
    return read_cobol(varlen_files, **VARLEN_BASE).to_arrow()


@pytest.fixture(scope="module")
def fixed_file():
    d = tempfile.mkdtemp(prefix="supervision_fixed_")
    p = os.path.join(d, "fixed.dat")
    with open(p, "wb") as f:
        f.write(generate_exp1(301, seed=21).tobytes())
    return p


def sup(data):
    return data.metrics.as_dict()["supervision"]


# -- worker crash: re-dispatch onto a respawned worker, full parity ------

def test_worker_crash_recovery_varlen(varlen_files, varlen_clean, plan):
    plan.crash(1)
    with plan.installed():
        data = read_cobol(varlen_files, hosts="2",
                          input_split_records="400", **VARLEN_BASE)
    assert data.to_arrow().equals(varlen_clean)
    report = sup(data)
    assert report["worker_crashes"] >= 1
    assert report["re_dispatches"] >= 1
    # NOT pinned: worker_respawns. The pool only refills when the scan
    # still needs the capacity — on a loaded box the surviving worker
    # can absorb the re-dispatched shard with nothing else pending, and
    # recovering WITHOUT a respawn is the cheaper, equally-correct
    # outcome (the parity + shards_failed==0 asserts are the recovery
    # contract; the pin made scheduling luck a test failure)
    assert report["shards_failed"] == 0
    assert data.diagnostics is None  # recovered fail_fast read is clean


def test_worker_crash_recovery_fixed(fixed_file, plan):
    kw = dict(copybook_contents=EXP1_COPYBOOK)
    clean = read_cobol(fixed_file, **kw).to_arrow()
    plan.crash(0)
    with plan.installed():
        data = read_cobol(fixed_file, hosts="2", **kw)
    assert data.to_arrow().equals(clean)
    assert sup(data)["worker_crashes"] >= 1


# -- worker hang: shard deadline kills + re-dispatches -------------------

def test_worker_hang_redispatched_after_deadline(varlen_files,
                                                 varlen_clean, plan):
    plan.hang(2, seconds=60.0)
    with plan.installed():
        t0 = time.monotonic()
        data = read_cobol(varlen_files, hosts="2",
                          input_split_records="400", shard_timeout_s="2",
                          **VARLEN_BASE)
        elapsed = time.monotonic() - t0
    assert data.to_arrow().equals(varlen_clean)
    report = sup(data)
    assert report["shard_timeouts"] >= 1
    assert report["re_dispatches"] >= 1
    assert elapsed < 60  # the hang was cut short, not waited out


# -- straggler: speculative duplicate wins, duplicates dedupe ------------

def test_straggler_speculation_first_completion_wins(varlen_files,
                                                     varlen_clean, plan):
    plan.slow(1, seconds=20.0)  # once: the speculative copy runs clean
    with plan.installed():
        t0 = time.monotonic()
        data = read_cobol(varlen_files, hosts="2",
                          input_split_records="400",
                          speculative_quantile="0.5", **VARLEN_BASE)
        elapsed = time.monotonic() - t0
    assert data.to_arrow().equals(varlen_clean)
    report = sup(data)
    assert report["speculations_launched"] >= 1
    assert report["speculations_won"] >= 1
    assert elapsed < 20  # the straggler did not serialize the scan


# -- poison shard: fail_fast raises the original error, partial ledgers --

def test_poison_shard_fail_fast_raises_original(varlen_files, plan):
    plan.error(0, message="injected shard error", once=False)
    with plan.installed():
        with pytest.raises(RuntimeError, match="injected shard error"):
            read_cobol(varlen_files, hosts="2", input_split_records="400",
                       shard_max_retries="1", **VARLEN_BASE)


def test_poison_shard_partial_returns_rest_plus_ledger(varlen_files,
                                                       varlen_clean,
                                                       plan):
    plan.error(0, once=False)
    with plan.installed():
        data = read_cobol(varlen_files, hosts="2",
                          input_split_records="400",
                          shard_error_policy="partial",
                          shard_max_retries="1", **VARLEN_BASE)
    table = data.to_arrow()
    assert 0 < table.num_rows < varlen_clean.num_rows
    d = data.diagnostics
    assert d is not None and d.shards_failed == 1
    failure = d.shard_failures[0]
    assert failure.reason == "error"
    assert "injected shard error" in failure.error
    assert failure.attempts == 2  # initial + one re-dispatch
    # the completed shards are byte-faithful: the missing rows are
    # exactly the failed shard's contiguous prefix of file 0
    clean_ids = set(varlen_clean.column("Record_Id").to_pylist())
    part_ids = set(table.column("Record_Id").to_pylist())
    missing = clean_ids - part_ids
    assert part_ids <= clean_ids and missing
    assert min(missing) == 0 and max(missing) == len(missing) - 1


def test_persistent_crash_partial(varlen_files, varlen_clean, plan):
    plan.crash(1, once=False)
    with plan.installed():
        data = read_cobol(varlen_files, hosts="2",
                          input_split_records="400",
                          shard_error_policy="partial",
                          shard_max_retries="1", **VARLEN_BASE)
    d = data.diagnostics
    assert d is not None and d.shards_failed == 1
    assert d.shard_failures[0].reason == "crash"
    assert sup(data)["worker_crashes"] >= 2  # every attempt died
    assert 0 < data.to_arrow().num_rows < varlen_clean.num_rows


def test_persistent_crash_fail_fast_raises_supervision_error(
        varlen_files, plan):
    plan.crash(1, once=False)
    with plan.installed():
        with pytest.raises(ShardSupervisionError, match="crash"):
            read_cobol(varlen_files, hosts="2", input_split_records="400",
                       shard_max_retries="1", **VARLEN_BASE)


# -- whole-scan deadline -------------------------------------------------

def test_scan_deadline_fail_fast(varlen_files, plan):
    plan.hang(1, seconds=60.0, once=False)
    t0 = time.monotonic()
    with plan.installed():
        with pytest.raises(ScanDeadlineError, match="deadline"):
            read_cobol(varlen_files, hosts="2", input_split_records="400",
                       scan_deadline_s="2", **VARLEN_BASE)
    assert time.monotonic() - t0 < 30


def test_scan_deadline_partial(varlen_files, varlen_clean, plan):
    plan.hang(1, seconds=60.0, once=False)
    t0 = time.monotonic()
    with plan.installed():
        data = read_cobol(varlen_files, hosts="2",
                          input_split_records="400", scan_deadline_s="2",
                          shard_error_policy="partial", **VARLEN_BASE)
    assert time.monotonic() - t0 < 30
    d = data.diagnostics
    assert d is not None and d.shards_failed >= 1
    assert {f.reason for f in d.shard_failures} == {"scan_deadline"}
    assert 0 < data.to_arrow().num_rows < varlen_clean.num_rows


# -- satellite regressions ----------------------------------------------

def test_duplicate_shard_keys_dedupe_deterministically(varlen_files,
                                                       varlen_clean):
    """A duplicated shard in the plan (speculation/re-dispatch aftermath,
    or a planner bug) must dedupe to one result + a metric, not silently
    last-write-wins overwrite."""
    import pyarrow as pa

    from cobrix_tpu.api import (CobolOutputSchema, _plan_var_len_shards,
                                parse_options)
    from cobrix_tpu.parallel.hosts import multihost_scan
    from cobrix_tpu.reader.var_len_reader import VarLenReader

    params, _ = parse_options(dict(VARLEN_BASE))
    reader = VarLenReader(EXP2_COPYBOOK, params)
    files = sorted(
        os.path.join(os.path.dirname(varlen_files), f)
        for f in os.listdir(os.path.dirname(varlen_files))
        if f.endswith(".dat"))
    shards = _plan_var_len_shards(reader, files, params)
    assert len(shards) >= 2
    schema = CobolOutputSchema(
        reader.copybook, policy=params.schema_policy,
        generate_record_id=True, generate_seg_id_field_count=0,
        segment_id_prefix="")
    tables, failures, report = multihost_scan(
        reader, list(shards) + [shards[0]], True, schema, 2, "SUP")
    assert report["duplicate_shard_keys"] == 1
    assert not failures
    merged = pa.concat_tables(tables)
    assert merged.num_rows == varlen_clean.num_rows


def test_concurrent_multihost_scans_do_not_clobber(tmp_path):
    """Two multihost scans in flight at once: the worker context is
    per-scan (fork closure), not a module global — each must return its
    own rows (the old `_CTX` global made this a race). The scans run
    with supervision deadlines enabled: forking from a threaded parent
    can wedge a child under load, and recovering from exactly that
    (kill + re-dispatch on a fresh fork) is the supervisor's job."""
    kw = dict(copybook_contents=EXP1_COPYBOOK, shard_timeout_s="15",
              scan_deadline_s="90")
    paths, singles = [], []
    for i, n in enumerate((180, 260)):
        p = str(tmp_path / f"c{i}.dat")
        with open(p, "wb") as f:
            f.write(generate_exp1(n, seed=30 + i).tobytes())
        paths.append(p)
        singles.append(read_cobol(p, **kw).to_arrow())
    outputs = [None, None]
    errors = []

    def scan(i):
        try:
            outputs[i] = read_cobol(paths[i], hosts="2", **kw).to_arrow()
        except BaseException as exc:  # surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=scan, args=(i,), daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=100)
    assert not errors
    for i in range(2):
        assert outputs[i] is not None and outputs[i].equals(singles[i])


def test_shard_failure_ledger_roundtrip():
    d = ReadDiagnostics()
    d.record_shard_failure(ShardFailureInfo(
        file="/data/x.dat", offset_from=100, offset_to=900,
        record_index=4, attempts=3, reason="timeout", error="wedged"))
    back = ReadDiagnostics.from_json(d.to_json())
    assert back.shards_failed == 1
    assert back.shard_failures[0] == d.shard_failures[0]
    assert not back.is_clean
    merged = ReadDiagnostics.merged([back, ReadDiagnostics()])
    assert merged.shards_failed == 1


def test_supervision_option_validation():
    kw = dict(copybook_contents=EXP1_COPYBOOK)
    with pytest.raises(ValueError, match="speculative_quantile"):
        read_cobol("/nonexistent", speculative_quantile="1.5", **kw)
    with pytest.raises(ValueError, match="shard_timeout_s"):
        read_cobol("/nonexistent", shard_timeout_s="-1", **kw)
    with pytest.raises(ValueError, match="shard_max_retries"):
        read_cobol("/nonexistent", shard_max_retries="-1", **kw)
    with pytest.raises(ValueError, match="shard_error_policy"):
        read_cobol("/nonexistent", shard_error_policy="maybe", **kw)
    with pytest.raises(ValueError, match="scan_deadline_s"):
        read_cobol("/nonexistent", scan_deadline_s="-2", **kw)


def test_supervision_options_pedantic_accepted(fixed_file):
    data = read_cobol(fixed_file, copybook_contents=EXP1_COPYBOOK,
                      hosts="2", pedantic="true",
                      shard_error_policy="partial", shard_timeout_s="30",
                      shard_max_retries="1", speculative_quantile="0.9",
                      scan_deadline_s="60", heartbeat_interval_s="0.2")
    assert len(data) == 301


# -- pipeline executor watchdog (thread path, same discipline) -----------

def _task(i, proc):
    return ((lambda: i), proc)


def test_pipeline_requeues_chunk_once():
    failed_once = []

    def proc(x):
        if x == 1 and not failed_once:
            failed_once.append(x)
            raise RuntimeError("transient chunk failure")
        return x * 10

    ex = PipelineExecutor(2)
    assert ex.run([_task(i, proc) for i in range(4)]) == [0, 10, 20, 30]
    assert ex.report["chunk_retries"] == 1


def test_pipeline_second_failure_is_fatal_fail_fast():
    def proc(x):
        if x == 1:
            raise RuntimeError("poison chunk")
        return x

    ex = PipelineExecutor(2)
    with pytest.raises(RuntimeError, match="poison chunk"):
        ex.run([_task(i, proc) for i in range(3)])
    assert ex.report["chunk_retries"] == 1


def test_pipeline_partial_drops_failed_chunk_with_ledger():
    def proc(x):
        if x == 0:
            raise ValueError("poison chunk 0")
        return x

    ex = PipelineExecutor(2, error_policy=ShardErrorPolicy.PARTIAL)
    out = ex.run([_task(i, proc) for i in range(3)])
    assert out == [None, 1, 2]
    assert [f.reason for f in ex.shard_failures] == ["error"]
    assert "poison chunk 0" in ex.shard_failures[0].error


def test_pipeline_chunk_deadline_fail_fast_names_stage():
    def proc(x):
        if x == 1:
            time.sleep(30)
        return x

    ex = PipelineExecutor(2, chunk_timeout_s=1.0)
    t0 = time.monotonic()
    with pytest.raises(PipelineTimeoutError, match="decode"):
        ex.run([_task(i, proc) for i in range(3)])
    assert time.monotonic() - t0 < 15  # bounded: no indefinite join


def test_pipeline_chunk_deadline_partial_respawns_worker():
    def proc(x):
        if x == 1:
            time.sleep(30)
        return x

    ex = PipelineExecutor(2, chunk_timeout_s=1.0,
                          error_policy=ShardErrorPolicy.PARTIAL)
    out = ex.run([_task(i, proc) for i in range(5)])
    assert out == [0, None, 2, 3, 4]
    assert ex.report["chunk_timeouts"] == 1
    assert ex.report["respawned_workers"] >= 1
    assert [f.reason for f in ex.shard_failures] == ["timeout"]


def test_pipeline_scan_deadline_bounded():
    def proc(x):
        time.sleep(30)
        return x

    ex = PipelineExecutor(2, scan_deadline_s=1.0)
    t0 = time.monotonic()
    with pytest.raises(PipelineTimeoutError, match="scan deadline"):
        ex.run([_task(i, proc) for i in range(2)])
    assert time.monotonic() - t0 < 15


def test_pipeline_stall_reports_stuck_stage():
    """A wedged assembler (no deadlines configured) trips the stall
    backstop and the error names the stuck stage instead of hanging."""
    def proc(x):
        return x

    def finalize(result):
        time.sleep(60)

    ex = PipelineExecutor(1, stall_timeout_s=1.5)
    with pytest.raises(PipelineTimeoutError, match="assemble"):
        ex.run([((lambda: 0), proc, finalize)])


# -- chaoscheck smoke (the hosts x fault grid stays behind `slow`) -------

def test_chaoscheck_quick():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "tools/chaoscheck.py", "--records", "1200"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


@pytest.mark.slow
def test_chaoscheck_sweep():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "tools/chaoscheck.py", "--records", "4800",
         "--sweep"],
        capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_pipelined_read_supervision_knobs_end_to_end(tmp_path):
    """The pipeline watchdog knobs thread through read_cobol (parity run
    with generous deadlines — supervision on, nothing to trip)."""
    p = str(tmp_path / "pipe.dat")
    with open(p, "wb") as f:
        f.write(generate_exp1(400, seed=5).tobytes())
    kw = dict(copybook_contents=EXP1_COPYBOOK)
    clean = read_cobol(p, **kw).to_arrow()
    data = read_cobol(p, pipeline_workers="2", chunk_size_mb="0.1",
                      shard_timeout_s="60", scan_deadline_s="120",
                      shard_error_policy="partial", **kw)
    assert data.to_arrow().equals(clean)
    assert data.diagnostics is None  # nothing failed -> clean read
