"""Header resynchronization: recover record framing after corruption.

Real mainframe dumps contain bit rot, torn tails and garbage splices; a
fail-fast frame chain turns one bad RDW into a dead file. In the
permissive policies the framers recover instead: on an invalid header
they scan forward within a bounded window (``resync_window_bytes``,
default 64 KB) for the next *plausible* header — one whose length parses
and whose implied next header also parses (or lands exactly on EOF) —
record the skipped byte range in the read's ledger, and resume. A corrupt
run longer than the window is a hard error even in permissive modes, so a
completely garbage file still fails promptly with a clear message.

Two framing planes share the same resync rules:

  * :func:`rdw_scan_permissive` — the whole-shard vectorized plane: wraps
    the native ``rdw_scan`` and re-drives it across corrupt regions using
    a vectorized candidate search (clean files cost one native call, same
    as fail-fast).
  * :class:`PendingReader` + :func:`resync_stream` — the per-record
    stream plane (custom header parsers, length fields, the host oracle
    path): a small pushback wrapper so bytes read ahead during a resync
    are re-served to the normal framing loop.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from ..copybook.datatypes import MAX_RDW_RECORD_SIZE
from .diagnostics import (
    FramingError,
    ReadDiagnostics,
    RecordErrorPolicy,
    hex_snapshot,
)
from .stream import SimpleStream


def _rdw_lengths_at(buf: np.ndarray, positions: np.ndarray,
                    big_endian: bool, adjustment: int) -> np.ndarray:
    """Parsed RDW length at each candidate position (vectorized)."""
    if big_endian:
        lens = buf[positions + 1].astype(np.int64) \
            + 256 * buf[positions].astype(np.int64)
    else:
        lens = buf[positions + 2].astype(np.int64) \
            + 256 * buf[positions + 3].astype(np.int64)
    return lens + adjustment


def _rdw_reserved_zero(buf: np.ndarray, positions: np.ndarray,
                       big_endian: bool) -> np.ndarray:
    """True where the RDW's reserved byte pair is zero (bytes 2-3 for
    big-endian, 0-1 for little-endian). The record parser itself stays
    lax (mirroring the reference), but for RESYNC plausibility this is
    the discriminator that keeps EBCDIC payload bytes — which routinely
    parse as large-but-valid lengths — from hijacking the scan: a
    payload-aligned candidate chain dies at its first successor, whose
    reserved pair is payload too."""
    if big_endian:
        reserved = buf[positions + 2] | buf[positions + 3]
    else:
        reserved = buf[positions] | buf[positions + 1]
    return reserved == 0


# How many successor headers a resync candidate must chain through before
# it is believed. Payload/garbage bytes regularly parse as ONE valid
# header, so a single-successor check mis-resyncs; requiring the chain to
# survive 3 successors (or land exactly on EOF) rejects those while still
# accepting a real record start even when ANOTHER corrupt site lies a few
# records ahead (deeper checks would reject everything between two nearby
# corruption sites, swallowing good records).
RESYNC_CHAIN_DEPTH = 3
GENERIC_CHAIN_DEPTH = 3


def find_next_rdw(buf: np.ndarray, start: int, end: int, big_endian: bool,
                  adjustment: int,
                  body_end: Optional[int] = None,
                  depth: int = RESYNC_CHAIN_DEPTH) -> Optional[int]:
    """First plausible RDW header position in ``buf[start:end)``.

    Plausible: the length parses into (0, MAX_RDW_RECORD_SIZE] and the
    implied header chain stays parseable for ``depth`` successors - or
    lands exactly on ``body_end`` first. With ``body_end`` of None the
    buffer is a window into a longer stream: a chain running past the
    window is unverifiable and accepted (the caller's framing loop
    re-validates it live). Deep chaining keeps payload bytes that happen
    to parse as one valid header from hijacking the resync.
    """
    limit = len(buf) if body_end is None else body_end
    end = min(end, limit - 3)
    if end <= start:
        return None
    cand = np.arange(start, end, dtype=np.int64)
    lens = _rdw_lengths_at(buf, cand, big_endian, adjustment)
    alive = (lens > 0) & (lens <= MAX_RDW_RECORD_SIZE) \
        & _rdw_reserved_zero(buf, cand, big_endian)
    confirmed = np.zeros(len(cand), dtype=bool)
    escaped = np.zeros(len(cand), dtype=bool)
    overshoot = np.full(len(cand), np.inf)
    pos = cand + 4 + lens  # each candidate's next-header position
    for _ in range(depth):
        if body_end is not None:
            confirmed |= alive & (pos == limit)
        # chain leaves the buffer before `depth` successors. Mid-stream
        # (no body_end) that is unverifiable; at/with a true end it is a
        # candidate whose final record overruns the data — a truncated
        # tail. Both are kept only as a fallback below, so a
        # payload-parsed giant length cannot outrank a candidate whose
        # chain verifies inside the buffer, yet a lone truncated final
        # record after a corrupt run is still recovered (and then
        # clamped + ledgered by the framing layer) rather than silently
        # swallowed into the skip.
        escaping = alive & ~confirmed & (pos + 4 > limit)
        overshoot[escaping] = pos[escaping] - limit
        escaped |= escaping
        alive &= ~confirmed & ~escaped
        if not alive.any():
            break
        safe = np.minimum(np.where(alive, pos, 0), limit - 4)
        nxt_lens = _rdw_lengths_at(buf, safe, big_endian, adjustment)
        alive &= (nxt_lens > 0) & (nxt_lens <= MAX_RDW_RECORD_SIZE) \
            & _rdw_reserved_zero(buf, safe, big_endian)
        pos = pos + 4 + nxt_lens
    hits = np.nonzero(confirmed | alive)[0]
    if len(hits):
        return int(cand[hits[0]])
    hits = np.nonzero(escaped)[0]
    if not len(hits):
        return None
    if body_end is None:
        return int(cand[hits[0]])
    # with the true end in view, the least-overshooting chain is the
    # plausible truncated tail; a payload-parsed giant length overshoots
    # by ~its whole bogus record
    return int(cand[hits[np.argmin(overshoot[hits])]])


def rdw_scan_permissive(data, big_endian: bool, adjustment: int,
                        file_header_bytes: int, file_footer_bytes: int,
                        policy: RecordErrorPolicy,
                        window: int,
                        ledger: ReadDiagnostics,
                        file_name: str = "",
                        base_offset: int = 0
                        ) -> Tuple[np.ndarray, np.ndarray, dict]:
    """Whole-shard RDW framing that survives corrupt headers.

    Drives the native ``rdw_scan`` across corrupt regions: on a framing
    error the clean prefix is kept, the corrupt run is skipped via
    :func:`find_next_rdw` (bounded by ``window``), and every incident is
    recorded in ``ledger``. Returns ``(offsets, lengths, corrupt_reasons)``
    where ``corrupt_reasons`` maps kept record positions to the reason a
    record is malformed (truncated tail); under ``drop_malformed`` those
    records are already removed from the output arrays.

    Byte offsets in ledger entries are absolute file offsets
    (``base_offset`` + buffer position).
    """
    from .. import native

    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(
        data, np.ndarray) else data
    size = buf.size
    body_end = size - file_footer_bytes \
        if 0 < file_footer_bytes < size else size
    resume = 0
    parts_off, parts_len = [], []

    def scan_clean(lo: int, hi: int, header_bytes: int):
        if hi <= lo:
            return
        o, l = native.rdw_scan(buf[lo:hi], big_endian, adjustment,
                               header_bytes, 0)
        if len(o):
            parts_off.append(o + lo)
            parts_len.append(l)

    while resume < body_end:
        header_bytes = file_header_bytes if resume == 0 else 0
        try:
            scan_clean(resume, body_end, header_bytes)
            break
        except FramingError as exc:
            err = resume + max(exc.offset, 0)
            # the prefix up to the bad header is clean by construction
            scan_clean(resume, err, header_bytes)
            snapshot = bytes(buf[err:err + 4])
            nxt = find_next_rdw(buf, err + 1, err + 1 + window, big_endian,
                                adjustment, body_end)
            if nxt is None:
                remaining = body_end - err
                if remaining > window:
                    raise FramingError(
                        f"Corrupt run at offset {base_offset + err} of "
                        f"'{file_name}' exceeds the resync window "
                        f"({window} bytes) with no plausible record header "
                        f"found (headers = {hex_snapshot(snapshot)}); "
                        "increase 'resync_window' or fix the input.",
                        offset=base_offset + err, header=snapshot,
                        file_name=file_name) from exc
                ledger.record_skip(file_name, base_offset + err, remaining,
                                   exc.reason, snapshot)
                break
            ledger.record_skip(file_name, base_offset + err, nxt - err,
                               exc.reason, snapshot)
            resume = nxt

    if parts_off:
        offsets = np.concatenate(parts_off)
        lengths = np.concatenate(parts_len)
    else:
        offsets = np.zeros(0, dtype=np.int64)
        lengths = np.zeros(0, dtype=np.int64)

    corrupt_reasons: dict = {}
    if len(offsets):
        # a record clamped against end-of-data was truncated: its header
        # declared more bytes than the file holds
        last = len(offsets) - 1
        declared = int(_rdw_lengths_at(
            buf, offsets[last:last + 1] - 4, big_endian, adjustment)[0])
        actual = int(lengths[last])
        if declared > actual:
            pos = int(offsets[last])
            reason = (f"record truncated at end of data: header declares "
                      f"{declared} bytes, {actual} available")
            ledger.record(
                _truncation_entry(file_name, base_offset + pos - 4,
                                  reason, bytes(buf[pos - 4:pos]),
                                  None if policy is RecordErrorPolicy.
                                  DROP_MALFORMED else last),
                dropped=policy is RecordErrorPolicy.DROP_MALFORMED)
            if policy is RecordErrorPolicy.DROP_MALFORMED:
                offsets = offsets[:last]
                lengths = lengths[:last]
            else:
                corrupt_reasons[last] = reason
    return offsets, lengths, corrupt_reasons


def _truncation_entry(file_name: str, offset: int, reason: str,
                      header: bytes, record_index: Optional[int]):
    from .diagnostics import CorruptRecordInfo

    return CorruptRecordInfo(file_name, offset, 0, reason,
                             hex_snapshot(header), record_index)


class PendingReader:
    """Forward reads over a SimpleStream with pushback: bytes read ahead
    during a resync are re-served before the stream is touched again."""

    __slots__ = ("stream", "_pending")

    def __init__(self, stream: SimpleStream):
        self.stream = stream
        self._pending = b""

    @property
    def offset(self) -> int:
        return self.stream.offset - len(self._pending)

    @property
    def at_end(self) -> bool:
        return not self._pending and self.stream.is_end_of_stream

    def push_back(self, data: bytes) -> None:
        self._pending = bytes(data) + self._pending

    def read(self, n: int) -> bytes:
        if n <= 0:
            return b""
        if self._pending:
            head = self._pending[:n]
            self._pending = self._pending[n:]
            if len(head) == n:
                return head
            return head + self.stream.next(n - len(head))
        return self.stream.next(n)


def rdw_blob_validator(parser) -> Callable[[bytes, int, bool], Optional[int]]:
    """Candidate validator over a resync blob for RDW headers: vectorized
    search delegated to :func:`find_next_rdw`. When the blob reaches the
    end of the stream (`at_eof`) the chain rules match the whole-file
    scan exactly, so the stream and vectorized planes resync identically."""

    def first_plausible(blob: bytes, start: int,
                        at_eof: bool) -> Optional[int]:
        buf = np.frombuffer(blob, dtype=np.uint8)
        return find_next_rdw(buf, start, len(blob), parser.is_big_endian,
                             parser.rdw_adjustment,
                             body_end=len(blob) if at_eof else None)

    return first_plausible


def generic_blob_validator(parser, file_size: int, base_offset: int
                           ) -> Callable[[bytes, int, bool], Optional[int]]:
    """Candidate validator for arbitrary header parsers: a position is
    plausible when the parser yields a positive record length there and
    the implied header chain stays parseable for GENERIC_CHAIN_DEPTH
    successors (or exits the blob — exactly at its end when `at_eof`)."""
    hlen = parser.header_length

    def meta_len(blob: bytes, k: int) -> Optional[int]:
        try:
            meta = parser.get_record_metadata(
                blob[k:k + hlen], base_offset + k + hlen, file_size, 0)
        except ValueError:
            return None
        return meta.record_length if meta.record_length > 0 else None

    def chains(blob: bytes, k: int, at_eof: bool) -> bool:
        q = k
        for _ in range(GENERIC_CHAIN_DEPTH + 1):
            if q == len(blob) and at_eof:
                return True
            if q + hlen > len(blob):
                return not at_eof  # unverifiable: accept mid-stream only
            ln = meta_len(blob, q)
            if ln is None:
                return False
            q = q + hlen + ln
        return True

    def first_plausible(blob: bytes, start: int,
                        at_eof: bool) -> Optional[int]:
        for k in range(start, len(blob) - hlen + 1):
            if chains(blob, k, at_eof):
                return k
        return None

    return first_plausible


def resync_stream(reader: PendingReader, bad_header: bytes,
                  first_plausible: Callable[[bytes, int, bool],
                                            Optional[int]],
                  header_length: int, window: int,
                  ledger: ReadDiagnostics, file_name: str,
                  reason: str) -> Optional[bytes]:
    """Skip a corrupt run on the stream plane and return the next
    plausible header's bytes (the remainder of the read-ahead blob is
    pushed back). None means the corrupt run reaches end-of-stream (the
    remaining bytes were skipped and ledgered). Raises FramingError when
    the run exceeds the window mid-stream.
    """
    bad_offset = reader.offset - len(bad_header)
    blob = bytes(bad_header) + reader.read(window)
    at_eof = len(blob) < window + len(bad_header)
    found = (first_plausible(blob, 1, at_eof)
             if len(blob) > header_length else None)
    if found is None:
        if at_eof:
            if len(blob):
                ledger.record_skip(file_name, bad_offset, len(blob), reason,
                                   blob[:4])
            return None
        raise FramingError(
            f"Corrupt run at offset {bad_offset} of '{file_name}' exceeds "
            f"the resync window ({window} bytes) with no plausible record "
            f"header found (headers = {hex_snapshot(blob[:4])}); increase "
            "'resync_window' or fix the input.",
            offset=bad_offset, header=blob[:4], file_name=file_name)
    ledger.record_skip(file_name, bad_offset, found, reason, blob[:4])
    header = blob[found:found + header_length]
    reader.push_back(blob[found + header_length:])
    return header
