"""Read-ahead prefetching for sequential byte-range consumers.

The scan engine consumes remote files almost perfectly sequentially
(BufferedSourceStream fills chunk after chunk; the pipeline's chunk
readers each walk one byte range) — which makes the access pattern
predictable enough to hide network latency entirely: while framing and
decode chew on block k, a small pool fetches blocks k+1..k+N. That is
the same overlap the chunked pipeline buys between *stages*, applied to
the network fetch itself — the decode-throughput papers' point that a
fast decoder leaves the scan bandwidth-bound is answered here, where
the bandwidth is produced.

`ReadAheadSource` wraps any ByteRangeSource (typically a CachingSource,
so prefetches also warm the persistent cache):

* reads are served block-aligned from an in-memory window of at most
  `depth + 2` blocks (bounded memory regardless of file size);
* after each consumer read, the next `depth` blocks are scheduled on
  the pool; consecutive missing blocks coalesce into ONE backend range
  request (`prefetch_issued` counts fetches, not blocks);
* a consumer read finding its block already fetched counts
  `prefetch_hits`; finding it in flight waits and counts
  `prefetch_waits`; blocks never consumed count `prefetch_unused` at
  close — utilization = issued minus unused over issued;
* a failed prefetch is dropped from the window and the error re-raised
  on the consumer thread, where the stream's RetryPolicy already
  governs re-issue — the pool never retries on its own.

The pool is created lazily on first read and torn down on close, so a
forked worker that inherited an un-started source builds its own
threads (and its own backend connection) after the fork — threads and
fds never cross process boundaries.
"""
from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional, Tuple

from ..reader.stream import ByteRangeSource
from .stats import IoStats


class _Block:
    """One prefetch-window slot: a future (in flight) or bytes (done),
    plus whether the consumer ever read from it."""

    __slots__ = ("future", "data", "consumed", "prefetched")

    def __init__(self, future: Optional[Future] = None,
                 data: Optional[bytes] = None, prefetched: bool = False):
        self.future = future
        self.data = data
        self.consumed = False
        self.prefetched = prefetched


class ReadAheadSource(ByteRangeSource):
    def __init__(self, inner: ByteRangeSource, block_bytes: int,
                 depth: int, io_stats: Optional[IoStats] = None,
                 count_fetch_bytes: bool = False,
                 limit: int = 0):
        self._inner = inner
        self._block = max(1, int(block_bytes))
        self._depth = max(1, int(depth))
        self._io_stats = io_stats
        # True when this source sits directly on the backend (no
        # CachingSource below, which would already count bytes_fetched)
        self._count_fetch_bytes = count_fetch_bytes
        # the consumer's logical end (a byte-range shard stops at its
        # bound): read-ahead never schedules past it, so shard streams
        # don't fetch their neighbors' bytes. 0 = whole file
        self._limit = int(limit) if limit > 0 else 0
        self._size = inner.size()
        self._lock = threading.Lock()
        self._blocks: Dict[int, _Block] = {}
        self._pool: Optional[ThreadPoolExecutor] = None
        self._closed = False

    # -- ByteRangeSource surface ----------------------------------------

    def size(self) -> int:
        return self._size

    @property
    def name(self) -> str:
        return self._inner.name

    def fingerprint(self) -> str:
        return self._inner.fingerprint()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pool = self._pool
            self._pool = None
            unused = sum(1 for b in self._blocks.values()
                         if b.prefetched and not b.consumed)
            self._blocks.clear()
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        if unused and self._io_stats is not None:
            self._io_stats.bump("prefetch_unused", unused)
        self._inner.close()

    # -- internals -------------------------------------------------------

    def _block_range(self, idx: int) -> Tuple[int, int]:
        start = idx * self._block
        return start, min(start + self._block, self._size)

    def _last_block(self) -> int:
        end = min(self._size, self._limit) if self._limit else self._size
        return (end - 1) // self._block if end else -1

    def _fetch_range(self, first: int, last: int) -> Dict[int, bytes]:
        """One coalesced inner read covering blocks [first, last],
        re-issued on short reads, split per block."""
        from .blockcache import read_span

        start = first * self._block
        end = min((last + 1) * self._block, self._size)
        data = read_span(self._inner, start, end)
        if self._count_fetch_bytes and self._io_stats is not None:
            self._io_stats.bump("bytes_fetched", len(data))
        out = {}
        for idx in range(first, last + 1):
            bs, be = self._block_range(idx)
            out[idx] = data[bs - start:be - start]
        return out

    def _prefetch_task(self, first: int, last: int) -> None:
        try:
            blocks = self._fetch_range(first, last)
        except BaseException as exc:
            with self._lock:
                for idx in range(first, last + 1):
                    blk = self._blocks.get(idx)
                    if blk is not None and blk.data is None:
                        self._blocks.pop(idx, None)
                        if blk.future is not None \
                                and not blk.future.done():
                            blk.future.set_exception(exc)
            return
        with self._lock:
            for idx, data in blocks.items():
                blk = self._blocks.get(idx)
                if blk is None:
                    continue
                blk.data = data
                if blk.future is not None and not blk.future.done():
                    blk.future.set_result(data)

    def _schedule_ahead(self, after: int) -> None:
        """Queue fetches for the `depth` blocks following `after`;
        consecutive unscheduled blocks go to the pool as one task."""
        last_wanted = min(after + self._depth, self._last_block())
        runs = []  # (first, last) of blocks needing a fetch
        with self._lock:
            if self._closed:
                return
            run_start = None
            for idx in range(after + 1, last_wanted + 1):
                if idx in self._blocks:
                    if run_start is not None:
                        runs.append((run_start, idx - 1))
                        run_start = None
                    continue
                self._blocks[idx] = _Block(future=Future(),
                                           prefetched=True)
                if run_start is None:
                    run_start = idx
            if run_start is not None:
                runs.append((run_start, last_wanted))
            if runs and self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self._depth,
                    thread_name_prefix="cobrix-io-prefetch")
            pool = self._pool
        for first, last in runs:
            try:
                pool.submit(self._prefetch_task, first, last)
            except RuntimeError:  # closed between the lock and the submit
                with self._lock:
                    for idx in range(first, last + 1):
                        self._blocks.pop(idx, None)
                return
            if self._io_stats is not None:
                # counted per BLOCK (coalescing is an implementation
                # detail) so utilization = (issued - unused) / issued
                # stays in consistent units
                self._io_stats.bump("prefetch_issued", last - first + 1)

    def _evict_behind(self, before: int) -> None:
        """Drop completed blocks wholly before `before` (sequential
        consumers never look back; random access refetches)."""
        with self._lock:
            stale = [i for i, b in self._blocks.items()
                     if i < before and b.data is not None]
            # keep the window bounded even under pathological patterns
            if len(self._blocks) > self._depth + 2:
                done = sorted(i for i, b in self._blocks.items()
                              if b.data is not None and b.consumed)
                stale.extend(done[:len(self._blocks)
                                  - (self._depth + 2)])
            unused = 0
            for i in set(stale):
                blk = self._blocks.pop(i, None)
                if blk is not None and blk.prefetched \
                        and not blk.consumed:
                    unused += 1
        if unused and self._io_stats is not None:
            self._io_stats.bump("prefetch_unused", unused)

    def _get_block(self, idx: int) -> bytes:
        future: Optional[Future] = None
        with self._lock:
            blk = self._blocks.get(idx)
            if blk is not None and blk.data is not None:
                if self._io_stats is not None and blk.prefetched \
                        and not blk.consumed:
                    self._io_stats.bump("prefetch_hits")
                blk.consumed = True
                return blk.data
            if blk is not None and blk.future is not None:
                if self._io_stats is not None and blk.prefetched \
                        and not blk.consumed:
                    self._io_stats.bump("prefetch_waits")
                blk.consumed = True
                future = blk.future
            else:
                # sync fetch on the consumer thread (first touch, or a
                # re-read after a failed/evicted prefetch)
                blk = _Block()
                blk.consumed = True
                self._blocks[idx] = blk
        if future is not None:
            # wait outside the lock; on failure the task already removed
            # the block, so the caller's RetryPolicy re-read refetches
            return future.result()
        data = self._fetch_range(idx, idx)[idx]
        with self._lock:
            cur = self._blocks.get(idx)
            if cur is not None:
                cur.data = data
        return data

    def read(self, offset: int, n: int) -> bytes:
        if self._closed:
            raise ValueError(f"read on closed source '{self.name}'")
        if offset >= self._size or n <= 0:
            return b""
        n = min(n, self._size - offset)
        first = offset // self._block
        last = (offset + n - 1) // self._block
        parts = []
        for idx in range(first, last + 1):
            part = self._get_block(idx)
            parts.append(part)
            bs, be = self._block_range(idx)
            if len(part) < be - bs:
                # short backend block (truncated object): joining later
                # blocks would misalign them — serve the short read
                break
        self._schedule_ahead(last)
        self._evict_behind(first)
        data = b"".join(parts)
        lead = offset - first * self._block
        return data[lead:lead + n]
