"""Summarize a cobrix_tpu Chrome-trace file: critical path, per-stage
utilization, straggler table, supervision events.

The trace comes from the `trace_file=` read option (cobrix_tpu.obs) and
opens graphically in chrome://tracing or https://ui.perfetto.dev; this
tool is the terminal view — what took the time, which shard straggled,
what the supervisor did — without leaving the shell.

    python tools/traceview.py scan.trace.json     # summarize a trace
    python tools/traceview.py --fields scan.trace.json
                                                  # per-field cost table
                                                  # (busy_s, bytes, MB/s,
                                                  # % of decode) from a
                                                  # trace whose read ran
                                                  # with field_costs /
                                                  # explain=True, or from
                                                  # any metrics/bench
                                                  # JSON carrying a
                                                  # field_costs table
    python tools/traceview.py --smoke             # self-check: run a
                                                  # small traced scan and
                                                  # assert the summary
                                                  # parses (CI smoke,
                                                  # like pipecheck)
    python tools/traceview.py --smoke --sweep     # + multihost profile
                                                  # (slow; tier-1 runs
                                                  # the quick smoke)

Exit code 0 = summary produced (and, under --smoke, sanity checks hold);
1 = malformed trace or failed smoke assertion.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from collections import defaultdict
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_events(path: str) -> List[dict]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("not a Chrome trace: no traceEvents array")
    return events


def summarize(events: List[dict]) -> dict:
    """Structured summary of one trace (the dict `main` prints)."""
    spans = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    if not spans:
        raise ValueError("trace contains no complete ('X') spans")

    by_id: Dict[int, dict] = {}
    children: Dict[int, List[dict]] = defaultdict(list)
    for e in spans:
        args = e.get("args") or {}
        sid = args.get("span_id")
        if sid is not None:
            by_id[sid] = e
    for e in spans:
        if e.get("cat") == "phase":
            # phase timers (parse_copybook/plan_index/scan) wrap whole
            # sections and would shadow the real chunk/stage chain in
            # the critical-path walk; they still show as lanes in the
            # trace viewer
            continue
        args = e.get("args") or {}
        parent = args.get("parent_id")
        if parent in by_id:
            children[parent].append(e)

    roots = [e for e in spans if e.get("cat") == "scan"]
    root = max(roots, key=lambda e: e.get("dur", 0)) if roots else None
    wall_us = (root["dur"] if root is not None
               else max(e["ts"] + e.get("dur", 0) for e in spans)
               - min(e["ts"] for e in spans))
    wall_s = wall_us / 1e6 if wall_us else 0.0

    # per-stage busy: thread-summed duration by stage name (overlapped
    # stages exceed wall — utilization > 1 means real overlap)
    stage_busy: Dict[str, float] = defaultdict(float)
    for e in spans:
        if e.get("cat") == "stage":
            stage_busy[e["name"]] += e.get("dur", 0) / 1e6
    utilization = {k: round(v / wall_s, 3) if wall_s else 0.0
                   for k, v in stage_busy.items()}

    # straggler table: work units (shards/chunks) by descending duration
    units = [e for e in spans if e.get("cat") in ("shard", "chunk")]
    units.sort(key=lambda e: -e.get("dur", 0))
    stragglers = []
    mean_us = (sum(e.get("dur", 0) for e in units) / len(units)
               if units else 0.0)
    for e in units[:10]:
        args = e.get("args") or {}
        stragglers.append({
            "kind": e.get("cat"),
            "id": args.get("seq", args.get("chunk")),
            "file": args.get("file"),
            "pid": e.get("pid"),
            "dur_s": round(e.get("dur", 0) / 1e6, 6),
            "x_mean": (round(e.get("dur", 0) / mean_us, 2)
                       if mean_us else None),
        })

    # critical path: end-anchored walk from the scan root — at each level
    # follow the child that FINISHED last (the span the wall actually
    # waited on), e.g. scan -> straggler chunk -> its assemble stage
    critical = []
    if root is not None:
        node = root
        while node is not None:
            args = node.get("args") or {}
            critical.append({
                "name": node["name"], "cat": node.get("cat"),
                "id": args.get("seq", args.get("chunk")),
                "dur_s": round(node.get("dur", 0) / 1e6, 6),
                "pid": node.get("pid"),
            })
            kids = children.get(args.get("span_id"), [])
            node = (max(kids, key=lambda e: e["ts"] + e.get("dur", 0))
                    if kids else None)

    sup_events: Dict[str, int] = defaultdict(int)
    for e in instants:
        sup_events[e["name"]] += 1

    return {
        "wall_s": round(wall_s, 6),
        "spans": len(spans),
        "processes": len({e.get("pid") for e in spans}),
        "threads": len({(e.get("pid"), e.get("tid")) for e in spans}),
        "stage_busy_s": {k: round(v, 6)
                         for k, v in sorted(stage_busy.items())},
        "stage_utilization": dict(sorted(utilization.items())),
        "work_units": len(units),
        "stragglers": stragglers,
        "critical_path": critical,
        "supervision_events": dict(sorted(sup_events.items())),
    }


def print_summary(s: dict) -> None:
    print(f"wall {s['wall_s']:.3f}s | {s['spans']} spans | "
          f"{s['processes']} process(es), {s['threads']} thread lane(s) | "
          f"{s['work_units']} work unit(s)")
    if s["stage_busy_s"]:
        print("stage        busy_s    utilization")
        for k in s["stage_busy_s"]:
            print(f"  {k:<10} {s['stage_busy_s'][k]:>8.3f}    "
                  f"{s['stage_utilization'][k]:>5.2f}x")
    if s["critical_path"]:
        chain = " -> ".join(
            f"{n['name']}"
            + (f"[{n['id']}]" if n.get("id") is not None else "")
            + f"({n['dur_s']:.3f}s)"
            for n in s["critical_path"])
        print(f"critical path: {chain}")
    if s["stragglers"]:
        print("top stragglers (kind id dur_s x_mean pid file):")
        for t in s["stragglers"][:5]:
            print(f"  {t['kind']:<6} {str(t['id']):<4} "
                  f"{t['dur_s']:>8.4f}  "
                  f"{t['x_mean'] if t['x_mean'] is not None else '-':>6} "
                  f" {t['pid']}  {t['file'] or ''}")
    if s["supervision_events"]:
        evs = " ".join(f"{k}={v}"
                       for k, v in s["supervision_events"].items())
        print(f"supervision: {evs}")


def find_field_costs(doc) -> Optional[dict]:
    """Locate a per-field cost table ({field -> {busy_s, bytes, ...}})
    in any artifact shape: an explain/metrics dict (`field_costs` key
    at any depth, e.g. bench JSON `read_metrics`), or a Chrome trace
    whose scan-root span args carry it (ReadMetrics.finalize embeds
    the table when attribution ran)."""
    if isinstance(doc, dict):
        fc = doc.get("field_costs")
        if isinstance(fc, dict) and fc and all(
                isinstance(v, dict) and "busy_s" in v
                for v in fc.values()):
            return fc
        events = doc.get("traceEvents")
        if isinstance(events, list):
            for e in events:
                if e.get("cat") == "scan":
                    fc = (e.get("args") or {}).get("field_costs")
                    if isinstance(fc, dict) and fc:
                        return fc
            return None
        for v in doc.values():
            found = find_field_costs(v)
            if found is not None:
                return found
    elif isinstance(doc, list):
        for v in doc:
            found = find_field_costs(v)
            if found is not None:
                return found
    return None


def print_fields(costs: dict, top_n: int = 20) -> None:
    """The per-field cost table: busy seconds split decode/assemble,
    bytes, MB/s, and each field's share of the decode plane — the
    terminal twin of ScanReport.render()'s cost section."""
    rows = sorted(costs.items(), key=lambda kv: -kv[1].get("busy_s", 0))
    decode_total = sum(r.get("decode_s", 0) for _, r in rows)
    print(f"{len(rows)} field(s), decode plane "
          f"{decode_total:.4f}s busy; top {min(top_n, len(rows))}:")
    print(f"{'field':<26} {'kernel':<20} {'busy_s':>8} {'dec_s':>8} "
          f"{'asm_s':>8} {'MB':>8} {'MB/s':>8} {'%decode':>8}")
    for name, r in rows[:top_n]:
        mb = r.get("bytes", 0) / (1024 * 1024)
        busy = r.get("busy_s", 0)
        mbps = mb / busy if busy > 0 else 0.0
        pct = (r.get("decode_s", 0) / decode_total * 100
               if decode_total > 0 else 0.0)
        print(f"{name:<26} {r.get('kernel', ''):<20} {busy:>8.4f} "
              f"{r.get('decode_s', 0):>8.4f} "
              f"{r.get('assemble_s', 0):>8.4f} {mb:>8.2f} "
              f"{mbps:>8.1f} {pct:>7.1f}%")


def _smoke(sweep: bool) -> int:
    """Generate small traced scans and assert the summary parses — the
    end-to-end self-check CI runs (pipecheck/chaoscheck style)."""
    from cobrix_tpu import read_cobol
    from cobrix_tpu.testing.generators import (
        EXP1_COPYBOOK,
        EXP2_COPYBOOK,
        generate_exp1,
        generate_exp2,
    )

    ok = True
    cases = [("exp1_pipelined",
              generate_exp1(600, seed=11).tobytes(),
              dict(copybook_contents=EXP1_COPYBOOK, pipeline_workers="2",
                   chunk_size_mb="0.05"))]
    if sweep:
        cases.append(
            ("exp2_multihost", generate_exp2(4000, seed=11),
             dict(copybook_contents=EXP2_COPYBOOK,
                  is_record_sequence="true", segment_field="SEGMENT-ID",
                  redefine_segment_id_map="STATIC-DETAILS => C",
                  redefine_segment_id_map_1="CONTACTS => P",
                  hosts="2", input_split_records="800")))
    for name, data, kw in cases:
        with tempfile.NamedTemporaryFile(suffix=".dat",
                                         delete=False) as f:
            f.write(data)
            path = f.name
        trace_path = path + ".trace.json"
        try:
            out = read_cobol(path, trace_file=trace_path, **kw)
            summary = summarize(load_events(trace_path))
            print(f"--- {name}: {len(out)} rows")
            print_summary(summary)
            good = bool(summary["spans"] > 0 and summary["wall_s"] > 0
                        and summary["stage_busy_s"]
                        and len(summary["critical_path"]) >= 2)
            if name == "exp2_multihost":
                good &= summary["processes"] >= 3  # parent + 2 workers
            if not good:
                print(f"SMOKE FAILED for {name}: {summary}")
            ok &= good
        finally:
            os.unlink(path)
            if os.path.exists(trace_path):
                os.unlink(trace_path)
    print("OK: traceview smoke passed" if ok
          else "FAILED: traceview smoke")
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="?", help="Chrome-trace JSON to view")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object")
    ap.add_argument("--smoke", action="store_true",
                    help="self-check: run a traced scan and summarize it")
    ap.add_argument("--sweep", action="store_true",
                    help="with --smoke: add the multihost profile (slow)")
    ap.add_argument("--fields", action="store_true",
                    help="render the per-field cost table from the "
                         "artifact (trace or metrics/bench JSON)")
    args = ap.parse_args()
    if args.smoke:
        return _smoke(args.sweep)
    if not args.trace:
        ap.error("a trace file (or --smoke) is required")
    if args.fields:
        try:
            with open(args.trace, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"FAILED: {exc}", file=sys.stderr)
            return 1
        costs = find_field_costs(doc)
        if costs is None:
            print("FAILED: no field_costs table in this artifact (run "
                  "the read with field_costs=true or explain=True)",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(costs))
        else:
            print_fields(costs)
        return 0
    try:
        summary = summarize(load_events(args.trace))
    except (ValueError, OSError, json.JSONDecodeError) as exc:
        print(f"FAILED: {exc}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(summary))
    else:
        print_summary(summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
