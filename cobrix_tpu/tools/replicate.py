"""Test-data replication tool.

The equivalent of the reference's standalone test-data multiplier
(spark-cobol replication/CobolBinaryFilesReplicator.scala:31-75 with
HDFSFileWriter and IncrementalFileIdProvider): copy a set of binary
mainframe files round-robin into a target directory, each copy under a new
incremental file id, until a total byte budget is reached. Used to scale
small golden files up to benchmark-sized datasets.
"""
from __future__ import annotations

import os
import shutil
import threading
from typing import List, Sequence


def replicate_files(source_files: Sequence[str], target_dir: str,
                    target_bytes: int, threads: int = 4) -> List[str]:
    """Replicate `source_files` into `target_dir` until their cumulative
    size reaches `target_bytes`. Returns the created file paths
    (`<stem>_<id><ext>`, ids increasing from 0)."""
    sources = [s for s in source_files if os.path.getsize(s) > 0]
    if not sources:
        raise ValueError("No non-empty source files to replicate")
    if target_bytes <= 0:
        raise ValueError(f"Invalid byte budget {target_bytes}")
    os.makedirs(target_dir, exist_ok=True)

    lock = threading.Lock()
    state = {"bytes": 0, "next_id": 0, "round_robin": 0}
    created: List[str] = []

    def claim():
        """One replication task: (source, target path) or None when the
        budget is spent."""
        with lock:
            if state["bytes"] >= target_bytes:
                return None
            src = sources[state["round_robin"] % len(sources)]
            state["round_robin"] += 1
            state["bytes"] += os.path.getsize(src)
            file_id = state["next_id"]
            state["next_id"] += 1
        stem, ext = os.path.splitext(os.path.basename(src))
        dst = os.path.join(target_dir, f"{stem}_{file_id}{ext}")
        return src, dst

    errors: List[BaseException] = []

    def worker():
        while True:
            with lock:
                if errors:
                    return
            task = claim()
            if task is None:
                return
            src, dst = task
            try:
                shutil.copyfile(src, dst)
            except BaseException as e:
                with lock:
                    errors.append(e)
                return
            with lock:
                created.append(dst)

    pool = [threading.Thread(target=worker) for _ in range(max(1, threads))]
    for t in pool:
        t.start()
    for t in pool:
        t.join()
    if errors:
        raise RuntimeError(
            f"Replication failed after {len(created)} copies") from errors[0]
    return sorted(created)
