"""PR 16's self-healing data plane: routing front + peer cache + chaos.

The fleet pieces individually (registry identity reclaim, router
health rules, peer-tier degradation discipline) and then composed the
way production composes them: a client streaming THROUGH the
`RouteServer` proxy while the preferred replica dies mid-stream. The
matrix crosses that death with fixed-width, variable-length (RDW),
follow-mode, and pushdown scans, and each cell must deliver a table
BYTE-IDENTICAL to an uninterrupted local read — the router's
note_failure + the PR 9 resume token composing exactly-once, with no
SLO double-burn on the resumed attempt. The subprocess chaos harness
(tools/routecheck.py: actuator-owned fleet, SIGKILL under load,
respawn budget) runs here too so tier-1 exercises real process death.
"""
import importlib.util
import json
import os
import shutil
import socket
import threading
import time

import pyarrow as pa
import pytest

from cobrix_tpu import read_cobol
from cobrix_tpu.fleet.registry import (
    LIVE_FACTOR,
    ReplicaRecord,
    ReplicaRegistry,
)
from cobrix_tpu.fleet.router import (
    RouteServer,
    RoutingFront,
    read_router_state,
    route_scan,
)
from cobrix_tpu.io.peercache import PeerCacheTier
from cobrix_tpu.obs.audit import read_audit_log
from cobrix_tpu.serve import ScanServer, fetch_table, stream_scan
from cobrix_tpu.testing.generators import EXP2_COPYBOOK, generate_exp2

from test_resume import _CuttingProxy
from util import hard_timeout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

COPYBOOK = """
        01  R.
            05  KEY    PIC 9(7) COMP.
            05  NAME   PIC X(9).
"""
OPTS = dict(copybook_contents=COPYBOOK, chunk_size_mb="0.05",
            pipeline_workers="2")
EXP2_OPTS = dict(copybook_contents=EXP2_COPYBOOK,
                 is_record_sequence="true",
                 segment_field="SEGMENT-ID",
                 redefine_segment_id_map="STATIC-DETAILS => C",
                 **{"redefine_segment_id_map:1": "CONTACTS => P"})


def make_records(n: int) -> bytes:
    return b"".join(
        i.to_bytes(4, "big") + f"ROW{i % 1000000:06d}".encode("ascii")
        for i in range(n))


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rec(rid: str, port: int = 0, **kw) -> ReplicaRecord:
    now = time.time()
    defaults = dict(replica_id=rid, pid=os.getpid(), host="t",
                    scan_address=["127.0.0.1", port],
                    started_at=now - 10, heartbeat_at=now,
                    interval_s=60.0)
    defaults.update(kw)
    return ReplicaRecord(**defaults)


# ---------------------------------------------------------------------------
# registry: same-id restart reclaims the heartbeat as ONE member
# ---------------------------------------------------------------------------

def test_same_id_restart_reclaims_one_member(tmp_path):
    """A replica that restarts under its old identity before the old
    heartbeat expires must read as ONE live member carrying the NEW
    endpoints — a live+stale pair double-counts capacity and routes
    traffic onto a dead port."""
    reg = ReplicaRegistry(str(tmp_path / "fleet"), interval_s=60)
    reg.write(_rec("alpha", 1001, pid=111))
    # a second FILE claiming the same replica_id (a stranded record
    # from before a node rename; sorts BEFORE the canonical file so
    # listing order cannot be what saves us)
    stray = os.path.join(reg.replica_dir, "0-alpha-stray.json")
    shutil.copy(reg.path_for("alpha"), stray)
    past = time.time() - 20
    os.utime(stray, (past, past))
    # the restart: same id, new pid + port, fresher mtime
    reg.write(_rec("alpha", 2002, pid=222))
    statuses = reg.read()
    assert len(statuses) == 1, [s.record.replica_id for s in statuses]
    assert statuses[0].record.pid == 222
    assert statuses[0].record.scan_address == ["127.0.0.1", 2002]
    assert statuses[0].state == "live"
    # flipped freshness: when the stray is the NEWER record it wins —
    # mtime decides, not file name
    future = time.time() + 5
    os.utime(stray, (future, future))
    statuses = reg.read()
    assert len(statuses) == 1
    assert statuses[0].record.pid == 111


# ---------------------------------------------------------------------------
# routing front: health rules, affinity, failure cooldown, publication
# ---------------------------------------------------------------------------

def _front(fleet: str, **kw) -> RoutingFront:
    kw.setdefault("slo_aware", False)
    kw.setdefault("publish_interval_s", 0.0)
    return RoutingFront(fleet, **kw)


def test_health_rules_order_healthy_first_unhealthy_tail(tmp_path):
    fleet = str(tmp_path / "fleet")
    reg = ReplicaRegistry(fleet)
    reg.write(_rec("a", 1001))
    reg.write(_rec("b", 1002))
    reg.write(_rec("drainer", 1003, draining=True))
    reg.write(_rec("shedder", 1004, pressure="shed"))
    reg.write(_rec("ghost", 1005))
    p = reg.path_for("ghost")
    old = time.time() - 60.0 * (LIVE_FACTOR + 1)
    os.utime(p, (old, old))
    front = _front(fleet)
    out = front.replicas_for(["f.dat"])
    ids = [rid for rid, _ in out]
    # healthy lead; degraded-but-alive next; transport-suspect LAST;
    # nothing is ever dropped (an all-degraded fleet still routes)
    assert set(ids[:2]) == {"a", "b"}
    assert set(ids[2:4]) == {"drainer", "shedder"}
    assert ids[4] == "ghost"
    assert out == front.replicas_for(["f.dat"])  # deterministic
    st = front.state()
    assert st["decisions"] == 2
    assert st["around"]["drainer"] == {"draining": 2}
    assert st["around"]["shedder"] == {"memory_shed": 2}
    assert st["around"]["ghost"] == {"stale_heartbeat": 2}
    assert st["routed"][ids[0]] == 2


def test_affinity_overrides_hash_and_counts_hits(tmp_path):
    fleet = str(tmp_path / "fleet")
    reg = ReplicaRegistry(fleet)
    for rid, port in (("a", 1001), ("b", 1002), ("c", 1003)):
        heat = ([{"key": "file:/data/f.dat", "count": 7}]
                if rid == "c" else [])
        reg.write(_rec(rid, port, heat=heat))
    front = _front(fleet)
    out = front.replicas_for(["/data/f.dat"])
    assert out[0][0] == "c"  # the warm replica leads, hash or not
    assert front.state()["affinity_hits"] == 1
    # a DIFFERENT file has no heat anywhere: pure rendezvous, no hit
    front.replicas_for(["/data/other.dat"])
    assert front.state()["affinity_hits"] == 1


def test_failure_cooldown_beats_heartbeat_then_recovers(tmp_path):
    fleet = str(tmp_path / "fleet")
    reg = ReplicaRegistry(fleet)
    reg.write(_rec("a", 1001))
    reg.write(_rec("b", 1002))
    front = _front(fleet, failure_cooldown_s=0.3)
    first = front.replicas_for(["f.dat"])[0][0]
    # the router watched first's stream die: instantly tail-ranked,
    # long before its (still fresh) heartbeat could say anything
    front.note_failure(first)
    out = front.replicas_for(["f.dat"])
    assert out[0][0] != first and out[-1][0] == first
    assert front.state()["around"][first] == {"recent_failure": 1}
    assert front.state()["failures"][first] == 1
    time.sleep(0.35)  # cooldown expires -> re-earns its slot
    assert front.replicas_for(["f.dat"])[0][0] == first


def test_router_state_publishes_crc_stamped_and_survives_garbage(
        tmp_path):
    fleet = str(tmp_path / "fleet")
    reg = ReplicaRegistry(fleet)
    reg.write(_rec("a", 1001))
    front = _front(fleet, router_id="r-test")
    front.replicas_for(["f.dat"])
    front.publish()
    docs = read_router_state(fleet)
    assert [d["router_id"] for d in docs] == ["r-test"]
    assert docs[0]["decisions"] == 1
    # a torn/corrupt record reads as ABSENT, never as a phantom router
    rdir = os.path.join(fleet, "router")
    torn = os.path.join(rdir, "torn.json")
    with open(torn, "w") as f:
        f.write('{"router_id": "evil", "decisions": 9')
    doc = json.load(open(os.path.join(rdir, "r-test.json")))
    doc["decisions"] = 999  # valid JSON, stale CRC
    with open(os.path.join(rdir, "forged.json"), "w") as f:
        json.dump(doc, f)
    assert [d["router_id"] for d in read_router_state(fleet)] \
        == ["r-test"]


def test_route_scan_refuses_an_empty_fleet(tmp_path):
    with pytest.raises(ConnectionError):
        route_scan(str(tmp_path / "nofleet"), "f.dat",
                   copybook_contents=COPYBOOK)


# ---------------------------------------------------------------------------
# peer cache tier: degradation discipline
# ---------------------------------------------------------------------------

def _dead_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_peer_failure_is_a_miss_never_an_error():
    """A refused peer must read as a cache MISS (the caller proceeds
    to the backend) and enter cooldown so the NEXT miss skips it."""
    port = _dead_port()
    tier = PeerCacheTier(lambda: [("dead", ("127.0.0.1", port))],
                         timeout_s=0.5, cooldown_s=30.0)
    assert tier.fetch("memory://x", "fp", 0, 128) is None
    assert tier.stats.get("miss") == 1
    t0 = time.monotonic()
    assert tier.fetch("memory://x", "fp", 0, 128) is None
    # the cooled-down peer was never dialed: instant miss
    assert time.monotonic() - t0 < 0.2
    assert tier.stats.get("miss") == 2


def test_peer_corrupt_frame_is_quarantined_to_a_miss():
    """A peer whose reply fails the traveling CRC delivers NOTHING to
    the caller — the corrupt bytes become a miss + cooldown, and the
    tier's ledger says 'corrupt', not 'hit'."""
    from cobrix_tpu.serve.protocol import (FRAME_DATA, FRAME_FINAL,
                                           read_frame, write_frame,
                                           write_json_frame)

    def liar(conn):
        rf = conn.makefile("rb")
        wf = conn.makefile("wb")
        read_frame(rf)  # the peer_block request
        write_frame(wf, FRAME_DATA, b"\x00" * 64)  # not framed bytes
        write_json_frame(wf, FRAME_FINAL, {"found": True})
        wf.flush()
        conn.close()

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    addr = srv.getsockname()

    def accept():
        conn, _ = srv.accept()
        liar(conn)

    t = threading.Thread(target=accept, daemon=True)
    t.start()
    try:
        tier = PeerCacheTier(lambda: [("liar", tuple(addr))],
                             timeout_s=2.0, cooldown_s=30.0)
        assert tier.fetch("memory://x", "fp", 0, 64) is None
        assert tier.stats.get("corrupt") == 1
    finally:
        srv.close()


def test_cold_miss_answered_from_warm_peer(tmp_path):
    """Two fleet replicas with SEPARATE cache roots: replica B's first
    scan of a file replica A already cached is answered from A's disk
    over the serve protocol — visible as a peer HIT on B's tier and a
    peer-served HIT on A, distinguishable from local block-cache hits."""
    import fsspec

    from cobrix_tpu.obs.metrics import scan_metrics, serve_metrics

    with hard_timeout(180, "peer cache tier"):
        fleet = str(tmp_path / "fleet")
        raw = make_records(5000)
        fs = fsspec.filesystem("memory")
        with fs.open("/peer-tier/f.dat", "wb") as f:
            f.write(raw)
        url = "memory://peer-tier/f.dat"
        servers = [
            ScanServer(enable_http=False, fleet=True,
                       replica_id=f"pc-{i}", fleet_dir=fleet,
                       heartbeat_interval_s=0.2,
                       server_options={"cache_dir": str(
                           tmp_path / f"cache{i}")}).start()
            for i in range(2)]
        try:
            reg = ReplicaRegistry(fleet)
            deadline = time.monotonic() + 15
            while (len(reg.read()) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            local = read_cobol(url, **OPTS).to_arrow()
            served_before = serve_metrics()["peer_served"] \
                .value(result="hit")
            hits_before = scan_metrics()["peer_cache"] \
                .value(result="hit")
            # A scans cold (backend miss -> A's cache warms) ...
            assert fetch_table(servers[0].address, url,
                               **OPTS).equals(local)
            # ... B's cold scan hits A's cache instead of the backend
            assert fetch_table(servers[1].address, url,
                               **OPTS).equals(local)
            tier_b = servers[1]._peer_cache_host.peer_tier
            assert tier_b.stats.get("hit", 0) >= 1, tier_b.stats
            # /metrics keeps peer hits distinguishable from local hits
            assert scan_metrics()["peer_cache"].value(
                result="hit") > hits_before
            assert serve_metrics()["peer_served"].value(
                result="hit") > served_before
        finally:
            for srv in servers:
                srv.stop()
            try:
                fs.rm("/peer-tier", recursive=True)
            except Exception:
                pass


# ---------------------------------------------------------------------------
# the composition: routed failover x {fixed, VRL, follow, pushdown}
# ---------------------------------------------------------------------------

@pytest.fixture()
def routed_fleet(tmp_path):
    """Two real fleet replicas + a 'lure' pseudo-replica whose scan
    address is a cutting proxy in front of replica 1. Heat pins the
    routed scan onto the lure, the proxy kills it mid-stream, and the
    client's resume must ride the router around the corpse."""
    fleet = str(tmp_path / "fleet")
    audits = [str(tmp_path / f"audit{i}.log") for i in range(2)]
    servers = [
        ScanServer(enable_http=False, fleet=True,
                   replica_id=f"real-{i}", fleet_dir=fleet,
                   heartbeat_interval_s=0.2,
                   audit_log=audits[i],
                   slos=["first_batch_p99=0.000001",
                         "error_rate=0.5"],
                   server_options={"cache_dir": str(
                       tmp_path / f"cache{i}")}).start()
        for i in range(2)]
    reg = ReplicaRegistry(fleet)
    deadline = time.monotonic() + 15
    while len(reg.read()) < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    state = {"proxies": []}

    def lure(path: str, cut_after: int):
        proxy = _CuttingProxy(servers[0].address, cut_after)
        state["proxies"].append(proxy)
        reg.write(_rec("lure", proxy.address[1], interval_s=120.0,
                       heat=[{"key": f"file:{path}", "count": 9}]))
        return proxy

    front = RoutingFront(fleet, slo_aware=False,
                         failure_cooldown_s=60.0,
                         publish_interval_s=0.0)
    router = RouteServer(front=front).start()
    try:
        yield {"servers": servers, "registry": reg, "front": front,
               "router": router, "lure": lure, "audits": audits}
    finally:
        router.stop()
        for proxy in state["proxies"]:
            proxy.stop()
        for srv in servers:
            srv.stop()


def _assert_failed_over_around_lure(front, stream):
    assert stream.failovers >= 1, "the cut never landed mid-stream"
    st = front.state()
    assert st["failures"].get("lure", 0) >= 1, st
    assert "recent_failure" in st["around"].get("lure", {}), st


def test_routed_failover_fixed_width(routed_fleet, tmp_path):
    """The tentpole composition: a client holding ONE address (the
    router) streams a fixed-width scan; the preferred replica dies
    mid-stream; reconnecting to the same router routes around the
    corpse and the resume token finishes the scan byte-identically —
    and the resumed audit record burns no SLO twice."""
    with hard_timeout(300, "routed fixed failover"):
        path = str(tmp_path / "fixed.dat")
        with open(path, "wb") as f:
            f.write(make_records(40_000))
        local = read_cobol(path, **OPTS).to_arrow()
        routed_fleet["lure"](path, cut_after=64 * 1024)
        front = routed_fleet["front"]
        with stream_scan(routed_fleet["router"].address, path,
                         **OPTS) as stream:
            table = pa.Table.from_batches(list(stream))
        _assert_failed_over_around_lure(front, stream)
        assert table.equals(local)
        assert table.schema.metadata == local.schema.metadata
        # the resumed attempt ties to the original via resume_of and
        # carries NO slo_breaches despite the impossibly tight
        # first-batch objective: resumes never double-burn
        original = stream.request_id
        deadline = time.monotonic() + 10
        done = []
        while time.monotonic() < deadline and not done:
            records = [r for a in routed_fleet["audits"]
                       if os.path.exists(a)
                       for r in read_audit_log(a)]
            done = [r for r in records
                    if r.resume_of == original and r.outcome == "ok"]
            if not done:
                time.sleep(0.05)
        assert done
        assert all(not r.slo_breaches for r in done)


def test_routed_failover_variable_length(routed_fleet, tmp_path):
    """Same death, RDW-framed variable-length records: the resume
    watermark must cut on RECORD boundaries the VRL reader re-finds."""
    with hard_timeout(300, "routed VRL failover"):
        path = str(tmp_path / "vrl.dat")
        with open(path, "wb") as f:
            f.write(generate_exp2(6000, seed=11))
        opts = dict(EXP2_OPTS, chunk_size_mb="0.05",
                    pipeline_workers="2")
        local = read_cobol(path, **opts).to_arrow()
        routed_fleet["lure"](path, cut_after=48 * 1024)
        with stream_scan(routed_fleet["router"].address, path,
                         **opts) as stream:
            table = pa.Table.from_batches(list(stream))
        _assert_failed_over_around_lure(routed_fleet["front"], stream)
        assert table.equals(local)


def test_routed_failover_follow_exactly_once(routed_fleet, tmp_path):
    """A follow subscription through the router: the watermark token
    must seed the resumed subscription on the next-preferred replica —
    every record exactly once, none duplicated across the cut."""
    with hard_timeout(300, "routed follow failover"):
        path = str(tmp_path / "feed.dat")
        total = 3000
        with open(path, "wb") as f:
            f.write(make_records(total))
        local = read_cobol(path, copybook_contents=COPYBOOK).to_arrow()
        routed_fleet["lure"](path, cut_after=20_000)
        stream = stream_scan(
            routed_fleet["router"].address, path,
            copybook_contents=COPYBOOK,
            follow={"poll_interval_s": 0.02, "idle_timeout_s": 5.0,
                    "batch_max_mb": 0.005},
            max_records=total)
        table = pa.Table.from_batches(list(stream))
        _assert_failed_over_around_lure(routed_fleet["front"], stream)
        assert table.num_rows == total
        got = table.replace_schema_metadata(None)
        want = local.replace_schema_metadata(None)
        assert got.equals(want)


def test_routed_failover_pushdown(routed_fleet, tmp_path):
    """Projection + predicate pushdown across the routed cut: the
    resume token's plan fingerprint includes the filter, so the
    resumed attempt continues the FILTERED row sequence."""
    with hard_timeout(300, "routed pushdown failover"):
        path = str(tmp_path / "filt.dat")
        with open(path, "wb") as f:
            f.write(make_records(40_000))
        opts = dict(OPTS, filter="KEY < 30000", select="KEY")
        local = read_cobol(path, **opts).to_arrow()
        routed_fleet["lure"](path, cut_after=32 * 1024)
        with stream_scan(routed_fleet["router"].address, path,
                         **opts) as stream:
            table = pa.Table.from_batches(list(stream))
        _assert_failed_over_around_lure(routed_fleet["front"], stream)
        assert table.num_rows == local.num_rows
        assert table.equals(local)


# ---------------------------------------------------------------------------
# subprocess chaos: actuator-owned fleet, SIGKILL under load
# ---------------------------------------------------------------------------

def test_routecheck_quick():
    """The routed chaos harness end to end: 3 actuator-owned replica
    subprocesses, warm-affinity beats cold, SIGKILL mid-routed-stream
    with byte-identical resume, respawn within 2 heartbeats, identity
    reclaim, zero orphans."""
    routecheck = _load_tool("routecheck")
    with hard_timeout(420, "routecheck quick"):
        assert routecheck.check_route(sweep=False)


@pytest.mark.slow
def test_routecheck_sweep():
    """Chaos fuzz: several kill-under-load rounds with re-warm between
    them — the fleet must regain affinity and survive every round."""
    routecheck = _load_tool("routecheck")
    with hard_timeout(900, "routecheck sweep"):
        assert routecheck.check_route(sweep=True)
