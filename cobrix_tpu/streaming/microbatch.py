"""Micro-batch streaming reads (the original `streaming.py` surface).

The equivalent of the reference's experimental DStream integration
(`CobolStreamer.cobolStream`, spark-cobol
source/streaming/CobolStreamer.scala:42-82): fixed-length records arrive
as a stream — either an iterable of byte chunks (sockets, queues) or new
files appearing in a directory (the `binaryRecordsStream` semantic) — and
each micro-batch is decoded with the standard fixed-length reader into a
`CobolData` batch. Record_Id numbering continues monotonically across
batches so re-assembled streams stay reproducible.

For live, growing, rotating sources with crash recovery, use the
production ingestion layer (`streaming.ingest.ContinuousIngestor`) —
this module consumes whole files exactly once per process lifetime and
keeps its only state in memory.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Iterable, Iterator, Optional

from ..api import CobolData, list_input_files, parse_options
from ..reader.fixed_len_reader import FixedLenReader
from ..reader.schema import CobolOutputSchema

_logger = logging.getLogger(__name__)

# per-file read granularity for stream_directory: files above this
# stream as several record-aligned batches instead of one whole-file
# read, bounding peak memory at ~one chunk + its decoded columns
DIRECTORY_CHUNK_BYTES = 64 * 1024 * 1024

# how long a size-stable file whose length is NOT a whole number of
# records may sit before it is consumed under the record-error policy
# anyway (a slow writer paused mid-record gets this long to finish; a
# junk file can starve at most this long before it surfaces)
NONDIVISIBLE_GRACE_S = 1.0


class CobolStreamer:
    """Decode a stream of fixed-length COBOL records in micro-batches.

    Options are the standard `read_cobol` option keys (record layout,
    schema policy, generate_record_id, ...). Variable-length streams are
    not supported, matching the reference (CobolStreamer.scala uses the
    fixed-length reader only).
    """

    def __init__(self, copybook_contents, backend: str = "numpy", **options):
        params, _ = parse_options(options, streaming=True)
        if params.is_record_sequence:
            raise ValueError(
                "Streaming supports fixed-length records only "
                "(like the reference's CobolStreamer)")
        self.backend = backend
        self.reader = FixedLenReader(copybook_contents, params)
        self.params = params
        self._schema = CobolOutputSchema(
            self.reader.copybook,
            policy=params.schema_policy,
            input_file_name_field=params.input_file_name_column,
            generate_record_id=params.generate_record_id,
            corrupt_record_field=params.corrupt_record_column)
        self._next_record_id = 0

    @property
    def record_size(self) -> int:
        return self.reader.record_size

    def _batch(self, data, file_id: int = 0,
               input_file_name: str = "",
               whole_file: bool = True) -> CobolData:
        result = self.reader.read_result(
            data, backend=self.backend, file_id=file_id,
            first_record_id=self._next_record_id,
            input_file_name=input_file_name)
        # advance by records CONSUMED (file header/footer regions are not
        # records), independent of rows emitted
        body = len(data) - (
            (self.params.file_start_offset + self.params.file_end_offset)
            if whole_file else 0)
        self._next_record_id += max(body, 0) // self.record_size
        data_out = CobolData.from_results([result], self._schema)
        data_out.diagnostics = result.diagnostics
        return data_out

    # -- chunked byte stream ------------------------------------------------

    def stream_chunks(self, chunks: Iterable[bytes]) -> Iterator[CobolData]:
        """One decoded batch per incoming chunk (chunks need not align to
        record boundaries; partial records carry over)."""
        if self.params.file_start_offset or self.params.file_end_offset:
            # a chunk stream has no file boundaries: there is no "file
            # header/footer" to trim, and _batch would subtract the offsets
            # from every micro-batch (mis-sizing the divisibility check and
            # the record-id advance). Offsets stay valid for
            # stream_directory, where each file genuinely has them.
            raise ValueError(
                "Options 'file_start_offset'/'file_end_offset' cannot be "
                "used with stream_chunks; use stream_directory for files "
                "with headers/footers")
        rs = self.record_size
        # carried partial-record bytes accumulate in a LIST joined once
        # per emitted batch: the old `pending += chunk` rebuilt the whole
        # buffer per incoming chunk — O(n^2) over a chunky stream
        parts = []
        pending_len = 0
        for chunk in chunks:
            if not chunk:
                continue
            parts.append(bytes(chunk))
            pending_len += len(parts[-1])
            usable = pending_len - (pending_len % rs)
            if usable == 0:
                continue
            buf = b"".join(parts)
            data, remainder = buf[:usable], buf[usable:]
            parts = [remainder] if remainder else []
            pending_len = len(remainder)
            yield self._batch(data)
        if pending_len:
            raise ValueError(
                f"Stream ended mid-record: {pending_len} trailing bytes "
                f"(record size {rs})")

    # -- directory watching -------------------------------------------------

    def stream_directory(self, path, poll_interval: float = 1.0,
                         max_batches: Optional[int] = None,
                         idle_timeout: Optional[float] = None
                         ) -> Iterator[CobolData]:
        """Yield batches as new files appear under `path` (the
        `binaryRecordsStream` micro-batch semantic; files larger than
        ~64 MB stream as several record-aligned batches). Stops after
        `max_batches` files, or after `idle_timeout` seconds without new
        files (None = poll forever).

        A file is consumed only once its size is stable across two
        polls (an in-progress write is left for the next poll) and is
        marked consumed only after a successful decode. A stable file
        whose size is NOT a whole number of records gets
        `NONDIVISIBLE_GRACE_S` seconds for its writer to finish, then
        is consumed anyway and handled by the ``record_error_policy``
        — fail_fast raises the reader's divisibility error, permissive
        policies ledger the partial tail — instead of being silently
        skipped forever."""
        consumed = set()
        pending_sizes = {}
        nondivisible_since = {}
        produced = 0
        batches = 0
        idle_since = time.monotonic()
        while True:
            listing_ok = True
            try:
                files = list_input_files(path)
            except FileNotFoundError:
                # directory/glob not there (not created yet, or a
                # transiently unmounted volume) — keep polling, and do
                # NOT shrink bookkeeping off an empty failed listing:
                # wiping `consumed` here would re-deliver every file
                # when the mount comes back
                files = []
                listing_ok = False
            listed = set(files)
            if listing_ok:
                # files that left the listing can never be consumed
                # again: drop their bookkeeping so a long-lived watcher
                # over a rotating directory holds O(current files)
                # state, not O(everything ever seen)
                consumed &= listed
                for stale in [f for f in pending_sizes
                              if f not in listed]:
                    pending_sizes.pop(stale, None)
                    nondivisible_since.pop(stale, None)
            progressed = False
            for f in files:
                if f in consumed:
                    continue
                try:
                    size = os.path.getsize(f)
                except OSError:
                    continue  # vanished between listing and stat
                if pending_sizes.get(f) != size:
                    pending_sizes[f] = size  # new or still growing
                    nondivisible_since.pop(f, None)
                    continue
                body = (size - self.params.file_start_offset
                        - self.params.file_end_offset)
                if body % self.record_size != 0:
                    # stable but mid-record: give the writer a bounded
                    # grace to finish, then consume it under the record
                    # error policy — a junk file must surface through
                    # the ledger (or raise), never starve silently
                    first = nondivisible_since.setdefault(
                        f, time.monotonic())
                    if time.monotonic() - first < NONDIVISIBLE_GRACE_S:
                        continue
                    _logger.warning(
                        "streamed file %s is size-stable at %d bytes, "
                        "which is not a whole number of %d-byte "
                        "records; consuming it under "
                        "record_error_policy=%s", f, size,
                        self.record_size,
                        self.params.record_error_policy.name.lower())
                emitted = yield from self._stream_file(f, produced, size)
                consumed.add(f)
                pending_sizes.pop(f, None)
                nondivisible_since.pop(f, None)
                produced += 1
                batches += emitted
                progressed = True
                idle_since = time.monotonic()
                if max_batches is not None and produced >= max_batches:
                    return
            if not progressed:
                if (idle_timeout is not None
                        and time.monotonic() - idle_since >= idle_timeout):
                    return
            time.sleep(poll_interval)

    def _stream_file(self, f: str, file_id: int, size: int):
        """One file -> one or more batches; whole-file reads go through
        a zero-copy mmap view, oversized files stream in record-aligned
        chunks (both bound peak memory, replacing the old unbounded
        `fh.read()`). Returns the number of batches emitted."""
        from ..reader.stream import open_stream

        rs = self.record_size
        chunkable = (size > DIRECTORY_CHUNK_BYTES
                     and not self.params.file_start_offset
                     and not self.params.file_end_offset
                     and size % rs == 0)
        if not chunkable:
            with open_stream(f) as stream:
                data = stream.next_view(size)
            yield self._batch(data, file_id=file_id, input_file_name=f)
            return 1
        chunk_bytes = max(rs, (DIRECTORY_CHUNK_BYTES // rs) * rs)
        emitted = 0
        with open_stream(f) as stream:
            done = 0
            while done < size:
                data = stream.next_view(min(chunk_bytes, size - done))
                if not data:
                    break
                yield self._batch(data, file_id=file_id,
                                  input_file_name=f, whole_file=False)
                done += len(data)
                emitted += 1
        return emitted


def stream_cobol(copybook_contents, chunks: Iterable[bytes],
                 backend: str = "numpy", **options) -> Iterator[CobolData]:
    """Functional shorthand: decode an iterable of byte chunks."""
    return CobolStreamer(copybook_contents, backend=backend,
                         **options).stream_chunks(chunks)
