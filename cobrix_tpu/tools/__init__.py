"""Standalone operational tools (the reference's `replication/` package)."""
from .replicate import replicate_files

__all__ = ["replicate_files"]
