"""Output schema: COBOL AST -> columnar/nested schema.

Mirrors the reference AST->Spark StructType mapping
(spark-cobol schema/CobolSchema.scala:77-243): Decimal->decimal(p,s) with
effective precision/scale, COMP-1/2->float/double, Integral->int/long/decimal
by precision buckets, RAW->binary, OCCURS->array, hierarchical child segments
nested as arrays of structs, generated fields prepended.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional

from ..copybook.ast import Group, Primitive
from ..copybook.copybook import Copybook
from ..copybook.datatypes import (
    AlphaNumeric,
    Decimal,
    Encoding,
    FILE_ID_FIELD,
    Integral,
    MAX_INTEGER_PRECISION,
    MAX_LONG_PRECISION,
    RECORD_ID_FIELD,
    SEGMENT_ID_FIELD,
    SchemaRetentionPolicy,
    Usage,
)


@dataclass
class Field:
    name: str
    dtype: "DataType"
    nullable: bool = True


@dataclass
class StructType:
    fields: List[Field] = dc_field(default_factory=list)

    def to_json_dict(self):
        return {"type": "struct",
                "fields": [{"name": f.name, "type": _type_json(f.dtype),
                            "nullable": f.nullable, "metadata": {}}
                           for f in self.fields]}

    def to_json(self) -> str:
        return json.dumps(self.to_json_dict(), separators=(",", ":"))

    def field_names(self) -> List[str]:
        return [f.name for f in self.fields]


@dataclass
class ArrayType:
    element: "DataType"
    contains_null: bool = True


@dataclass
class SimpleType:
    name: str  # string|integer|long|float|double|binary|decimal(p,s)


DataType = object


def _type_json(t):
    if isinstance(t, SimpleType):
        return t.name
    if isinstance(t, StructType):
        return t.to_json_dict()
    if isinstance(t, ArrayType):
        return {"type": "array", "elementType": _type_json(t.element),
                "containsNull": t.contains_null}
    raise TypeError(t)


STRING = SimpleType("string")
INTEGER = SimpleType("integer")
LONG = SimpleType("long")
FLOAT = SimpleType("float")
DOUBLE = SimpleType("double")
BINARY = SimpleType("binary")


def decimal_type(precision: int, scale: int) -> SimpleType:
    return SimpleType(f"decimal({precision},{scale})")


def primitive_data_type(p: Primitive):
    """reference CobolSchema.parsePrimitive (schema/CobolSchema.scala:144-173)."""
    dt = p.dtype
    if isinstance(dt, Decimal):
        if dt.usage is Usage.COMP1:
            return FLOAT
        if dt.usage is Usage.COMP2:
            return DOUBLE
        return decimal_type(dt.effective_precision, dt.effective_scale)
    if isinstance(dt, AlphaNumeric):
        return BINARY if dt.enc is Encoding.RAW else STRING
    if isinstance(dt, Integral):
        if dt.precision > MAX_LONG_PRECISION:
            return decimal_type(dt.precision, 0)
        if dt.precision > MAX_INTEGER_PRECISION:
            return LONG
        return INTEGER
    raise TypeError(f"Unknown AST object {dt!r}")


def output_schema_for(copybook, params, is_var_len: bool
                      ) -> "CobolOutputSchema":
    """The read's output schema from one (copybook, parameters) pair —
    the SINGLE construction every layer shares (api single-host and
    multihost paths, the readers' generic filter path, the dataset
    schema probe), so the schema a pre-built table was assembled under
    can never drift from the one the API layer asks for. Seg_Id
    columns exist only on the variable-length path (the reference
    fixed-length reader never generates them), hence `is_var_len`."""
    seg_count = (len(params.multisegment.segment_level_ids)
                 if params.multisegment and is_var_len else 0)
    return CobolOutputSchema(
        copybook,
        policy=params.schema_policy,
        input_file_name_field=params.input_file_name_column,
        generate_record_id=params.generate_record_id,
        generate_seg_id_field_count=seg_count,
        segment_id_prefix="",
        corrupt_record_field=params.corrupt_record_column)


class CobolOutputSchema:
    """Nested and flat output schemas + generated-field bookkeeping
    (reference reader/schema/CobolSchema.scala:38-76 and
    spark-cobol schema/CobolSchema.scala)."""

    def __init__(self,
                 copybook: Copybook,
                 policy: SchemaRetentionPolicy = SchemaRetentionPolicy.KEEP_ORIGINAL,
                 input_file_name_field: str = "",
                 generate_record_id: bool = False,
                 generate_seg_id_field_count: int = 0,
                 segment_id_prefix: str = "",
                 corrupt_record_field: str = ""):
        self.copybook = copybook
        self.policy = policy
        self.input_file_name_field = input_file_name_field
        self.generate_record_id = generate_record_id
        self.generate_seg_id_field_count = generate_seg_id_field_count
        self.segment_id_prefix = segment_id_prefix
        # optional trailing debug column: corruption reason per kept
        # malformed row, null for clean rows (Spark's
        # columnNameOfCorruptRecord analogue)
        self.corrupt_record_field = corrupt_record_field
        self._schema: Optional[StructType] = None

    @property
    def schema(self) -> StructType:
        if self._schema is None:
            self._schema = self._create_schema()
        return self._schema

    def _create_schema(self) -> StructType:
        redefines = self.copybook.get_all_segment_redefines()
        records = [self._parse_group(g, redefines)
                   for g in self.copybook.ast.children if isinstance(g, Group)]
        if self.policy is SchemaRetentionPolicy.COLLAPSE_ROOT:
            expanded: List[Field] = []
            for rec in records:
                expanded.extend(rec.dtype.fields if isinstance(rec.dtype, StructType)
                                else [rec])
            records = expanded
        if self.generate_seg_id_field_count > 0:
            seg_fields = [Field(f"{SEGMENT_ID_FIELD}{lvl}", STRING, True)
                          for lvl in range(self.generate_seg_id_field_count)]
            records = seg_fields + records
        if self.input_file_name_field:
            records = [Field(self.input_file_name_field, STRING, True)] + records
        if self.generate_record_id:
            records = [Field(FILE_ID_FIELD, INTEGER, False),
                       Field(RECORD_ID_FIELD, LONG, False)] + records
        if self.corrupt_record_field:
            records = records + [Field(self.corrupt_record_field, STRING,
                                       True)]
        return StructType(records)

    def _parse_group(self, group: Group, segment_redefines: List[Group]) -> Field:
        fields: List[Field] = []
        for child in group.children:
            if child.is_filler:
                continue
            if isinstance(child, Group):
                if child.parent_segment is None:
                    fields.append(self._parse_group(child, segment_redefines))
            else:
                dt = primitive_data_type(child)
                if child.is_array:
                    fields.append(Field(child.name, ArrayType(dt)))
                else:
                    fields.append(Field(child.name, dt))
        # child segments become nested arrays of structs
        for segment in segment_redefines:
            if (segment.parent_segment is not None
                    and segment.parent_segment.name.upper() == group.name.upper()):
                child_struct = self._parse_group(segment, segment_redefines)
                fields.append(Field(segment.name,
                                    ArrayType(child_struct.dtype)))
        if group.is_array:
            return Field(group.name, ArrayType(StructType(fields)))
        return Field(group.name, StructType(fields))

    # -- flat schema (reference parseGroupFlat) -------------------------------

    def flat_schema(self) -> StructType:
        fields: List[Field] = []
        for record in self.copybook.ast.children:
            if isinstance(record, Group):
                fields.extend(self._parse_group_flat(record, f"{record.name}_"))
        return StructType(fields)

    def _parse_group_flat(self, group: Group, path: str) -> List[Field]:
        fields: List[Field] = []
        for child in group.children:
            if child.is_filler:
                continue
            if isinstance(child, Group):
                if child.is_array:
                    for i in range(1, child.array_max_size + 1):
                        fields.extend(self._parse_group_flat(
                            child, f"{path}{child.name}_{i}_"))
                else:
                    fields.extend(self._parse_group_flat(child, f"{path}{child.name}_"))
            else:
                dt = self._flat_primitive_type(child)
                if child.is_array:
                    for i in range(1, child.array_max_size + 1):
                        fields.append(Field(f"{path}{child.name}_{i}", dt))
                else:
                    fields.append(Field(f"{path}{child.name}", dt))
        return fields

    @staticmethod
    def _flat_primitive_type(p: Primitive):
        dt = p.dtype
        if isinstance(dt, Decimal):
            return decimal_type(dt.effective_precision, dt.effective_scale)
        if isinstance(dt, AlphaNumeric):
            return BINARY if dt.enc is Encoding.RAW else STRING
        if isinstance(dt, Integral):
            return LONG if dt.precision > MAX_INTEGER_PRECISION else INTEGER
        raise TypeError(f"Unknown AST object {dt!r}")
