"""Optional Arrow Flight front-end over the same serving core.

When `pyarrow.flight` is importable (it is an optional pyarrow
extension — the frame protocol in serve/protocol.py never requires
it), `FlightScanServer` exposes the identical handler core as a Flight
service: a `do_get` ticket carries the same JSON request the 'R' frame
does, admission control and the streaming session are shared (one
AdmissionController, one metrics registry), and batches stream out as
a Flight record-batch stream. Standard Flight tooling (`pyarrow.flight
.connect(...).do_get(...)`) can then consume scans with zero custom
client code.
"""
from __future__ import annotations

import json
import queue
import threading
from typing import Dict, Optional

from ..obs.metrics import serve_metrics
from .admission import AdmissionController, AdmissionRejected, TenantQuota
from .session import ScanRequest, ScanSession


def flight_available() -> bool:
    try:
        import pyarrow.flight  # noqa: F401
        return True
    except ImportError:
        return False


# one sentinel per stream end; exceptions travel as themselves
_EOS = object()


class FlightScanServer:
    """`FlightScanServer(...)` wraps a pyarrow.flight server around the
    serving core. Construction raises ImportError when the flight
    extension is absent — gate with `flight_available()`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 default_quota: Optional[TenantQuota] = None,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 max_concurrent_scans: int = 16,
                 queue_timeout_s: float = 30.0,
                 server_options: Optional[dict] = None):
        import pyarrow.flight as flight

        metrics = serve_metrics()
        controller = AdmissionController(
            default_quota=default_quota, quotas=quotas,
            max_concurrent_scans=max_concurrent_scans,
            queue_timeout_s=queue_timeout_s, metrics=metrics)
        outer_options = dict(server_options or {})

        class _Server(flight.FlightServerBase):
            def do_get(self, context, ticket):
                try:
                    request = ScanRequest(
                        json.loads(ticket.ticket.decode()))
                except Exception as exc:
                    raise flight.FlightServerError(
                        f"malformed ticket: {exc}")
                try:
                    admission = controller.admit(request.tenant)
                except AdmissionRejected as exc:
                    # flight's closest match to the structured 'E'
                    # rejection frame
                    raise flight.FlightUnavailableError(
                        f"rejected ({exc.reason}): {exc}")
                out: "queue.Queue" = queue.Queue(maxsize=4)
                # set when the Flight stream stops pulling (client done
                # or GONE — GeneratorStream closes the generator, its
                # finally fires): the scan worker must then ABORT, not
                # block on the full queue forever with the admission
                # slot held
                consumer_gone = threading.Event()
                session = ScanSession(request,
                                      server_options=outer_options,
                                      controller=controller)

                def deliver(item) -> None:
                    while True:
                        if consumer_gone.is_set():
                            raise ConnectionError(
                                "flight peer stopped consuming "
                                "mid-stream")
                        try:
                            out.put(item, timeout=0.5)
                            return
                        except queue.Full:
                            continue

                def run_scan():
                    try:
                        session.run(deliver)
                        deliver(_EOS)
                    except BaseException as exc:
                        try:
                            deliver(exc)
                        except ConnectionError:
                            pass  # peer gone — nothing left to tell it
                    finally:
                        controller.release(admission)

                worker = threading.Thread(
                    target=run_scan, name="cobrix-serve-flight-scan",
                    daemon=True)
                worker.start()
                first = out.get()
                if isinstance(first, BaseException):
                    consumer_gone.set()
                    raise flight.FlightServerError(
                        f"{type(first).__name__}: {first}")

                def batches(first_table):
                    try:
                        item = first_table
                        while item is not _EOS:
                            if isinstance(item, BaseException):
                                raise item
                            for batch in item.to_batches():
                                yield batch
                            item = out.get()
                    finally:
                        consumer_gone.set()

                if first is _EOS:
                    schema = session.result_schema
                    import pyarrow as pa

                    return flight.RecordBatchStream(
                        pa.Table.from_batches([], schema=schema))
                return flight.GeneratorStream(first.schema,
                                              batches(first))

        self._server = _Server(
            location=f"grpc://{host}:{port}")
        self.controller = controller
        self.metrics = metrics
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.port

    def start(self) -> "FlightScanServer":
        self._thread = threading.Thread(target=self._server.serve,
                                        name="cobrix-serve-flight",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
