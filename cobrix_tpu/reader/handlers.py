"""Record handlers: the target-agnostic record assembly seam.

Mirrors the reference's `RecordHandler[T]` abstraction
(reader/extractors/record/RecordHandler.scala:21-25, proven by
cobol-converters' SerializersSpec.scala:26): extraction walks the AST and
delegates the materialization of each group to a handler, so the same
decode produces Spark-Row-like tuples, dicts, JSON — or any user type —
without touching reader internals. Both the scalar extractor
(reader.extractors.extract_record) and the columnar row path
(DecodedBatch.to_rows) accept a handler.
"""
from __future__ import annotations

from typing import List, Sequence

from ..copybook.ast import Group


class RecordHandler:
    """create(values, group) -> record; to_seq(record) -> field values.

    `values` are the group's non-filler child values in declaration order
    (nested groups arrive already created by this handler). Hierarchical
    extraction calls `create_named` instead: its value order differs from
    declaration order (child-segment records are appended after the
    parent's own fields), so the matching names come with the values."""

    def create(self, values: List[object], group: Group) -> object:
        raise NotImplementedError

    def create_named(self, values: List[object], names: List[str],
                     group: Group) -> object:
        return self.create(values, group)

    def to_seq(self, record: object) -> Sequence[object]:
        raise NotImplementedError


class TupleHandler(RecordHandler):
    """The default: groups become tuples (the GenericRow analogue,
    SparkCobolRowType.scala:24)."""

    def create(self, values, group):
        return tuple(values)

    def to_seq(self, record):
        return record


class DictHandler(RecordHandler):
    """Groups become {field_name: value} dicts (the StructHandler of
    SerializersSpec.scala:134-147)."""

    def __init__(self):
        # per-group name lists, cached: the compiled row maker calls
        # create() once per group per row
        self._names: dict = {}

    def _group_names(self, group: Group) -> List[str]:
        names = self._names.get(id(group))
        if names is None:
            names = [ch.name for ch in group.children if not ch.is_filler]
            self._names[id(group)] = names
        return names

    def create(self, values, group):
        return dict(zip(self._group_names(group), values))

    def create_named(self, values, names, group):
        return dict(zip(names, values))

    def to_seq(self, record):
        return list(record.values())


class JsonHandler(DictHandler):
    """Like DictHandler, with a helper to render one extracted record as a
    JSON document (the SerializersSpec JSON-generation shape)."""

    def render(self, values: List[object], root: Group) -> str:
        import json
        from decimal import Decimal

        def default(o):
            if isinstance(o, Decimal):
                return int(o) if o == o.to_integral_value() else float(o)
            if isinstance(o, bytes):
                return o.decode("latin-1")
            return str(o)

        return json.dumps(self.create(values, root), default=default,
                          separators=(",", ":"))


DEFAULT_HANDLER = TupleHandler()
