"""Fault injection — the corruption side of the test-data generators.

Where `generators.py` builds clean EBCDIC fixtures, this module breaks
them in the ways real mainframe dumps break: flipped bits, torn tails,
garbage splices, zeroed/oversized RDW headers, and storage that fails a
few reads before recovering. The fault-tolerance test matrix
(tests/test_fault_tolerance.py) and `tools/corruptcheck.py` drive every
`record_error_policy` through these injectors; they are permanent test
infrastructure, not throwaway helpers.

All byte-level injectors are pure: they take `bytes` and return
corrupted `bytes` plus (where useful) the corruption site, so assertions
can check the ledger points at the right offset. `ShardFaultPlan` at the
bottom breaks *workers* instead of bytes (crash / hang / straggle /
error per shard) — the supervision test matrix
(tests/test_supervision.py, tools/chaoscheck.py) drives the shard
supervisor through it.
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..reader.stream import ByteRangeSource


def rdw_record_starts(data: bytes, big_endian: bool = False,
                      rdw_adjustment: int = 0) -> List[int]:
    """Byte offset of every RDW header in a clean file — the structural
    boundaries truncation/corruption fixtures enumerate."""
    from .. import native

    offsets, _ = native.rdw_scan(data, big_endian, rdw_adjustment, 0, 0)
    return [int(o) - 4 for o in offsets]


def flip_bit(data: bytes, offset: int, bit: int = 0) -> bytes:
    """Flip one bit at `offset` (bit 0 = LSB)."""
    out = bytearray(data)
    out[offset] ^= (1 << bit)
    return bytes(out)


def truncate(data: bytes, keep: int) -> bytes:
    """Torn tail: keep only the first `keep` bytes."""
    return data[:keep]


def splice_garbage(data: bytes, offset: int, garbage: bytes) -> bytes:
    """Insert foreign bytes at `offset` (a torn-and-respliced dump)."""
    return data[:offset] + garbage + data[offset:]


def overwrite(data: bytes, offset: int, patch: bytes) -> bytes:
    """Overwrite bytes in place at `offset`."""
    return data[:offset] + patch + data[offset + len(patch):]


def zero_rdw(data: bytes, record_start: int) -> bytes:
    """Zero out the 4-byte RDW header at a record start — the classic
    'RDW headers should never be zero' failure."""
    return overwrite(data, record_start, b"\x00\x00\x00\x00")


def oversize_rdw(data: bytes, record_start: int,
                 big_endian: bool = False) -> bytes:
    """Make the RDW at a record start declare an absurd length (driven
    through the 100 MB cap by the rdw_adjustment=0 default decoders the
    suite uses via huge 16-bit lengths only when adjusted; here it simply
    declares far more bytes than the file holds)."""
    header = b"\xff\xff\x00\x00" if big_endian else b"\x00\x00\xff\xff"
    return overwrite(data, record_start, header)


def garbage_run(length: int, seed: int = 0) -> bytes:
    """Deterministic non-header-looking garbage: 0x00/0x40 heavy like a
    real torn EBCDIC region (zero RDWs, so framing must resync)."""
    rng = np.random.default_rng(seed)
    pool = np.asarray([0x00, 0x40, 0x00, 0xFF], dtype=np.uint8)
    return bytes(pool[rng.integers(0, len(pool), size=length)])


def every_structural_truncation(data: bytes, big_endian: bool = False
                                ) -> List[Tuple[int, bytes]]:
    """(cut_position, truncated_file) for a cut at every structural
    boundary class: mid-header, right after a header, and mid-payload of
    each record (bounded to the first few records plus the last one to
    keep fuzz loops fast by default; the full sweep is the slow tier)."""
    starts = rdw_record_starts(data, big_endian)
    cuts = []
    for s in starts:
        cuts.extend([s + 1, s + 4, s + 5])
    cuts.append(len(data) - 1)
    out = []
    for cut in sorted({c for c in cuts if 0 < c < len(data)}):
        out.append((cut, data[:cut]))
    return out


# -- encoder-aware record corruption --------------------------------------
#
# The injectors above damage FRAMING (headers, tails, splices). The
# helpers below damage the *content* of one record in ways only a
# decoder notices — an invalid packed sign nibble, a non-digit BCD
# nibble, a segment id no redefine maps — plus the two framing flavors
# a per-record corruptor needs (RDW length damage, mid-record torn
# write). Each damage class has a SPECIFIC observable diagnostic, which
# tests/test_fault_tolerance.py asserts per kind:
#
#   sign-nibble   the damaged COMP-3 field decodes to None (0x0A is not
#                 a sign), neighbors intact;
#   packed-digit  same field-level None (a nibble >= 0x0A is not a
#                 digit);
#   rdw-length    zeroed header => "zero-length RDW header" resync
#                 ledger entry; oversized => clamped-tail truncation;
#   segment-id    no redefine branch matches => every segment column
#                 of the row is None;
#   torn-write    the record's tail is lost mid-field => permissive
#                 nulls the tail and ledgers a truncation.

CORRUPT_RECORD_KINDS = ("sign-nibble", "packed-digit", "rdw-length",
                        "segment-id", "torn-write")


def field_site(copybook, field_name: str):
    """(byte_offset, byte_size) of a named primitive inside one record
    — the encoder-aware aim point for `corrupt_record`. Accepts
    copybook text or a parsed `Copybook`."""
    from ..copybook.ast import transform_identifier
    from ..copybook.copybook import parse_copybook

    if isinstance(copybook, str):
        copybook = parse_copybook(copybook)
    want = transform_identifier(field_name)
    for st in copybook.ast.walk_primitives():
        if st.name == want:
            return (st.binary_properties.offset,
                    st.binary_properties.data_size)
    raise KeyError(f"no primitive named {field_name!r} in copybook")


def corrupt_record(record: bytes, kind: str, *, site=None,
                   header: bool = False, big_endian: bool = False,
                   seed: int = 0) -> bytes:
    """Damage ONE record's bytes in an encoder-aware way and return the
    corrupted record. `site` is the (offset, size) of the targeted field
    *within the record body* (from `field_site`); `header=True` means
    `record` starts with its own 4-byte RDW (sites shift by 4, and
    'rdw-length' is applicable). `kind` is one of CORRUPT_RECORD_KINDS.
    """
    out = bytearray(record)
    base = 4 if header else 0
    if kind == "sign-nibble":
        off, size = site
        pos = base + off + size - 1  # sign lives in the final nibble
        out[pos] = (out[pos] & 0xF0) | 0x0A  # 0xA: not C/D/F
    elif kind == "packed-digit":
        off, size = site
        pos = base + off  # first digit byte
        out[pos] = 0xBB   # nibbles 0xB: not decimal digits
    elif kind == "rdw-length":
        if not header:
            raise ValueError("rdw-length damage needs header=True "
                             "(the record must carry its own RDW)")
        out[0:4] = b"\x00\x00\x00\x00" if seed % 2 == 0 else (
            b"\xff\xff\x00\x00" if big_endian else b"\x00\x00\xff\xff")
    elif kind == "segment-id":
        off, size = site
        # 0x5A..: EBCDIC punctuation — never a mapped segment id value
        for i in range(size):
            out[base + off + i] = 0x5A
    elif kind == "torn-write":
        keep = base + max(1, (len(record) - base) * 2 // 3)
        return bytes(out[:keep])
    else:
        raise ValueError(f"unknown corruption kind {kind!r}; one of "
                         f"{CORRUPT_RECORD_KINDS}")
    return bytes(out)


class FlakySource(ByteRangeSource):
    """A ByteRangeSource that fails its first `fail_reads` read() calls
    (raising IOError), then recovers — the transient-storage profile the
    IO retry layer must absorb. `fail_forever=True` models a dead backend
    (every read raises) for deadline tests."""

    def __init__(self, data: bytes, fail_reads: int = 2,
                 name: str = "flaky://test",
                 fail_forever: bool = False,
                 short_read: Optional[int] = None):
        self._data = data
        self._name = name
        self.fail_reads = fail_reads
        self.fail_forever = fail_forever
        self.short_read = short_read
        self.read_calls = 0
        self.failures_served = 0

    def size(self) -> int:
        return len(self._data)

    def read(self, offset: int, n: int) -> bytes:
        self.read_calls += 1
        if self.fail_forever or self.failures_served < self.fail_reads:
            self.failures_served += 1
            raise IOError(
                f"injected transient failure #{self.failures_served} "
                f"(offset={offset}, n={n})")
        if self.short_read:
            n = min(n, self.short_read)
        return self._data[offset:offset + n]

    @property
    def name(self) -> str:
        return self._name


def register_flaky_backend(scheme: str, data: bytes,
                           **kwargs) -> "FlakySource":
    """Register a `scheme://` backend serving `data` through a single
    FlakySource instance (returned for assertions on its counters)."""
    from ..reader.stream import register_stream_backend

    source = FlakySource(data, **kwargs)
    register_stream_backend(scheme, lambda path: source)
    return source


class ChaosSource(ByteRangeSource):
    """Network-shaped fault wrapper over ANY ByteRangeSource (including
    the fsspec adapter) — the composable injector the remote-io test
    matrix drives the retry + cache + prefetch stack through:

    * `fail_reads` / `fail_every` — transient IOErrors: the first N
      reads fail, or every k-th read fails (exercises retries landing
      on prefetch-pool threads, not just the consumer);
    * `error_type` — what a failure raises (proves 'dead backend fails
      with the backend's OWN error type' end to end);
    * `latency_s` — per-read sleep: a slow filesystem (read-ahead must
      hide it; supervision deadlines must survive it);
    * `truncate_at` — storage EOF short of the advertised size: reads
      at/after the cut return b'' while size() keeps promising more —
      the short-read anomaly BufferedSourceStream re-probes and the
      framing layer then ledgers as truncation.

    Counters (`read_calls`, `failures_served`, `slept_s`) stay on the
    wrapper for assertions."""

    def __init__(self, inner: ByteRangeSource, fail_reads: int = 0,
                 fail_every: int = 0, fail_forever: bool = False,
                 error_type=IOError, latency_s: float = 0.0,
                 truncate_at: Optional[int] = None):
        self._inner = inner
        self.fail_reads = fail_reads
        self.fail_every = fail_every
        self.fail_forever = fail_forever
        self.error_type = error_type
        self.latency_s = latency_s
        self.truncate_at = truncate_at
        self.read_calls = 0
        self.failures_served = 0
        self.slept_s = 0.0

    def size(self) -> int:
        return self._inner.size()

    def fingerprint(self) -> str:
        return self._inner.fingerprint()

    @property
    def name(self) -> str:
        return self._inner.name

    def close(self) -> None:
        self._inner.close()

    def read(self, offset: int, n: int) -> bytes:
        import time

        self.read_calls += 1
        if self.latency_s:
            time.sleep(self.latency_s)
            self.slept_s += self.latency_s
        should_fail = (self.fail_forever
                       or self.failures_served < self.fail_reads
                       or (self.fail_every
                           and self.read_calls % self.fail_every == 0))
        if should_fail:
            self.failures_served += 1
            raise self.error_type(
                f"injected fault #{self.failures_served} "
                f"(offset={offset}, n={n})")
        if self.truncate_at is not None:
            if offset >= self.truncate_at:
                return b""  # storage EOF short of the logical limit
            n = min(n, self.truncate_at - offset)
        return self._inner.read(offset, n)


def register_chaos_backend(scheme: str, data: bytes,
                           **kwargs) -> "ChaosSource":
    """Register `scheme://` serving `data` through one ChaosSource over
    an in-memory source (returned for counter assertions)."""
    from ..reader.stream import register_stream_backend

    class _MemSource(ByteRangeSource):
        def __init__(self, payload: bytes, name: str):
            self._payload = payload
            self._name = name

        def size(self) -> int:
            return len(self._payload)

        def read(self, offset: int, n: int) -> bytes:
            return self._payload[offset:offset + n]

        def fingerprint(self) -> str:
            import hashlib

            return hashlib.sha256(self._payload).hexdigest()

        @property
        def name(self) -> str:
            return self._name

    source = ChaosSource(_MemSource(data, f"{scheme}://chaos"), **kwargs)
    register_stream_backend(scheme, lambda path: source)
    return source


# -- compressed-feed fault injection --------------------------------------
#
# The injectors below damage COMPRESSED WIRE BYTES, not the decompressed
# payload: a feed torn mid-member (an aborted upload), a member whose
# trailer CRC no longer matches (a bit rotted in transit), and foreign
# bytes spliced between members (a concatenation gone wrong). The
# streaming decompression plane (io/compress.py) must turn each into a
# structured `CompressedStreamError` carrying BOTH offsets (where in the
# wire bytes and where in the decompressed stream), honor
# `record_error_policy`, and count the damage under the `compress`
# integrity plane. Driven by tests/test_compressed_io.py and
# tools/compcheck.py.


def compressed_member_spans(data: bytes, codec: str = "gzip"
                            ) -> List[Tuple[int, int]]:
    """[(start, end)) wire-byte span of every member/frame in a
    concatenated compressed stream — the structural boundaries the
    compressed injectors aim at. Found by actually decoding (magic
    bytes can occur inside compressed payloads, so scanning is not
    safe)."""
    from ..io.compress import codec_by_name

    c = codec_by_name(codec)
    spans: List[Tuple[int, int]] = []
    pos = 0
    while pos < len(data):
        d = c.new_decoder()
        chunk = data[pos:]
        d.decompress(chunk)
        if not d.eof:
            raise ValueError(
                f"stream ends mid-member at wire offset {pos} "
                f"(already damaged?)")
        consumed = len(chunk) - len(d.unused_data)
        spans.append((pos, pos + consumed))
        pos += consumed
    return spans


def truncate_compressed_member(data: bytes, codec: str = "gzip",
                               which: int = -1,
                               keep_fraction: float = 0.5
                               ) -> Tuple[bytes, int]:
    """Tear the stream mid-member: keep everything before member
    `which` plus `keep_fraction` of that member's wire bytes. Returns
    (torn_stream, cut_wire_offset). The inflate must fail (or, under a
    permissive policy, stop) AT the cut — never frame garbage past it.
    """
    spans = compressed_member_spans(data, codec)
    start, end = spans[which % len(spans)]
    cut = start + max(1, int((end - start) * keep_fraction))
    return data[:cut], cut


def corrupt_compressed_trailer(data: bytes, codec: str = "gzip",
                               which: int = -1) -> Tuple[bytes, int]:
    """Flip one bit inside member `which`'s trailer region (the final
    bytes of the member's wire span — for gzip the CRC32/ISIZE words).
    Returns (corrupted_stream, flip_offset). The decoder's own
    integrity check must surface as `CompressedStreamError`, not as
    silently wrong decompressed bytes."""
    spans = compressed_member_spans(data, codec)
    start, end = spans[which % len(spans)]
    pos = max(start, end - 5)  # inside gzip CRC32; tail bytes otherwise
    return flip_bit(data, pos), pos


def garbage_between_members(data: bytes, codec: str = "gzip",
                            which: int = 0, length: int = 64,
                            seed: int = 0) -> Tuple[bytes, int]:
    """Splice non-codec garbage at the boundary AFTER member `which` —
    the mis-concatenated feed. Returns (spliced_stream, splice_offset).
    The inflater tolerates NUL padding there (tape-style blocking) but
    must refuse anything else with a structured error at the splice."""
    spans = compressed_member_spans(data, codec)
    _start, end = spans[which % len(spans)]
    rng = np.random.default_rng(seed)
    junk = bytes(rng.integers(1, 255, size=length, dtype=np.uint8))
    return splice_garbage(data, end, junk), end


# -- durable-state fault injection ---------------------------------------
#
# The injectors below break DISK, not bytes-in-flight or workers: the
# persistent cache planes (io/blockcache, io/index_store, the roofline
# calibration) trust files across process lifetimes, and
# tests/test_integrity.py + tools/fsckcache.py drive the self-verifying
# read path through exactly the corruptions real storage produces —
# flipped bits, torn tails — plus the writer-side failures (ENOSPC,
# read-only volume) that must degrade to "cache off", never to a failed
# scan.


def cache_entry_paths(cache_dir: str, plane: str = "block"):
    """Every durable entry file of one cache plane under `cache_dir`,
    sorted for determinism. Planes: 'block' (aligned .blk entries),
    'index' (sparse-index .json payloads), 'stats' (scan-profile .json
    payloads), 'compress' (seekable inflate-index .json payloads),
    'checkpoint' (continuous-ingest watermark slots — pass the
    CHECKPOINT directory)."""
    if plane == "checkpoint":
        from ..streaming.checkpoint import checkpoint_files

        return checkpoint_files(cache_dir)
    sub = {"block": "blocks", "index": "index", "stats": "stats",
           "compress": "compress"}[plane]
    suffix = {"block": ".blk", "index": ".json", "stats": ".json",
              "compress": ".json"}[plane]
    root = os.path.join(cache_dir, sub)
    out = []
    for dirpath, dirs, files in os.walk(root):
        if os.path.basename(dirpath) == "quarantine":
            dirs[:] = []
            continue
        for name in files:
            if name.endswith(suffix):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def corrupt_cache_entry(cache_dir: str, plane: str = "block",
                        mode: str = "bitflip", which: int = 0,
                        offset: int = -9) -> str:
    """Corrupt one persistent-cache entry in place and return its path.

    * ``mode='bitflip'`` — flip one bit at `offset` (negative = from
      the tail, default lands inside the payload, past any header);
    * ``mode='truncate'`` — tear the file to half its size (a crashed
      copy, a filesystem that lost the tail);
    * ``mode='garbage'`` — replace the whole file with non-format bytes.

    `which` picks the entry (sorted order). The integrity layer must
    turn every one of these into a counted, quarantined MISS."""
    paths = cache_entry_paths(cache_dir, plane)
    if not paths:
        raise FileNotFoundError(
            f"no '{plane}' cache entries under {cache_dir}")
    path = paths[which % len(paths)]
    data = open(path, "rb").read()
    if mode == "bitflip":
        pos = offset if offset >= 0 else len(data) + offset
        pos = max(0, min(len(data) - 1, pos))
        data = flip_bit(data, pos)
    elif mode == "truncate":
        data = data[:max(1, len(data) // 2)]
    elif mode == "garbage":
        data = b"\x00\xff" * max(8, len(data) // 4)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    with open(path, "wb") as f:
        f.write(data)
    return path


class cache_write_faults:
    """Context manager making every cache-plane WRITE fail the way a
    full or read-only volume does (``mode='enospc'`` => OSError ENOSPC,
    ``mode='readonly'`` => OSError EROFS) while reads keep working.
    Patches the `write_atomic` symbol each persistence module bound at
    import, so the fault hits exactly the durable-write call sites::

        with cache_write_faults("enospc"):
            read_cobol(...)   # scans fine; cache stays cold

    The contract under test: a failing cache write DEGRADES (warn +
    refetch next time), it never fails the scan."""

    def __init__(self, mode: str = "enospc"):
        import errno

        self.errno = {"enospc": errno.ENOSPC,
                      "readonly": errno.EROFS}[mode]
        self.mode = mode
        self.write_attempts = 0
        self._patched = []

    def _raiser(self):
        fault = self

        def failing_write_atomic(path, data, fsync=False):
            fault.write_attempts += 1
            raise OSError(fault.errno,
                          f"injected {fault.mode} on cache write", path)
        return failing_write_atomic

    def __enter__(self):
        # patching utils.atomic also covers late `from ..utils.atomic
        # import write_atomic` call sites (roofline's lazy import)
        from ..io import blockcache, compress_index, index_store
        from ..utils import atomic

        fail = self._raiser()
        # patch each consumer's bound symbol AND the source module (for
        # late importers)
        for mod in (blockcache, index_store, compress_index, atomic):
            self._patched.append((mod, "write_atomic",
                                  mod.write_atomic))
            mod.write_atomic = fail
        return self

    def __exit__(self, *exc):
        for mod, name, original in self._patched:
            setattr(mod, name, original)
        self._patched.clear()
        return False


# -- live-source fault injection -----------------------------------------
#
# The injectors below break LIVE sources, not static bytes: the
# continuous-ingest tailer (cobrix_tpu.streaming) must survive files
# that grow in torn non-record-aligned increments, rotate under it,
# shrink below its watermark, and consumers that die mid-stream. Driven
# by tests/test_streaming_ingest.py and tools/streamcheck.py.


class LiveAppender:
    """Background thread growing a file in TORN increments: appends are
    deliberately cut at non-record boundaries (and optionally fsync'd
    mid-record with a pause), so the tailer's stable-prefix framing is
    exercised against every partial-record shape a live writer
    produces.

        app = LiveAppender(path, payload, slice_sizes=(7, 3, 12))
        app.start(); ...; app.join()

    `slice_sizes` cycles; when exhausted the remainder goes out in one
    write. `pause_s` sleeps between appends (0 = as fast as possible).
    """

    def __init__(self, path: str, payload: bytes,
                 slice_sizes=(5, 1, 9, 2), pause_s: float = 0.02,
                 fsync: bool = False):
        import threading

        self.path = str(path)
        self.payload = payload
        self.slice_sizes = tuple(slice_sizes)
        self.pause_s = pause_s
        self.fsync = fsync
        self.appended = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        import itertools
        import time

        sizes = itertools.cycle(self.slice_sizes)
        pos = 0
        with open(self.path, "ab") as f:
            while pos < len(self.payload):
                n = min(next(sizes), len(self.payload) - pos)
                f.write(self.payload[pos:pos + n])
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
                pos += n
                self.appended = pos
                if self.pause_s:
                    time.sleep(self.pause_s)

    def start(self) -> "LiveAppender":
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    @property
    def done(self) -> bool:
        return not self._thread.is_alive()


def rotate_source(path: str, new_content: bytes,
                  rotated_suffix: str = ".1") -> str:
    """Classic rename rotation: the current file moves to
    ``path + rotated_suffix`` (same inode, same content) and a NEW file
    with `new_content` appears at `path`. Returns the rotated-away
    path. The tailer must drain the old generation exactly once (via
    its held descriptor or the inode-matched alias) before switching."""
    rotated = path + rotated_suffix
    os.replace(path, rotated)
    with open(path, "wb") as f:
        f.write(new_content)
    return rotated


def truncate_source(path: str, keep_bytes: int) -> None:
    """Shrink a live file in place below (presumably) the consumer's
    watermark — the copy-truncate / operator-mistake shape that must
    surface as a structured ``source_truncated`` outcome, never as
    silently wrong rows."""
    with open(path, "r+b") as f:
        f.truncate(keep_bytes)


def replace_source(path: str, new_content: bytes) -> None:
    """In-place content replacement keeping the path (and usually the
    inode): the rotation flavor only the head-CRC check can detect
    when the new content is not shorter than the watermark."""
    with open(path, "wb") as f:
        f.write(new_content)


def crash_consumer_after(batches: int):
    """A consumer-side crash hook: returns a callable to invoke once
    per delivered batch; on the N-th call the PROCESS dies via
    ``os._exit`` — no exception, no cleanup, no atexit — exactly how
    SIGKILL/OOM ends an ingesting worker. For in-process tests prefer
    simply abandoning the ingestor (same recovery path, no subprocess);
    subprocess harnesses (tools/streamcheck.py) use this."""
    state = {"n": 0}

    def hook() -> None:
        state["n"] += 1
        if state["n"] >= batches:
            os._exit(137)
    return hook


# -- lakehouse-sink fault injection --------------------------------------
#
# The injectors below break the TRANSACTIONAL SINK (cobrix_tpu.sink):
# consumers killed between staging a data file and committing its
# manifest record, manifest records torn or bit-flipped on disk, and
# dataset volumes that fail writes — the crash matrix
# (tests/test_sink.py, tools/sinkcheck.py) drives the commit protocol's
# recovery through every window. Once-markers use the same O_EXCL
# cross-process claim as ShardFaultPlan: a RESTARTED consumer re-
# installs the plan, but the marker guarantees each fault fires exactly
# once across the whole kill/restart sequence.

SINK_KILL_POINTS = ("pre_stage", "post_stage", "pre_commit",
                    "post_commit")


class SinkKilled(Exception):
    """Raised by a SinkFaultPlan in ``action='raise'`` mode — the
    in-process stand-in for SIGKILL (the commit is abandoned exactly
    where the kill landed; recovery runs on the next sink open)."""


class SinkFaultPlan:
    """Kill plan keyed by commit kill-window (and optionally commit
    seq). ``action='exit'`` dies via ``os._exit(137)`` (subprocess
    harnesses — tools/sinkcheck.py); ``action='raise'`` raises
    `SinkKilled` (in-process tests: abandon the sink+ingestor, rebuild
    from the checkpoint, continue).

        plan = SinkFaultPlan(state_dir, action="raise")
        plan.kill("pre_commit")          # first commit reaching the
                                         # stage-write→manifest window
        plan.kill("post_commit", seq=3)  # commit #3, after the append,
                                         # before the ack
        with plan.installed():
            sink_cobol(tail_cobol(...), dataset_dir)
    """

    def __init__(self, state_dir: str, action: str = "exit"):
        if action not in ("exit", "raise"):
            raise ValueError(f"action must be 'exit' or 'raise', "
                             f"got {action!r}")
        self.state_dir = str(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self.action = action
        self._kills: dict = {}

    def kill(self, point: str, seq: Optional[int] = None,
             once: bool = True) -> "SinkFaultPlan":
        if point not in SINK_KILL_POINTS:
            raise ValueError(f"unknown sink kill point {point!r}; "
                             f"one of {SINK_KILL_POINTS}")
        self._kills[(point, seq)] = once
        return self

    def _marker(self, point: str, seq: Optional[int]) -> str:
        return os.path.join(self.state_dir,
                            f"sink_fault_{point}_{seq or 'any'}")

    def fired(self, point: str, seq: Optional[int] = None) -> bool:
        return os.path.exists(self._marker(point, seq))

    def _claim(self, point: str, seq: Optional[int]) -> bool:
        try:
            fd = os.open(self._marker(point, seq),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        os.close(fd)
        return True

    def __call__(self, point: str, seq: int) -> None:
        for key in ((point, seq), (point, None)):
            if key not in self._kills:
                continue
            once = self._kills[key]
            if once and not self._claim(*key):
                continue
            if not once:
                self._claim(*key)  # fired() breadcrumb
            if self.action == "exit":
                os._exit(137)
            raise SinkKilled(
                f"injected sink kill at {point} (commit seq {seq})")

    def installed(self):
        """Context manager installing this plan as the sink fault hook
        (uninstalled on exit, even on test failure)."""
        import contextlib

        from ..sink.writer import set_sink_fault_hook

        @contextlib.contextmanager
        def _ctx():
            set_sink_fault_hook(self)
            try:
                yield self
            finally:
                set_sink_fault_hook(None)
        return _ctx()


def corrupt_sink_manifest(dataset_dir: str, mode: str = "bitflip",
                          which: int = -1) -> str:
    """Corrupt one record of a LOCAL sink dataset's manifest in place;
    returns the manifest path.

    * ``mode='bitflip'`` — flip one bit inside record `which` (default
      the last record; the CRC stamp must catch it even when the JSON
      stays parseable);
    * ``mode='torn'`` — tear the manifest mid-way through record
      `which` (a crashed appender / lost tail page).

    Recovery must treat damage past the checkpointed position as a
    self-healing truncation and damage inside it as loud
    `SinkCorruption` — never silence, never replay."""
    from ..sink.manifest import MANIFEST_NAME

    path = os.path.join(dataset_dir, MANIFEST_NAME)
    data = open(path, "rb").read()
    lines = data.split(b"\n")[:-1]  # trailing "" after final newline
    if not lines:
        raise FileNotFoundError(f"no manifest records under {path}")
    idx = which % len(lines)
    start = sum(len(ln) + 1 for ln in lines[:idx])
    if mode == "bitflip":
        # flip a LOW bit inside the record's payload region (past the
        # opening brace, ahead of the newline) so the line often stays
        # valid JSON — only the CRC can catch it
        pos = start + min(len(lines[idx]) - 2, 20)
        data = flip_bit(data, pos, bit=0)
    elif mode == "torn":
        data = data[:start + max(1, len(lines[idx]) // 2)]
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    with open(path, "wb") as f:
        f.write(data)
    return path


class sink_write_faults:
    """Context manager making every DATASET-VOLUME write fail the way
    a full or read-only volume does (``mode='enospc'`` => OSError
    ENOSPC, ``mode='readonly'`` => OSError EROFS) while reads keep
    working. Patches the sink writer's durable-write call sites
    (`_local_write` staging/meta writes, `_local_append` manifest
    appends)::

        with sink_write_faults("enospc"):
            sink.commit_table(table)   # raises ENOSPC, NOTHING
                                       # half-committed

    The contract under test: unlike cache planes, the sink must fail
    LOUDLY (an un-persistable commit must never be acked) and
    atomically (the manifest is unchanged; recovery quarantines any
    finalized-but-unreferenced files)."""

    def __init__(self, mode: str = "enospc",
                 fail_writes: bool = True, fail_appends: bool = True):
        import errno

        self.errno = {"enospc": errno.ENOSPC,
                      "readonly": errno.EROFS}[mode]
        self.mode = mode
        self.write_attempts = 0
        self.append_attempts = 0
        self.fail_writes = fail_writes
        self.fail_appends = fail_appends
        self._saved = None

    def __enter__(self):
        from ..sink import writer

        fault = self
        self._saved = (writer._local_write, writer._local_append)

        def failing_write(path, data):
            fault.write_attempts += 1
            if fault.fail_writes:
                raise OSError(fault.errno,
                              f"injected {fault.mode} on sink write",
                              path)
            return fault._saved[0](path, data)

        def failing_append(path, data):
            fault.append_attempts += 1
            if fault.fail_appends:
                raise OSError(fault.errno,
                              f"injected {fault.mode} on sink append",
                              path)
            return fault._saved[1](path, data)

        writer._local_write = failing_write
        writer._local_append = failing_append
        return self

    def __exit__(self, *exc):
        from ..sink import writer

        writer._local_write, writer._local_append = self._saved
        return False


# -- distributed-supervision fault injection -----------------------------
#
# The injectors below break WORKERS, not bytes: a multihost worker
# process crashes mid-shard (os._exit), wedges past its deadline
# (sleep), straggles (sleep-then-succeed), or raises — the profiles the
# shard supervisor (parallel/supervisor.py) must recover from. A
# ShardFaultPlan installs itself as the hosts-module fault hook; fork
# children inherit it, so no pickling. "Once" faults coordinate across
# worker processes (the re-dispatched attempt runs in a DIFFERENT fork)
# through O_CREAT|O_EXCL marker files in a shared state dir: exactly one
# attempt fires the fault, every later attempt sails through — which is
# precisely the transient-failure profile recovery tests need.


class ShardFaultPlan:
    """Per-shard fault plan keyed by shard sequence number (the shard's
    position in the supervisor's canonical (file_order, offset) order).

        plan = ShardFaultPlan(state_dir)
        plan.crash(1)              # worker scanning shard 1 dies once
        plan.hang(2, 120.0)        # shard 2 wedges once (kill+redispatch)
        plan.slow(0, 3.0)          # shard 0 straggles (speculation bait)
        plan.error(3, once=False)  # shard 3 raises on EVERY attempt
        with plan.installed():
            read_cobol(..., hosts=2)
    """

    def __init__(self, state_dir: str):
        self.state_dir = str(state_dir)
        self._faults: dict = {}

    def crash(self, seq: int, once: bool = True,
              exit_code: int = 42) -> "ShardFaultPlan":
        """Worker death mid-shard: os._exit — no exception, no cleanup,
        exactly how an OOM-killed or segfaulted executor goes."""
        self._faults[seq] = ("crash", float(exit_code), once)
        return self

    def hang(self, seq: int, seconds: float = 3600.0,
             once: bool = True) -> "ShardFaultPlan":
        """Worker wedge: sleep far past the shard deadline so the
        supervisor must kill + re-dispatch."""
        self._faults[seq] = ("hang", seconds, once)
        return self

    def slow(self, seq: int, seconds: float,
             once: bool = True) -> "ShardFaultPlan":
        """Straggler: delay, then scan normally. With `once`, a
        speculative duplicate of the shard runs at full speed — the
        first-completion-wins race the speculation tests pin."""
        self._faults[seq] = ("slow", seconds, once)
        return self

    def error(self, seq: int, message: str = "injected shard error",
              once: bool = False) -> "ShardFaultPlan":
        """Deterministic in-shard exception (a poison shard when
        once=False: every re-dispatch fails too)."""
        self._faults[seq] = ("error", message, once)
        return self

    def fired(self, seq: int) -> bool:
        """True once the fault for `seq` has fired in some worker."""
        return os.path.exists(self._marker(seq))

    def _marker(self, seq: int) -> str:
        return os.path.join(self.state_dir, f"shard_fault_{seq}")

    def _claim(self, seq: int) -> bool:
        """Atomically claim a once-fault across worker processes."""
        try:
            fd = os.open(self._marker(seq),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        os.close(fd)
        return True

    def __call__(self, shard, seq: int) -> None:
        """Runs inside the worker immediately before the shard scan."""
        import time

        fault = self._faults.get(seq)
        if fault is None:
            return
        kind, arg, once = fault
        if once and not self._claim(seq):
            return
        if not once:
            self._claim(seq)  # leave a fired() breadcrumb anyway
        if kind == "crash":
            os._exit(int(arg))
        elif kind in ("hang", "slow"):
            time.sleep(float(arg))
        elif kind == "error":
            raise RuntimeError(f"{arg} (shard seq {seq})")

    def installed(self):
        """Context manager installing this plan as the multihost fault
        hook (and uninstalling on exit, even on test failure)."""
        import contextlib

        from ..parallel import hosts

        @contextlib.contextmanager
        def _ctx():
            hosts.set_shard_fault_hook(self)
            try:
                yield self
            finally:
                hosts.set_shard_fault_hook(None)
        return _ctx()
