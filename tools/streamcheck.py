"""Continuous-ingestion smoke check: exactly-once under SIGKILL.

Drives cobrix_tpu.streaming end to end the way the chaos matrix
(ISSUE 10) demands:

  1. a LiveAppender grows a fixed-length file in torn, non-record-
     aligned increments while a consumer SUBPROCESS tails it with a
     durable checkpoint dir, appending every delivered batch to an
     output log and acking each batch with the output length
     (`app_state`) — the exactly-once recipe;
  2. the consumer is killed repeatedly — both by its own os._exit
     mid-stream and by a parent SIGKILL at a random instant — and
     restarted from the checkpoint until the feed drains;
  3. the concatenation of the surviving output batches MUST be
     byte-identical to a one-shot `read_cobol(...).to_arrow()` of the
     final file: zero duplicates, zero gaps, monotone Record_Ids,
     across every kill;
  4. follow-mode parity: a serve-tier ``follow=true`` subscription over
     the same growing source must deliver the identical table, and
     `/metrics` must report the `cobrix_stream_*` series.

    python tools/streamcheck.py             # quick (~2 kill cycles)
    python tools/streamcheck.py --sweep     # fixed + VRL x more kills
                                            # (slow; tier-1 runs quick)

Exit code 0 = every assertion held; 1 otherwise.
"""
from __future__ import annotations

import argparse
import os
import signal
import struct
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

COPYBOOK = """
        01  R.
            05  KEY    PIC 9(7) COMP.
            05  NAME   PIC X(9).
"""
RECORD_BYTES = 13


def make_records(n: int, start: int = 0) -> bytes:
    return b"".join(
        (start + i).to_bytes(4, "big")
        + f"ROW{(start + i) % 1000000:06d}".encode("ascii")
        for i in range(n))


def make_rdw_records(n: int, start: int = 0) -> bytes:
    out = []
    for i in range(start, start + n):
        payload = f"K{i:05d}".encode("cp037")
        out.append(bytes([0, 0, len(payload) % 256,
                          len(payload) // 256]) + payload)
    return b"".join(out)


RDW_COPYBOOK = """
        01  R.
            05  K  PIC X(6).
"""


def make_corpus_records(n: int) -> bytes:
    """Encoder-built TXN corpus (testing/corpus.py) as the live feed:
    continuous ingestion exercised on multi-field encoder-produced
    records (COMP-3, big/little-endian binary, DISPLAY decimals)
    instead of the toy layouts above — the synthetic load factory and
    the streaming tier meeting end to end."""
    from cobrix_tpu.testing import corpus as _corpus

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "txn.dat")
        _corpus.write_fixed_corpus(path, n, seed=33)
        with open(path, "rb") as f:
            return f.read()


# -- durable output log (the consumer side of exactly-once) ---------------

def append_batch(out_path: str, table) -> int:
    """Serialize one Arrow table as a length-framed IPC segment,
    append + fsync, return the new durable length (the app_state the
    matching ack commits)."""
    import pyarrow as pa

    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    payload = sink.getvalue().to_pybytes()
    with open(out_path, "ab") as f:
        f.write(struct.pack(">I", len(payload)) + payload)
        f.flush()
        os.fsync(f.fileno())
        return f.tell()


def read_output(out_path: str):
    """Every complete framed segment -> list of tables (a torn final
    frame — the crash window — is ignored, exactly what truncate-to-
    app_state would have removed)."""
    import pyarrow as pa

    tables = []
    try:
        data = open(out_path, "rb").read()
    except OSError:
        return tables
    pos = 0
    while pos + 4 <= len(data):
        (n,) = struct.unpack(">I", data[pos:pos + 4])
        if pos + 4 + n > len(data):
            break
        with pa.ipc.open_stream(data[pos + 4:pos + 4 + n]) as r:
            tables.append(r.read_all())
        pos += 4 + n
    return tables


def consume(source: str, checkpoint_dir: str, out_path: str,
            crash_after: int, options: dict) -> int:
    """The consumer subprocess body: resume from the checkpoint,
    truncate the output to the committed app_state, ingest + ack until
    idle, optionally dying after `crash_after` batches. Exit 0 = feed
    idle (caller decides whether it is truly done)."""
    from cobrix_tpu.streaming import tail_cobol

    ing = tail_cobol(source, checkpoint_dir=checkpoint_dir,
                     auto_ack=False, poll_interval_s=0.05,
                     idle_timeout_s=1.0, finalize_on_idle=True,
                     **options)
    committed = int(ing.app_state or 0)
    with open(out_path, "ab") as f:
        f.truncate(committed)
    batches = 0
    for batch in ing:
        new_len = append_batch(out_path, batch.to_arrow())
        batch.ack(app_state=new_len)
        batches += 1
        if crash_after and batches >= crash_after:
            os._exit(137)  # SIGKILL-shaped: no cleanup, no flush
    return 0


def _spawn_consumer(source, checkpoint_dir, out_path, crash_after,
                    options) -> subprocess.Popen:
    import json as _json

    code = (
        "import sys, json; sys.path.insert(0, {root!r});\n"
        "import importlib.util as iu;\n"
        "spec = iu.spec_from_file_location('streamcheck', {me!r});\n"
        "m = iu.module_from_spec(spec); spec.loader.exec_module(m);\n"
        "sys.exit(m.consume({src!r}, {ckpt!r}, {out!r}, {crash!r}, "
        "json.loads({opts!r})))"
    ).format(root=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        me=os.path.abspath(__file__), src=source, ckpt=checkpoint_dir,
        out=out_path, crash=crash_after,
        opts=_json.dumps(options))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen([sys.executable, "-c", code], env=env)


def check_exactly_once(tag: str, payload: bytes, options: dict,
                       kill_cycles: int = 3,
                       parent_kill: bool = True) -> bool:
    """Grow a file tornly, kill/restart the consumer `kill_cycles`
    times, assert the output equals the one-shot read."""
    from cobrix_tpu import read_cobol
    from cobrix_tpu.testing.faults import LiveAppender
    import pyarrow as pa

    work = tempfile.mkdtemp(prefix=f"streamcheck-{tag}-")
    src = os.path.join(work, "feed.dat")
    ckpt = os.path.join(work, "ckpt")
    out = os.path.join(work, "out.bin")
    open(src, "wb").write(payload[:len(payload) // 4])
    appender = LiveAppender(src, payload[len(payload) // 4:],
                            slice_sizes=(7, 3, 11, 2, 29),
                            pause_s=0.005).start()
    cycles = 0
    deadline = time.monotonic() + 180
    while True:
        crash_after = 2 if cycles < kill_cycles else 0
        proc = _spawn_consumer(src, ckpt, out, crash_after, options)
        if parent_kill and cycles == 1:
            # one cycle dies by PARENT SIGKILL at a random instant
            # instead of a self-crash
            time.sleep(0.2 + 0.3 * (cycles % 2))
            proc.send_signal(signal.SIGKILL)
        rc = proc.wait()
        cycles += 1
        if rc == 0 and appender.done:
            break  # drained an idle feed after the appender finished
        if time.monotonic() > deadline:
            print(f"FAIL [{tag}]: kill/restart loop did not drain "
                  f"within 180s (rc={rc})")
            return False
    tables = read_output(out)
    if not tables:
        print(f"FAIL [{tag}]: no output batches survived")
        return False
    got = pa.concat_tables(tables).replace_schema_metadata(None)
    want = read_cobol(src, **options).to_arrow() \
        .replace_schema_metadata(None)
    if not got.equals(want):
        print(f"FAIL [{tag}]: output != one-shot read "
              f"({got.num_rows} vs {want.num_rows} rows over "
              f"{cycles} kill cycles)")
        return False
    print(f"ok [{tag}]: {got.num_rows} rows byte-identical across "
          f"{cycles} kill/restart cycles ({len(tables)} batches)")
    return True


def check_follow_parity() -> bool:
    """Serve-tier follow mode over a growing file == one-shot read, and
    the stream metrics are live during the run."""
    from cobrix_tpu import prometheus_text, read_cobol
    from cobrix_tpu.serve import ScanServer
    from cobrix_tpu.serve.client import stream_scan
    from cobrix_tpu.testing.faults import LiveAppender
    import pyarrow as pa

    work = tempfile.mkdtemp(prefix="streamcheck-follow-")
    src = os.path.join(work, "feed.dat")
    total = 4000
    open(src, "wb").write(make_records(1000))
    appender = LiveAppender(src, make_records(total - 1000, 1000),
                            slice_sizes=(501, 13, 77),
                            pause_s=0.002)
    srv = ScanServer().start()
    try:
        appender.start()
        stream = stream_scan(
            srv.address, src, copybook_contents=COPYBOOK,
            follow={"poll_interval_s": 0.05, "idle_timeout_s": 5.0},
            max_records=total)
        batches = list(stream)
        got = pa.Table.from_batches(batches) \
            .replace_schema_metadata(None)
        appender.join(10)
        want = read_cobol(src, copybook_contents=COPYBOOK) \
            .to_arrow().replace_schema_metadata(None)
        if not got.equals(want):
            print(f"FAIL [follow]: subscription table != one-shot "
                  f"({got.num_rows} vs {want.num_rows} rows)")
            return False
        token = (stream.summary or {}).get("resume_token") or {}
        if not token.get("watermark"):
            print("FAIL [follow]: trailer token carries no watermark")
            return False
        text = prometheus_text()
        if "cobrix_stream_batches_total" not in text:
            print("FAIL [follow]: cobrix_stream_* metrics missing")
            return False
        print(f"ok [follow]: {got.num_rows} rows streamed live, "
              "watermark token + stream metrics present")
        return True
    finally:
        srv.stop()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep", action="store_true",
                    help="fixed + VRL, more kill cycles (slow)")
    ap.add_argument("--records", type=int, default=6000)
    args = ap.parse_args()
    fixed_opts = {"copybook_contents": COPYBOOK}
    ok = check_exactly_once(
        "fixed", make_records(args.records), fixed_opts,
        kill_cycles=3 if not args.sweep else 5)
    from cobrix_tpu.testing import corpus as _corpus
    ok = check_exactly_once(
        "corpus",
        make_corpus_records(args.records if args.sweep
                            else max(2000, args.records // 3)),
        dict(_corpus.fixed_read_options()),
        kill_cycles=2 if not args.sweep else 4) and ok
    if args.sweep:
        vrl_opts = {"copybook_contents": RDW_COPYBOOK,
                    "is_record_sequence": "true",
                    "generate_record_id": "true"}
        ok = check_exactly_once(
            "vrl", make_rdw_records(args.records), vrl_opts,
            kill_cycles=5) and ok
    ok = check_follow_parity() and ok
    print("STREAMCHECK", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
