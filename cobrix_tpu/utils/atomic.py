"""Crash-safe file writes: temp file + rename for whole artifacts, and
single-syscall O_APPEND appends for record logs — shared by every plane
that persists something (trace export, roofline calibration, block
cache, index store, scan audit log).

The guarantee: a reader never observes a partially-written file under
the final name — it sees the previous complete content or nothing. With
``fsync=True`` the guarantee extends across power loss / process kill on
filesystems that would otherwise surface a zero-length file under the
FINAL name after a crash shortly following the rename (durability
before visibility). Cache planes that can cheaply rebuild a lost entry
skip the fsync; artifacts a human or gate reads (traces, calibrations)
take it.

This module must stay import-light (no package-internal imports): it is
used from ``obs/`` which ``api`` itself imports.
"""
from __future__ import annotations

import os
import tempfile
from typing import Union


def append_line(path: str, line: str) -> int:
    """Append one newline-terminated record to `path` as a SINGLE
    O_APPEND write (creating the file 0666&~umask if absent). POSIX
    O_APPEND makes the offset seek+write atomic per call, so concurrent
    appenders (threads or processes sharing an audit log) interleave
    whole records, never splice bytes mid-record. Returns the bytes
    written so callers can track size for rotation without a stat."""
    if not line.endswith("\n"):
        line += "\n"
    data = line.encode("utf-8")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o666)
    try:
        os.write(fd, data)
    finally:
        os.close(fd)
    return len(data)


def write_atomic(path: str, data: Union[bytes, str],
                 fsync: bool = False) -> None:
    """Write `data` to `path` atomically (temp + rename in the target
    directory). On any failure the temp file is removed and the error
    re-raised; the target is either untouched or fully replaced."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    text = isinstance(data, str)
    fd, tmp = tempfile.mkstemp(dir=parent, prefix=".tmp-")
    try:
        # mkstemp creates 0600; artifacts written through here are read
        # by watchers/other processes (traces, shared cache dirs), so
        # restore the umask-derived mode a plain open() would have used
        um = os.umask(0)
        os.umask(um)
        os.chmod(tmp, 0o666 & ~um)
        with os.fdopen(fd, "w" if text else "wb",
                       encoding="utf-8" if text else None) as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
