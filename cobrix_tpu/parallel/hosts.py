"""Multi-host execution: the §2.5 host axis, actually running.

The reference executes its scan as Spark tasks in executor JVMs — one
process per executor, each opening its assigned byte ranges
(CobolScanners.buildScanForVarLenIndex, CobolScanners.scala:38-55). The
equivalent here: the parent plans shards (sparse index + LPT balancing,
parallel/planner.py) and forks one worker process per "host"; each worker
scans its shard list with the native/numpy kernels and returns its decoded
shards as Arrow IPC buffers (the DCN analogue: only columnar results
cross process boundaries, never raw record bytes — workers read their own
byte ranges from shared storage). The parent reassembles tables in
canonical shard order, so Record_Ids and row order are byte-identical to
a single-process read.

Workers are plain OS processes, not threads: the decode plane's small-op
Python/numpy glue holds the GIL, which caps thread scaling (the shard
scan's native kernels release it, but framing glue and Arrow assembly do
not). Fork semantics keep the parent's parsed copybook/options without
re-importing; workers use only numpy/native/pyarrow (never jax — the
device path belongs to the per-host process).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from .planner import WorkShard, balance

# worker context, set in the parent immediately before forking; inherited
# by fork (never pickled — the reader holds compiled plans)
_CTX: Optional[dict] = None


def _worker_scan(host_shards: List[WorkShard]) -> List[Tuple[tuple, bytes]]:
    """Runs in a worker process: scan each shard, return
    [(shard_key, arrow_ipc_bytes), ...]."""
    import pyarrow as pa

    from ..reader.diagnostics import ReadDiagnostics
    from ..reader.stream import RetryPolicy, open_stream

    ctx = _CTX
    reader = ctx["reader"]
    schema = ctx["schema"]
    params = reader.params
    retry = RetryPolicy(max_attempts=params.io_retry_attempts,
                        base_delay=params.io_retry_base_delay,
                        max_delay=params.io_retry_max_delay,
                        deadline=params.io_retry_deadline)
    out = []
    for shard in host_shards:
        key = (shard.file_order, shard.offset_from)
        retries: List[int] = []
        on_retry = lambda: retries.append(1)  # noqa: E731
        if ctx["is_var_len"]:
            max_bytes = (0 if shard.offset_to < 0
                         else shard.offset_to - shard.offset_from)
            with open_stream(shard.file_path,
                             start_offset=shard.offset_from,
                             maximum_bytes=max_bytes, retry=retry,
                             on_retry=on_retry) as stream:
                result = reader.read_result_columnar(
                    stream, file_id=shard.file_order, backend="numpy",
                    segment_id_prefix=ctx["prefix"],
                    start_record_id=shard.record_index,
                    starting_file_offset=shard.offset_from)
        else:
            max_bytes = (0 if shard.offset_to < 0
                         else shard.offset_to - shard.offset_from)
            with open_stream(shard.file_path,
                             start_offset=shard.offset_from,
                             maximum_bytes=max_bytes, retry=retry,
                             on_retry=on_retry) as stream:
                data = stream.next(stream.size() - shard.offset_from)
            result = reader.read_result(
                data, backend="numpy", file_id=shard.file_order,
                first_record_id=shard.record_index,
                input_file_name=shard.file_path,
                ignore_file_size=ctx["ignore_file_size"])
        table = result.to_arrow(schema)
        diag = getattr(result, "diagnostics", None)
        if retries:
            # retried-but-recovered IO is an incident too (matching the
            # single-process read, which ledgers io_retries even under
            # fail_fast)
            if diag is None:
                diag = ReadDiagnostics()
            diag.io_retries += len(retries)
        if diag is not None and not diag.is_clean:
            # ship the shard's error ledger to the parent on the IPC
            # stream; the parent merges the shards into the read's ledger
            metadata = dict(table.schema.metadata or {})
            metadata[b"cobrix_tpu.shard_diagnostics"] = \
                diag.to_json().encode()
            table = table.replace_schema_metadata(metadata)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        out.append((key, sink.getvalue().to_pybytes()))
    return out


def plan_fixed_len_shards(reader, files: Sequence[str], params,
                          hosts: int) -> List[WorkShard]:
    """Record-boundary slices of fixed-length files, one or more per host
    (the binaryRecords analogue, CobolScanners.scala:92). Files the split
    cannot handle faithfully — file headers/footers, sizes that do not
    divide by the record stride (the divisibility error must fire exactly
    as in a single-process read), or sub-record files — stay whole."""
    from ..reader.parameters import DEFAULT_FILE_RECORD_ID_INCREMENT
    from ..reader.stream import path_scheme

    shards: List[WorkShard] = []
    rs = reader.record_size  # effective stride: overrides + start/end pad
    for file_order, file_path in enumerate(files):
        base = file_order * DEFAULT_FILE_RECORD_ID_INCREMENT
        is_local = path_scheme(file_path) in (None, "file")
        size = os.path.getsize(file_path) if is_local else -1
        splittable = (is_local and hosts > 1 and size >= 2 * rs
                      and size % rs == 0
                      and not params.file_start_offset
                      and not params.file_end_offset)
        if not splittable:
            shards.append(WorkShard(file_path, file_order, 0, -1, base))
            continue
        n_records = size // rs
        per_host = -(-n_records // hosts)
        start = 0
        while start < n_records:
            cnt = min(per_host, n_records - start)
            shards.append(WorkShard(
                file_path, file_order, start * rs, (start + cnt) * rs,
                base + start))
            start += cnt
    return shards


def multihost_scan(reader, shards: Sequence[WorkShard], is_var_len: bool,
                   schema, hosts: int, prefix: str,
                   ignore_file_size: bool = False) -> List:
    """Fork `hosts` workers over a shard plan and reassemble Arrow tables
    in canonical (file_order, offset) order. Returns the ordered list."""
    import multiprocessing as mp

    import pyarrow as pa

    global _CTX

    assignments = [a for a in balance(shards, hosts) if a]

    _CTX = {"reader": reader, "schema": schema, "prefix": prefix,
            "is_var_len": is_var_len, "ignore_file_size": ignore_file_size}
    try:
        if len(assignments) <= 1:
            results = [_worker_scan(a) for a in assignments]
        else:
            ctx = mp.get_context("fork")
            with ctx.Pool(processes=len(assignments)) as pool:
                results = pool.map(_worker_scan, assignments)
    finally:
        _CTX = None

    by_key: Dict[tuple, bytes] = {}
    for host_result in results:
        for key, buf in host_result:
            by_key[key] = buf
    tables = []
    for key in sorted(by_key):
        with pa.ipc.open_stream(pa.py_buffer(by_key[key])) as rd:
            tables.append(rd.read_all())
    return tables
