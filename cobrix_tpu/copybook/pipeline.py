"""AST post-processing pipeline.

Mirrors the transform chain of reference CopybookParser.parseTree
(CopybookParser.scala:225-261): sizes -> offsets -> non-terminals -> dependees
-> fillers -> segment redefines -> segment parents -> debug fields ->
non-filler sizes. Operates in place on the mutable Python AST.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from .ast import Group, Primitive, Statement, transform_identifier
from .datatypes import (
    AlphaNumeric,
    DebugFieldsPolicy,
    Encoding,
    FILLER,
    Integral,
    NON_TERMINALS_POSTFIX,
)


# ---------------------------------------------------------------------------
# sizes & offsets (reference calculateSchemaSizes / getSchemaWithOffsets)
# ---------------------------------------------------------------------------

def calculate_sizes(group: Group) -> None:
    """Bottom-up data/actual sizes; REDEFINES blocks share the running max size."""
    redefined_sizes: List[int] = []
    redefined_names: Set[str] = set()
    redefined_block: List[Statement] = []
    for i, child in enumerate(group.children):
        if child.redefines is None:
            redefined_sizes.clear()
            redefined_names.clear()
            redefined_block.clear()
        else:
            if i == 0:
                from .lexer import CopybookSyntaxError
                raise CopybookSyntaxError(
                    child.line_number, child.name,
                    "The first field of a group cannot use REDEFINES keyword.")
            if child.redefines.upper() not in redefined_names:
                from .lexer import CopybookSyntaxError
                raise CopybookSyntaxError(
                    child.line_number, child.name,
                    f"The field {child.name} redefines {child.redefines}, "
                    "which is not part if the redefined fields block.")
            group.children[i - 1].is_redefined = True

        if isinstance(child, Group):
            calculate_sizes(child)
        else:
            size = child.data_size_bytes()
            child.binary_properties.data_size = size
            child.binary_properties.actual_size = size * child.array_max_size

        redefined_sizes.append(child.binary_properties.actual_size)
        redefined_names.add(child.name.upper())
        redefined_block.append(child)
        if child.redefines is not None:
            max_size = max(redefined_sizes)
            for st in redefined_block:
                st.binary_properties.actual_size = max_size

    group_size = sum(c.binary_properties.actual_size
                     for c in group.children if c.redefines is None)
    group.binary_properties.data_size = group_size
    group.binary_properties.actual_size = group_size * group.array_max_size


def assign_offsets(group: Group, start: int = 0) -> None:
    offset = start
    redefined_offset = start
    for child in group.children:
        if child.redefines is None:
            use_offset = offset
            redefined_offset = offset
        else:
            use_offset = redefined_offset
        child.binary_properties.offset = use_offset
        if isinstance(child, Group):
            assign_offsets(child, use_offset)
        if child.redefines is None:
            offset += child.binary_properties.actual_size
    group.binary_properties.offset = start


def calculate_binary_properties(root: Group) -> Group:
    calculate_sizes(root)
    assign_offsets(root, 0)
    # the root pseudo-group spans from 0
    root.binary_properties.offset = 0
    return root


# ---------------------------------------------------------------------------
# non-terminals (reference addNonTerminals)
# ---------------------------------------------------------------------------

def add_non_terminals(group: Group, non_terminals: Set[str], enc: Encoding) -> None:
    """For each requested group name, add an X(size) primitive redefining the
    whole group so its raw content is also exposed as a string column."""
    if not non_terminals:
        return
    new_children: List[Statement] = []
    for st in group.children:
        if isinstance(st, Primitive):
            new_children.append(st)
            continue
        add_non_terminals(st, non_terminals, enc)
        if st.name in non_terminals:
            st.is_redefined = True
            new_children.append(st)
            existing = {c.name for c in group.children}
            new_name = st.name + NON_TERMINALS_POSTFIX
            modifier = 0
            while new_name in existing:
                modifier += 1
                new_name = st.name + NON_TERMINALS_POSTFIX + str(modifier)
            sz = st.binary_properties.actual_size
            prim = Primitive(
                level=st.level,
                name=new_name,
                line_number=st.line_number,
                dtype=AlphaNumeric(pic=f"X({sz})", length=sz, enc=enc),
                redefines=st.name,
                parent=group,
            )
            from .ast import BinaryProperties
            prim.binary_properties = BinaryProperties(
                st.binary_properties.offset, sz, sz)
            new_children.append(prim)
        else:
            new_children.append(st)
    group.children = new_children


# ---------------------------------------------------------------------------
# DEPENDING ON (reference markDependeeFields)
# ---------------------------------------------------------------------------

def mark_dependee_fields(root: Group,
                         occurs_handlers: Dict[str, Dict[str, int]]) -> None:
    flat_fields: List[Primitive] = []
    dependees: Dict[int, List[Statement]] = {}
    dependee_by_id: Dict[int, Primitive] = {}

    def traverse(group: Group) -> None:
        for field in group.children:
            if field.depending_on is not None:
                name_upper = field.depending_on.upper()
                found = [f for f in flat_fields if f.name.upper() == name_upper]
                if not found:
                    raise ValueError(
                        f"Unable to find dependee field {name_upper} from "
                        "DEPENDING ON clause.")
                if field.name in occurs_handlers:
                    field.depending_on_handlers = dict(occurs_handlers[field.name])
                dependees.setdefault(id(found[0]), []).append(field)
                dependee_by_id[id(found[0])] = found[0]
            if isinstance(field, Group):
                traverse(field)
            else:
                flat_fields.append(field)

    traverse(root)
    for key, stmts in dependees.items():
        prim = dependee_by_id[key]
        if not isinstance(prim.dtype, Integral):
            for stmt in stmts:
                if not stmt.depending_on_handlers:
                    raise ValueError(
                        f"Field {prim.name} is a DEPENDING ON field of an OCCURS, "
                        f"should be integral, found {type(prim.dtype).__name__}.")
        prim.is_dependee = True


# ---------------------------------------------------------------------------
# fillers (reference processGroupFillers / renameGroupFillers)
# ---------------------------------------------------------------------------

def process_group_fillers(group: Group, drop_value_fillers: bool) -> bool:
    """Mark groups consisting only of fillers as fillers themselves.
    Returns True if the group has non-filler content."""
    has_non_fillers = False
    new_children: List[Statement] = []
    for st in group.children:
        if isinstance(st, Group):
            was_filler = st.is_filler  # reference checks the pre-recursion flag
            sub_has = process_group_fillers(st, drop_value_fillers)
            if not sub_has:
                st.is_filler = True
            if st.children:
                new_children.append(st)
            if not was_filler:
                has_non_fillers = True
        else:
            new_children.append(st)
            if not st.is_filler or not drop_value_fillers:
                has_non_fillers = True
    group.children = new_children
    return has_non_fillers


class _FillerCounter:
    def __init__(self):
        self.group = 0
        self.primitive = 0


def rename_group_fillers(root: Group, drop_group_fillers: bool,
                         drop_value_fillers: bool) -> None:
    counter = _FillerCounter()

    def process_primitive(st: Primitive) -> None:
        if not drop_value_fillers and st.is_filler:
            counter.primitive += 1
            st.name = f"{FILLER}_P{counter.primitive}"
            st.is_filler = False

    def rename(group: Group) -> bool:
        """Returns True if the group holds any non-filler child."""
        has_non_fillers = False
        new_children: List[Statement] = []
        for st in group.children:
            if isinstance(st, Group):
                was_filler = st.is_filler
                sub_has = rename(st)
                if sub_has:
                    if st.is_filler and not drop_group_fillers:
                        counter.group += 1
                        st.name = f"{FILLER}_{counter.group}"
                        st.is_filler = False
                else:
                    st.is_filler = True
                if st.children:
                    new_children.append(st)
                if not was_filler:
                    has_non_fillers = True
            else:
                process_primitive(st)
                new_children.append(st)
                if not st.is_filler:
                    has_non_fillers = True
        group.children = new_children
        return has_non_fillers

    if not rename(root):
        raise ValueError("The copybook is empty of consists only of FILLER fields.")


# ---------------------------------------------------------------------------
# segments (reference markSegmentRedefines / setSegmentParents)
# ---------------------------------------------------------------------------

def mark_segment_redefines(root: Group, segment_redefines: Sequence[str]) -> None:
    if not segment_redefines:
        return
    transformed = [transform_identifier(r) for r in segment_redefines]
    allow_non_redefines = len(segment_redefines) == 1
    found: Set[str] = set()
    state = {"v": 0}

    def ensure_in_group(name: str, is_redefine: bool) -> None:
        if state["v"] == 0 and is_redefine:
            state["v"] = 1
        elif state["v"] == 1 and not is_redefine:
            state["v"] = 2
        elif state["v"] == 2 and is_redefine:
            raise ValueError(
                f"The '{name}' field is specified to be a segment redefine. "
                "However, it is not in the same group of REDEFINE fields")

    def is_one_of(g: Group) -> bool:
        # exact-case match like the reference (markSegmentRedefines)
        return ((allow_non_redefines or g.is_redefined or g.redefines is not None)
                and g.name in transformed)

    def process(group: Group) -> None:
        for st in group.children:
            if isinstance(st, Primitive):
                ensure_in_group(st.name, False)
                continue
            if is_one_of(st):
                if st.name in found:
                    raise ValueError(
                        f"Duplicate segment redefine field '{st.name}' found.")
                ensure_in_group(st.name, True)
                found.add(st.name)
                st.is_segment_redefine = True
            else:
                ensure_in_group(st.name, False)
                if state["v"] == 0:
                    process(st)

    for st in root.children:
        if isinstance(st, Group):
            process(st)
    not_found = [r for r in transformed if r not in found]
    if not_found:
        raise ValueError(
            f"The following segment redefines not found: [ {','.join(not_found)} ]. "
            "Please check the fields exist and are redefines/redefined by.")


def set_segment_parents(root: Group, field_parent_map: Dict[str, str]) -> None:
    if not field_parent_map:
        return
    redefined_fields = get_all_segment_redefines(root)
    root_segments: List[str] = []

    def get_parent_field(child_name: str) -> Optional[Group]:
        parent_name = field_parent_map.get(child_name)
        if parent_name is None:
            return None
        for f in redefined_fields:
            if f.name == parent_name:
                return f
        raise ValueError(
            f"Field {parent_name} is specified to be the parent of {child_name}, "
            f"but {parent_name} is not a segment redefine. Please, check if the "
            "field is specified for any of 'redefine-segment-id-map' options.")

    def process(group: Group) -> None:
        for st in group.children:
            if not isinstance(st, Group):
                continue
            if st.is_segment_redefine:
                st.parent_segment = get_parent_field(st.name)
                if st.parent_segment is None:
                    root_segments.append(st.name)
            else:
                if st.name in field_parent_map:
                    raise ValueError(
                        "Parent field is defined for a field that is not a segment "
                        f"redefine. Field: '{st.name}'. Please, check if the field "
                        "is specified for any of 'redefine-segment-id-map' options.")
                process(st)

    process(root)
    if len(root_segments) > 1:
        raise ValueError("Only one root segment is allowed. Found root segments: "
                         f"[ {', '.join(root_segments)} ]. ")
    if not root_segments:
        raise ValueError("No root segment found in the segment parent-child map.")


def get_all_segment_redefines(root: Group) -> List[Group]:
    out: List[Group] = []

    def process(group: Group) -> None:
        for st in group.children:
            if isinstance(st, Group):
                if st.is_segment_redefine:
                    out.append(st)
                process(st)

    process(root)
    return out


def get_parent_to_children_map(root: Group) -> Dict[str, List[Group]]:
    redefines = get_all_segment_redefines(root)
    return {
        parent.name: [child for child in redefines
                      if child.parent_segment is not None
                      and child.parent_segment.name == parent.name]
        for parent in redefines
    }


def get_root_segment_ast(group: Group) -> Group:
    """A copy of the AST with child segments removed (reference getRootSegmentAST)."""
    import copy as _copy
    new_group = _copy.copy(group)
    new_children: List[Statement] = []
    for st in group.children:
        if isinstance(st, Primitive):
            new_children.append(st)
        elif st.parent_segment is None:
            new_children.append(get_root_segment_ast(st))
    new_group.children = new_children
    return new_group


# ---------------------------------------------------------------------------
# debug fields (reference addDebugFields)
# ---------------------------------------------------------------------------

def add_debug_fields(root: Group, policy: DebugFieldsPolicy) -> None:
    if policy is DebugFieldsPolicy.NONE:
        return
    enc = Encoding.HEX if policy is DebugFieldsPolicy.HEX else Encoding.RAW

    def process(group: Group) -> None:
        new_children: List[Statement] = []
        for st in group.children:
            if isinstance(st, Group):
                process(st)
                new_children.append(st)
            else:
                st.is_redefined = True
                new_children.append(st)
                size = st.binary_properties.data_size
                from .ast import BinaryProperties
                dbg = Primitive(
                    level=st.level,
                    name=st.name + "_debug",
                    line_number=st.line_number,
                    dtype=AlphaNumeric(pic=f"X({size})", length=size, enc=enc),
                    redefines=st.name,
                    occurs=st.occurs,
                    to=st.to,
                    depending_on=st.depending_on,
                    is_filler=st.is_filler,
                    parent=group,
                )
                dbg.binary_properties = BinaryProperties(
                    st.binary_properties.offset,
                    st.binary_properties.data_size,
                    st.binary_properties.actual_size)
                new_children.append(dbg)
        group.children = new_children

    process(root)


# ---------------------------------------------------------------------------
# non-filler sizes (reference calculateNonFillerSizes)
# ---------------------------------------------------------------------------

def calculate_non_filler_sizes(root: Group) -> None:
    def process(group: Group) -> None:
        new_children: List[Statement] = []
        for st in group.children:
            if isinstance(st, Group):
                process(st)
                if st.children:
                    new_children.append(st)
            else:
                new_children.append(st)
        group.children = new_children
        group.non_filler_size = sum(
            1 for c in group.children if not c.is_filler and not c.is_child_segment)

    process(root)
    root.non_filler_size = sum(
        1 for c in root.children if not c.is_filler and not c.is_child_segment)


def validate_field_parent_map(field_parent_map: Dict[str, str]) -> None:
    """Detect cycles in the segment parent map (reference validateFieldParentMap)."""
    for field in field_parent_map:
        visited = {field}
        current = field
        while current in field_parent_map:
            current = field_parent_map[current]
            if current in visited:
                raise ValueError(
                    f"Segment parent-child relation map has a cycle involving "
                    f"'{field}'.")
            visited.add(current)
