"""Transactional lakehouse sink: crash-consistent Parquet/Arrow-IPC
datasets driven by the exactly-once ingest ack window.

The streaming tier (`cobrix_tpu.streaming`) promises exactly-once only
"with the consumer's help": record your output position in the ack's
``app_state``, truncate your output back to it on restart. This package
IS that consumer, done right, as a product surface:

* `sink_cobol(tail_cobol(...), dataset_dir)` — continuous
  mainframe→lakehouse pipeline: each micro-batch is staged, finalized,
  and committed by a CRC-stamped manifest record whose position rides
  the checkpoint's ``app_state``; SIGKILL anywhere recovers to a
  dataset byte-identical to a one-shot read of the final sources.
* `read_cobol(...).to_dataset(dataset_dir)` — one-shot atomic batch
  export (one manifest commit; a crash leaves the dataset unchanged).
* `read_dataset(dataset_dir)` — checksum-verified read-back in commit
  order; the committed files are also plain Parquet/Arrow-IPC under
  ``data/``, consumable by any engine.
* `fsck_sink` / ``tools/fsckcache.py --sink`` — offline verify/repair.

Corruption detections count under Prometheus plane ``"sink"``
(``cobrix_cache_corruption_total``); commit/recovery counters are the
``cobrix_sink_*`` series (`obs.metrics.sink_metrics`).
"""
from .drive import SinkResult, sink_cobol, sink_for_ingestor
from .manifest import (
    SinkCorruption,
    SinkError,
    SinkSchemaError,
    schema_fingerprint,
)
from .writer import (
    ADOPT,
    DatasetSink,
    fsck_sink,
    read_dataset,
    set_sink_fault_hook,
)

__all__ = [
    "ADOPT",
    "DatasetSink",
    "SinkCorruption",
    "SinkError",
    "SinkResult",
    "SinkSchemaError",
    "fsck_sink",
    "read_dataset",
    "schema_fingerprint",
    "set_sink_fault_hook",
    "sink_cobol",
    "sink_for_ingestor",
]
