"""Fault-tolerant ingestion: the corruption x policy matrix.

For every corruption class (bit flip, truncated tail, garbage splice,
zero/oversized RDW, flaky storage): `permissive` returns every decodable
record with matching ledger entries and never raises; `drop_malformed`
returns only clean rows; `fail_fast` raises with the file offset and a
hex header snapshot. Indexed scans, the host oracle backend, and the
fixed-length path are held to the same contract.
"""
import json
import os

import pytest

from cobrix_tpu import read_cobol
from cobrix_tpu.reader.diagnostics import (
    FramingError,
    ReadDiagnostics,
    RecordErrorPolicy,
)
from cobrix_tpu.reader.recovery import find_next_rdw, rdw_scan_permissive
from cobrix_tpu.reader.stream import RetryPolicy, open_stream
from cobrix_tpu.testing import corpus
from cobrix_tpu.testing.faults import (
    FlakySource,
    corrupt_record,
    every_structural_truncation,
    field_site,
    flip_bit,
    garbage_run,
    oversize_rdw,
    rdw_record_starts,
    register_flaky_backend,
    splice_garbage,
    truncate,
    zero_rdw,
)
from cobrix_tpu.testing.generators import (
    EXP1_COPYBOOK,
    EXP2_COPYBOOK,
    generate_exp1,
    generate_exp2,
)

import numpy as np


def _write(tmp_path, name, data: bytes) -> str:
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


def _read(path, policy=None, **extra):
    kw = dict(copybook_contents=EXP2_COPYBOOK, is_record_sequence=True)
    if policy:
        kw["record_error_policy"] = policy
    kw.update(extra)
    return read_cobol(path, **kw)


@pytest.fixture(scope="module")
def clean():
    return generate_exp2(60, seed=11)


class TestZeroRdw:
    def test_fail_fast_raises_with_offset_and_hex(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        bad = zero_rdw(clean, starts[7])
        path = _write(tmp_path, "zero.dat", bad)
        with pytest.raises(ValueError) as exc:
            _read(path).to_rows()
        assert str(starts[7]) in str(exc.value)
        assert "00 00 00 00" in str(exc.value)

    def test_permissive_skips_and_ledgers(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        bad = zero_rdw(clean, starts[7])
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        data = _read(_write(tmp_path, "zero.dat", bad), "permissive")
        rows = data.to_rows()
        # the zeroed record is skipped by resync; every other record decodes
        assert rows == good_rows[:7] + good_rows[8:]
        diag = data.diagnostics
        assert diag.resyncs == 1
        assert diag.entries[0].offset == starts[7]
        assert diag.entries[0].reason == "zero-length RDW header"

    def test_drop_malformed_equals_permissive_for_skips(self, tmp_path,
                                                        clean):
        starts = rdw_record_starts(clean)
        bad = zero_rdw(clean, starts[3])
        p = _write(tmp_path, "zero.dat", bad)
        assert _read(p, "drop_malformed").to_rows() \
            == _read(p, "permissive").to_rows()

    def test_resync_rejects_payload_parsed_headers(self, tmp_path):
        """Regression: a resync candidate inside the zeroed header region
        'parses' (leading zeros act as the reserved pair, EBCDIC payload
        bytes as a ~60 KB length) and once hijacked the scan mid-file —
        framing drifted into payloads and a later garbage header clamped
        thousands of records away as a bogus tail. The reserved-pair
        check on successor headers must kill that chain so the resync
        lands on the true next record."""
        big = bytes(generate_exp2(4000, seed=9))
        starts = rdw_record_starts(big)
        bad = zero_rdw(big, starts[1000])
        path = _write(tmp_path, "big_zero.dat", bad)
        data = _read(path, "permissive")
        assert len(data.to_rows()) == 3999
        diag = data.diagnostics
        assert diag.resyncs == 1
        # exactly the corrupt record is skipped: header + payload
        assert diag.bytes_skipped == starts[1001] - starts[1000]
        assert diag.entries[0].offset == starts[1000]


class TestOversizedRdw:
    """A 16-bit RDW can only exceed the 100 MB cap through rdw_adjustment
    (unit-tested on the parser); at the file level 'oversized' means the
    header declares more bytes than the file holds — the reference clamps
    that silently, permissive additionally ledgers the truncation."""

    def test_parser_cap_raises_with_offset_and_hex(self):
        from cobrix_tpu.reader.header_parsers import RdwHeaderParser

        parser = RdwHeaderParser(rdw_adjustment=101 * 1024 * 1024)
        with pytest.raises(ValueError) as exc:
            parser.get_record_metadata(b"\x00\x00\x10\x00", 1234, 0, 0)
        assert "1234" in str(exc.value)
        assert "00 00 10 00" in str(exc.value)

    def test_permissive_ledgers_overrun_header(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        bad = oversize_rdw(clean, starts[5])
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        data = _read(_write(tmp_path, "big.dat", bad), "permissive")
        rows = data.to_rows()
        # the overrun record swallows the rest of the file as a clamped
        # tail; everything before it is untouched and the ledger says so
        assert rows[:5] == good_rows[:5]
        assert len(rows) == 6
        diag = data.diagnostics
        assert diag.corrupt_records == 1
        assert "truncated" in diag.entries[0].reason

    def test_drop_malformed_drops_overrun_record(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        bad = oversize_rdw(clean, starts[5])
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        data = _read(_write(tmp_path, "big.dat", bad), "drop_malformed")
        assert data.to_rows() == good_rows[:5]
        assert data.diagnostics.records_dropped == 1


class TestGarbageSplice:
    def test_permissive_skips_the_splice(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        bad = splice_garbage(clean, starts[10], garbage_run(120, seed=3))
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        data = _read(_write(tmp_path, "spliced.dat", bad), "permissive")
        assert data.to_rows() == good_rows
        diag = data.diagnostics
        assert diag.bytes_skipped == 120
        assert diag.entries[0].offset == starts[10]

    def test_corrupt_run_beyond_window_still_fails(self, tmp_path, clean):
        # all-zero garbage can never look like a header, so a run longer
        # than the window must abort even in permissive mode
        starts = rdw_record_starts(clean)
        bad = splice_garbage(clean, starts[10], b"\x00" * 8192)
        path = _write(tmp_path, "run.dat", bad)
        with pytest.raises(ValueError) as exc:
            _read(path, "permissive", resync_window="1024").to_rows()
        assert "resync window" in str(exc.value)

    def test_unheaderlike_garbage_tail_is_skipped(self, tmp_path, clean):
        # zero bytes can never parse as a header: the whole tail is
        # skipped and the clean rows are untouched
        bad = clean + b"\x00" * 300
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        data = _read(_write(tmp_path, "tailjunk.dat", bad), "permissive")
        assert data.to_rows() == good_rows
        assert data.diagnostics.bytes_skipped == 300

    def test_headerlike_garbage_tail_is_kept_but_ledgered(self, tmp_path,
                                                          clean):
        # garbage whose first bytes parse as a valid RDW is
        # indistinguishable from a legitimate truncated final record:
        # permissive keeps the clamped record and ledgers the truncation,
        # drop_malformed drops it
        bad = clean + garbage_run(300, seed=5)
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        p1 = _write(tmp_path, "tailjunk.dat", bad)
        data = _read(p1, "permissive")
        rows = data.to_rows()
        assert rows[:len(good_rows)] == good_rows
        assert data.diagnostics.corrupt_records == 1
        assert "truncated" in data.diagnostics.entries[0].reason
        assert _read(p1, "drop_malformed").to_rows() == good_rows


class TestTruncatedTail:
    def test_permissive_keeps_partial_record_with_nulled_tail(
            self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        # cut mid-payload of the last record
        cut = starts[-1] + 4 + 10
        path = _write(tmp_path, "torn.dat", truncate(clean, cut))
        good = _read(_write(tmp_path, "good.dat", clean))
        data = _read(path, "permissive")
        rows = data.to_rows()
        good_rows = good.to_rows()
        assert len(rows) == len(good_rows)
        assert rows[:-1] == good_rows[:-1]
        diag = data.diagnostics
        assert diag.corrupt_records == 1
        assert "truncated" in diag.entries[0].reason
        assert diag.entries[0].record_index == len(rows) - 1

    def test_drop_malformed_drops_partial_record(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        cut = starts[-1] + 4 + 10
        path = _write(tmp_path, "torn.dat", truncate(clean, cut))
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        data = _read(path, "drop_malformed")
        assert data.to_rows() == good_rows[:-1]
        assert data.diagnostics.records_dropped == 1

    def test_cut_inside_header_skips_partial_header(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        cut = starts[-1] + 2  # only half an RDW remains
        path = _write(tmp_path, "torn.dat", truncate(clean, cut))
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        assert _read(path, "permissive").to_rows() == good_rows[:-1]

    def test_every_structural_boundary_never_raises(self, tmp_path):
        data = generate_exp2(8, seed=23)
        for cut, torn in every_structural_truncation(data):
            path = _write(tmp_path, f"cut{cut}.dat", torn)
            result = _read(path, "permissive")
            result.to_rows()
            result.to_arrow()


class TestBitFlip:
    def test_payload_damage_never_raises(self, tmp_path, clean):
        # encoder-aware payload damage (an unmapped segment id) instead
        # of an arbitrary byte flip: framing is untouched, so every
        # record still decodes
        starts = rdw_record_starts(clean)
        s, e = starts[4], starts[5]
        bad = (clean[:s]
               + corrupt_record(clean[s:e], "segment-id", header=True,
                                site=field_site(EXP2_COPYBOOK,
                                                "SEGMENT-ID"))
               + clean[e:])
        data = _read(_write(tmp_path, "flip.dat", bad), "permissive")
        assert len(data.to_rows()) == 60

    def test_header_bit_flip_recovers_remaining_records(self, tmp_path,
                                                        clean):
        # flipping a high bit of the little-endian RDW length desyncs the
        # chain mid-file; permissive must resync and keep reading
        starts = rdw_record_starts(clean)
        bad = flip_bit(clean, starts[6] + 3, bit=6)  # length += 16384
        path = _write(tmp_path, "flip.dat", bad)
        data = _read(path, "permissive")
        rows = data.to_rows()
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        # everything before the flip decodes; the flipped record's declared
        # extent swallows the rest of the file, which comes back truncated
        assert rows[:6] == good_rows[:6]
        assert data.diagnostics.corrupt_records >= 1

    @pytest.mark.slow
    def test_fuzz_header_bit_flips_never_raise(self, tmp_path):
        data = generate_exp2(40, seed=31)
        starts = rdw_record_starts(data)
        k = 0
        for s in starts:
            for byte in range(4):
                for bit in (0, 3, 7):
                    bad = flip_bit(data, s + byte, bit=bit)
                    path = _write(tmp_path, f"f{k}.dat", bad)
                    k += 1
                    result = _read(path, "permissive")
                    result.to_rows()
                    result.to_arrow()


class TestEncoderAwareDamage:
    """faults.corrupt_record: every damage class has a SPECIFIC
    diagnostic — packed damage nulls exactly the aimed field with no
    framing entry, RDW damage ledgers a framing reason, an unmapped
    segment id blanks every redefine branch, a torn tail is ledgered
    as a truncation."""

    @pytest.fixture(scope="class")
    def txn(self, tmp_path_factory):
        path = str(tmp_path_factory.mktemp("txn") / "txn.dat")
        info = corpus.write_fixed_corpus(path, 60, seed=21)
        rows = read_cobol(path, **corpus.fixed_read_options()).to_rows()
        return open(path, "rb").read(), info["record_size"], rows

    def _damaged_fixed(self, tmp_path, txn, kind):
        data, rec, good_rows = txn
        site = field_site(corpus.TXN_COPYBOOK, "AMOUNT")
        s = 7 * rec
        bad = (data[:s] + corrupt_record(data[s:s + rec], kind, site=site)
               + data[s + rec:])
        out = read_cobol(_write(tmp_path, "bad.dat", bad),
                         **corpus.fixed_read_options(),
                         record_error_policy="permissive")
        return out, good_rows

    @pytest.mark.parametrize("kind", ["sign-nibble", "packed-digit"])
    def test_packed_damage_nulls_exactly_the_aimed_field(
            self, tmp_path, txn, kind):
        out, good_rows = self._damaged_fixed(tmp_path, txn, kind)
        rows = out.to_rows()
        # the aimed COMP-3 field is None; its neighbors are intact
        assert rows[7][0][3] is None
        assert rows[7][0][:3] == good_rows[7][0][:3]
        assert rows[7][0][4:] == good_rows[7][0][4:]
        assert rows[:7] == good_rows[:7] and rows[8:] == good_rows[8:]
        # field-level damage is NOT a framing fault: the ledger is clean
        assert out.diagnostics.corrupt_records == 0
        assert out.diagnostics.resyncs == 0

    def test_rdw_length_zeroed_ledgers_and_resyncs(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        s, e = starts[5], starts[6]
        bad = (clean[:s] + corrupt_record(clean[s:e], "rdw-length",
                                          header=True, seed=0)
               + clean[e:])
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        data = _read(_write(tmp_path, "rdwz.dat", bad), "permissive")
        assert data.to_rows() == good_rows[:5] + good_rows[6:]
        diag = data.diagnostics
        assert diag.resyncs == 1
        assert diag.entries[0].offset == starts[5]
        assert diag.entries[0].reason == "zero-length RDW header"

    def test_rdw_length_oversized_clamps_tail(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        s, e = starts[5], starts[6]
        bad = (clean[:s] + corrupt_record(clean[s:e], "rdw-length",
                                          header=True, seed=1)
               + clean[e:])
        good_rows = _read(_write(tmp_path, "good.dat", clean)).to_rows()
        data = _read(_write(tmp_path, "rdwo.dat", bad), "permissive")
        rows = data.to_rows()
        assert rows[:5] == good_rows[:5]
        assert len(rows) == 6
        assert data.diagnostics.corrupt_records == 1
        assert "truncated" in data.diagnostics.entries[0].reason

    def test_segment_id_damage_blanks_every_branch(self, tmp_path):
        path = str(tmp_path / "seg.dat")
        corpus.write_multiseg_corpus(path, 30, seed=4)
        data = open(path, "rb").read()
        good_rows = read_cobol(
            path, **corpus.multiseg_read_options()).to_rows()
        starts = rdw_record_starts(data)
        s, e = starts[0], starts[1]
        site = field_site(corpus.MULTISEG_COPYBOOK, "SEGMENT-ID")
        bad = (data[:s] + corrupt_record(data[s:e], "segment-id",
                                         site=site, header=True)
               + data[e:])
        out = read_cobol(_write(tmp_path, "segbad.dat", bad),
                         **corpus.multiseg_read_options(),
                         record_error_policy="permissive")
        rows = out.to_rows()
        # no redefine branch matches the damaged id: every segment
        # column of the row is None; all other rows are untouched
        assert rows[0][0][2] is None and rows[0][0][3] is None
        assert rows[0][0][0] != good_rows[0][0][0]
        assert rows[1:] == good_rows[1:]
        assert out.diagnostics.corrupt_records == 0

    def test_torn_write_ledgers_truncation(self, tmp_path):
        path = str(tmp_path / "seg.dat")
        info = corpus.write_multiseg_corpus(path, 30, seed=4)
        data = open(path, "rb").read()
        bad, sites = corpus.corrupt_multiseg_corpus(
            data, seed=2, kinds=("torn-write",))
        assert sites[-1]["kind"] == "torn-write"
        out = read_cobol(_write(tmp_path, "torn.dat", bad),
                         **corpus.multiseg_read_options(),
                         record_error_policy="permissive")
        assert len(out.to_rows()) == info["records"]
        diag = out.diagnostics
        assert diag.corrupt_records == 1
        assert "truncated" in diag.entries[0].reason


class TestHostOracleParity:
    """The host (per-record oracle) backend applies the same policies."""

    def test_permissive_rows_match_columnar(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        bad = splice_garbage(zero_rdw(clean, starts[3]), starts[12],
                             garbage_run(64, seed=9))
        path = _write(tmp_path, "multi.dat", bad)
        columnar = _read(path, "permissive").to_rows()
        host = _read(path, "permissive", backend="host").to_rows()
        assert host == columnar

    def test_fail_fast_host_raises(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        path = _write(tmp_path, "zero.dat", zero_rdw(clean, starts[3]))
        with pytest.raises(ValueError):
            read_cobol(path, copybook_contents=EXP2_COPYBOOK,
                       is_record_sequence=True, backend="host").to_rows()


class TestIndexedScanUnderCorruption:
    def test_indexed_equals_sequential_under_corruption(self, tmp_path):
        data = generate_exp2(400, seed=17)
        starts = rdw_record_starts(data)
        bad = splice_garbage(zero_rdw(data, starts[100]), starts[300],
                             b"\x00" * 96)
        path = _write(tmp_path, "big.dat", bad)
        kw = dict(copybook_contents=EXP2_COPYBOOK, is_record_sequence=True,
                  record_error_policy="permissive")
        sequential = read_cobol(path, enable_indexes="false", **kw)
        indexed = read_cobol(path, input_split_records=64, **kw)
        assert indexed.to_rows() == sequential.to_rows()
        assert indexed.diagnostics.resyncs >= 2


class TestCorruptRecordColumn:
    def test_debug_column_marks_truncated_row(self, tmp_path, clean):
        starts = rdw_record_starts(clean)
        cut = starts[-1] + 4 + 10
        path = _write(tmp_path, "torn.dat", truncate(clean, cut))
        data = _read(path, "permissive",
                     corrupt_record_column="_corrupt_record")
        assert data.schema.field_names()[-1] == "_corrupt_record"
        rows = data.to_rows()
        assert all(r[-1] is None for r in rows[:-1])
        assert "truncated" in rows[-1][-1]
        table = data.to_arrow()
        col = table.column("_corrupt_record").to_pylist()
        assert col[:-1] == [None] * (len(rows) - 1)
        assert "truncated" in col[-1]

    def test_debug_column_requires_permissive(self, tmp_path, clean):
        path = _write(tmp_path, "good.dat", clean)
        with pytest.raises(ValueError, match="corrupt_record_column"):
            _read(path, corrupt_record_column="_corrupt_record")


class TestFixedLengthTruncation:
    def test_fail_fast_message_is_actionable(self, tmp_path):
        data = generate_exp1(10, seed=3).tobytes()
        path = _write(tmp_path, "f.dat", data[:-7])
        with pytest.raises(ValueError, match="permissive"):
            read_cobol(path, copybook_contents=EXP1_COPYBOOK)

    def test_permissive_keeps_partial_tail_row(self, tmp_path):
        data = generate_exp1(10, seed=3).tobytes()
        path = _write(tmp_path, "f.dat", data[:-7])
        good = read_cobol(_write(tmp_path, "g.dat", data),
                          copybook_contents=EXP1_COPYBOOK).to_rows()
        res = read_cobol(path, copybook_contents=EXP1_COPYBOOK,
                         record_error_policy="permissive")
        rows = res.to_rows()
        assert len(rows) == 10
        assert rows[:9] == good[:9]
        assert res.diagnostics.corrupt_records == 1
        # host oracle parity for the truncated tail row
        host = read_cobol(path, copybook_contents=EXP1_COPYBOOK,
                          record_error_policy="permissive",
                          backend="host").to_rows()
        assert host == rows

    def test_drop_malformed_drops_tail(self, tmp_path):
        data = generate_exp1(10, seed=3).tobytes()
        path = _write(tmp_path, "f.dat", data[:-7])
        res = read_cobol(path, copybook_contents=EXP1_COPYBOOK,
                         record_error_policy="drop_malformed")
        assert len(res.to_rows()) == 9
        assert res.diagnostics.records_dropped == 1


class TestFlakyStorage:
    def test_retry_recovers_transient_failures(self, tmp_path, clean):
        source = register_flaky_backend("flaky1", clean, fail_reads=2)
        data = read_cobol("flaky1://f.dat",
                          copybook_contents=EXP2_COPYBOOK,
                          is_record_sequence=True,
                          record_error_policy="permissive",
                          io_retry_base_delay_ms=1)
        assert len(data.to_rows()) == 60
        assert source.failures_served == 2
        assert data.diagnostics.io_retries == 2

    def test_dead_backend_fails_promptly(self, tmp_path, clean):
        register_flaky_backend("flaky2", clean, fail_forever=True)
        with pytest.raises(IOError, match="attempt"):
            read_cobol("flaky2://f.dat",
                       copybook_contents=EXP2_COPYBOOK,
                       is_record_sequence=True,
                       io_retry_attempts=2, io_retry_base_delay_ms=1,
                       io_retry_deadline_ms=200)

    def test_retry_policy_backoff_is_bounded_and_jittered(self):
        policy = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=0.3)
        delays = [policy.delay(a) for a in range(1, 6)]
        assert all(0.05 <= d <= 0.3 for d in delays)


class TestLedger:
    def test_ledger_caps_entries_but_counts_all(self, tmp_path):
        data = generate_exp2(80, seed=13)
        starts = rdw_record_starts(data)
        bad = data
        for s in reversed(starts[10:50:5]):  # 8 corrupt sites
            bad = zero_rdw(bad, s)
        path = _write(tmp_path, "many.dat", bad)
        res = _read(path, "permissive", max_corrupt_ledger_entries="3")
        res.to_rows()
        diag = res.diagnostics
        assert diag.corrupt_records == 8
        assert len(diag.entries) == 3
        assert diag.entries_truncated

    def test_merge_accumulates(self):
        a = ReadDiagnostics(max_entries=10)
        b = ReadDiagnostics(max_entries=10)
        a.record_skip("f", 0, 10, "zero-length RDW header", b"\0\0\0\0")
        b.record_skip("f", 99, 5, "oversized RDW header", b"\xff\xff\0\0")
        b.io_retries = 3
        a.merge(b)
        assert a.corrupt_records == 2
        assert a.bytes_skipped == 15
        assert a.io_retries == 3
        assert len(a.entries) == 2

    def test_json_round_trip(self):
        d = ReadDiagnostics()
        d.record_skip("f.dat", 42, 7, "zero-length RDW header", b"\0\0\0\0")
        loaded = json.loads(d.to_json())
        assert loaded["entries"][0]["offset"] == 42
        assert loaded["entries"][0]["header_snapshot"] == "00 00 00 00"


class TestRecoveryPrimitives:
    def test_find_next_rdw_finds_clean_record(self):
        clean = generate_exp2(10, seed=1)
        starts = rdw_record_starts(clean)
        buf = np.frombuffer(b"\x00" * 32 + clean, dtype=np.uint8)
        found = find_next_rdw(buf, 1, 200, False, 0, body_end=len(buf))
        assert found == 32

    def test_scan_permissive_clean_file_matches_fail_fast(self):
        from cobrix_tpu import native

        clean = generate_exp2(25, seed=4)
        o1, l1 = native.rdw_scan(clean, False, 0, 0, 0)
        ledger = ReadDiagnostics()
        o2, l2, reasons = rdw_scan_permissive(
            clean, False, 0, 0, 0, RecordErrorPolicy.PERMISSIVE,
            64 * 1024, ledger)
        assert np.array_equal(o1, o2) and np.array_equal(l1, l2)
        assert not reasons and ledger.is_clean

    def test_policy_parse_rejects_unknown(self):
        with pytest.raises(ValueError, match="record_error_policy"):
            RecordErrorPolicy.parse("lenient")

    def test_framing_error_carries_location(self):
        err = FramingError("boom", offset=7, reason="zero-length RDW header",
                           header=b"\0\0\0\0", file_name="x.dat")
        assert isinstance(err, ValueError)
        assert err.offset == 7 and err.file_name == "x.dat"
