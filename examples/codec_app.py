"""Custom record-header-parser read (reference SparkCodecApp +
CustomRecordHeadersParser: a 5-byte header with a validity flag; invalid
records are skipped by the parser, TestDataGen11CustomRDW data)."""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cobrix_tpu import read_cobol
from cobrix_tpu.reader.header_parsers import (RecordHeaderParser,
                                              RecordMetadata)
from cobrix_tpu.testing.generators import (CUSTOM_RDW_COPYBOOK,
                                           generate_custom_rdw)


class CustomFlagHeaderParser(RecordHeaderParser):
    """Byte 0 = validity flag; bytes 3-4 = little-endian payload length."""

    @property
    def header_length(self):
        return 5

    @property
    def is_header_defined_in_copybook(self):
        return False

    def get_record_metadata(self, header, file_offset, file_size,
                            record_num):
        if len(header) < 5:
            return RecordMetadata(-1, False)
        return RecordMetadata(header[3] | (header[4] << 8), header[0] == 1)


def main():
    raw = generate_custom_rdw(500, seed=100)
    with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
        f.write(raw)
        path = f.name
    try:
        result = read_cobol(
            path, copybook_contents=CUSTOM_RDW_COPYBOOK,
            is_record_sequence="true",
            record_header_parser=f"{__name__}.CustomFlagHeaderParser",
            segment_field="SEGMENT-ID",
            redefine_segment_id_map="STATIC-DETAILS => C",
            **{"redefine_segment_id_map:1": "CONTACTS => P"})
        table = result.to_arrow()
    finally:
        os.unlink(path)
    print(f"{table.num_rows} valid records (invalid ones skipped "
          "by the custom header parser)")


if __name__ == "__main__":
    main()
