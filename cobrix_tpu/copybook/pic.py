"""PIC string parsing.

Implements the PIC semantics of the reference front-end
(ParserVisitor.scala:68-104 regex taxonomy, :574-758 visitors, :584-599
COMP-1/2 pseudo-PIC) with a single run-length parser instead of a regex
per grammar branch.

Supported pictures:
  X/A         -> AlphaNumeric                 (length = char count)
  N           -> AlphaNumeric UTF-16          (length = 2 * char count)
  [S]9..      -> Integral
  [S]9..V9..  -> Decimal(scale = fraction digits)
  [S]9..P..   -> Decimal(scale_factor = +k)   (value * 10^k, whole number)
  [S]P..9..   -> Decimal(scale_factor = -k)   (0.00..digits)
  [S]9...9..  -> Decimal(explicit_decimal)    ('.' or ',' in the picture)
  Z variants  -> unsigned Decimal/Integral with leading/trailing blanks
  +/- leading/trailing -> separate sign character
"""
from __future__ import annotations

import re
from dataclasses import replace
from typing import List, Optional, Tuple

from .datatypes import (
    AlphaNumeric,
    Decimal,
    Encoding,
    Integral,
    SignPosition,
    Usage,
)


class PicParseError(ValueError):
    pass


_RUN_RE = re.compile(r"([9XNPZAS+\-VB.,])(?:\((\d+)\))?")


def _expand_runs(text: str) -> List[Tuple[str, int]]:
    """Expand a PIC like 'S9(4)V99' into merged char runs [('S',1),('9',4),('V',1),('9',2)]."""
    runs: List[Tuple[str, int]] = []
    pos = 0
    while pos < len(text):
        m = _RUN_RE.match(text, pos)
        if not m:
            raise PicParseError(f"Error reading PIC {text!r} at position {pos}")
        ch, count = m.group(1), m.group(2)
        n = int(count) if count else 1
        if runs and runs[-1][0] == ch:
            runs[-1] = (ch, runs[-1][1] + n)
        else:
            runs.append((ch, n))
        pos = m.end()
    return runs


def _fmt(ch: str, n: int) -> str:
    return f"{ch}({n})" if n > 0 else ""


def comp1_comp2_type(usage: Usage, enc: Encoding):
    """Pseudo-PIC for a bare COMP-1/COMP-2 field (reference ParserVisitor.scala:584-599)."""
    return Decimal(
        pic="9(16)V9(16)",
        scale=16,
        precision=32,
        scale_factor=0,
        explicit_decimal=False,
        sign_position=None,
        is_sign_separate=False,
        usage=usage,
        enc=enc,
        original_pic=None,
    )


def parse_pic(text: str, enc: Encoding = Encoding.EBCDIC):
    """Parse a PIC string into a CobolType (no USAGE applied yet)."""
    original = text
    text = text.upper()
    runs = _expand_runs(text)
    chars = {ch for ch, _ in runs}

    if chars and chars <= {"X", "A"}:
        length = sum(n for _, n in runs)
        ch = runs[0][0]
        return AlphaNumeric(pic=f"{ch}({length})", length=length, enc=enc, original_pic=original)
    if chars <= {"N"}:
        length = sum(n for _, n in runs)
        return AlphaNumeric(pic=f"N({length})", length=length * 2,
                            enc=Encoding.UTF16, original_pic=original)

    return _parse_numeric(original, runs, enc)


def _parse_numeric(original: str, runs: List[Tuple[str, int]], enc: Encoding):
    # Leading/trailing explicit sign characters and the S flag.
    sign_char: Optional[str] = None
    sign_side: Optional[str] = None  # 'L' or 'T'
    if runs and runs[0][0] in "+-":
        if runs[0][1] != 1:
            raise PicParseError(f"Error reading PIC {original!r}")
        sign_char, sign_side = runs[0][0], "L"
        runs = runs[1:]
    elif runs and runs[-1][0] in "+-":
        if runs[-1][1] != 1:
            raise PicParseError(f"Error reading PIC {original!r}")
        sign_char, sign_side = runs[-1][0], "T"
        runs = runs[:-1]

    has_s = bool(runs) and runs[0][0] == "S"
    if has_s:
        if runs[0][1] != 1:
            raise PicParseError(f"Error reading PIC {original!r}")
        runs = runs[1:]

    # Bucket the remaining runs: [Z1][P_lead][9a][V|dot][P_scale][9b][Z2][P_trail]
    z1 = n1 = p_lead = p_scale = n2 = z2 = p_trail = 0
    seen_sep = False   # V or explicit dot seen
    explicit_dot = False
    seen_digits = False
    for ch, n in runs:
        if ch == "V":
            if seen_sep:
                raise PicParseError(f"Error reading PIC {original!r}")
            seen_sep = True
        elif ch in ".,":
            if seen_sep or n != 1:
                raise PicParseError(f"Error reading PIC {original!r}")
            seen_sep = True
            explicit_dot = True
        elif ch == "9":
            if seen_sep:
                n2 += n
            else:
                n1 += n
            seen_digits = True
        elif ch == "Z":
            if seen_sep:
                z2 += n
            elif seen_digits:
                raise PicParseError(f"Error reading PIC {original!r}")
            else:
                z1 += n
        elif ch == "P":
            if seen_sep:
                p_scale += n
            elif seen_digits:
                p_trail += n
            else:
                p_lead += n
        elif ch == "B":
            raise PicParseError(f"PIC 'B' insertion characters are not supported: {original!r}")
        else:
            raise PicParseError(f"Error reading PIC {original!r}")

    if z1 + n1 + n2 + z2 == 0:
        raise PicParseError(f"Error reading PIC {original!r}")
    is_z = z1 + z2 > 0
    if is_z and has_s:
        # reference Z regexes carry no S flag; explicit +/- signs are fine
        # (grammar rule trailingSign/leadingSign wraps any precision9)
        raise PicParseError(f"Z pictures cannot be signed: {original!r}")

    s_prefix = "S" if has_s else ""
    sign_position = SignPosition.LEFT if has_s else None

    if explicit_dot:
        # reference fromNumericSPicRegexExplicitDot / fromNumericZPicRegexExplicitDot
        pic = (("Z(%d)" % z1 if z1 else "") + s_prefix + _fmt("9", n1)
               + "." + _fmt("9", n2) + _fmt("Z", z2))
        dtype = Decimal(pic=pic, scale=n2 + z2, precision=z1 + n1 + n2 + z2,
                        scale_factor=0, explicit_decimal=True,
                        sign_position=sign_position, enc=enc, original_pic=original)
    elif seen_sep:
        # reference fromNumericSPicRegexDecimalScaled / fromNumericZPicRegexDecimalScaled
        # NOTE: the reference stores the P-run between V and the digits as a
        # *positive* scale factor (ParserVisitor.scala:243) — matched exactly.
        pic = (_fmt("Z", z1) + s_prefix + _fmt("9", n1) + "V"
               + _fmt("P", p_scale) + _fmt("9", n2) + _fmt("Z", z2))
        dtype = Decimal(pic=pic, scale=n2 + z2, precision=z1 + n1 + n2 + z2,
                        scale_factor=p_scale if not is_z else -p_scale,
                        explicit_decimal=False,
                        sign_position=sign_position, enc=enc, original_pic=original)
    elif p_lead:
        # reference fromNumericSPicRegexDecimalScaledLead: value = 0.0..digits
        pic = s_prefix + _fmt("P", p_lead) + _fmt("9", n1)
        dtype = Decimal(pic=pic, scale=0, precision=n1, scale_factor=-p_lead,
                        explicit_decimal=False,
                        sign_position=sign_position, enc=enc, original_pic=original)
    else:
        # reference fromNumericSPicRegexScaled / fromNumericZPicRegexScaled
        pic = _fmt("Z", z1) + s_prefix + _fmt("9", n1) + _fmt("P", p_trail)
        dtype = Decimal(pic=pic, scale=0, precision=z1 + n1, scale_factor=p_trail,
                        explicit_decimal=False,
                        sign_position=sign_position, enc=enc, original_pic=original)

    if sign_char is not None:
        dtype = apply_sign(dtype, sign_side, sign_char, separate=True)
    return dtype


def apply_sign(dtype, side: str, sign: str, separate: bool):
    """Apply a leading/trailing sign (reference ParserVisitor.replaceSign)."""
    position = SignPosition.LEFT if side == "L" else SignPosition.RIGHT
    new_pic = (sign if side == "L" else "") + dtype.pic + (sign if side == "T" else "")
    if isinstance(dtype, (Decimal, Integral)):
        return replace(dtype, pic=new_pic, sign_position=position, is_sign_separate=separate)
    raise PicParseError("Bad test for sign.")
