"""Fused Pallas TPU kernel for the decode hot plane.

The decode of a record batch has two parts: byte *layout* (pulling each
field's bytes out of the `[batch, record_len]` byte matrix) and byte
*arithmetic* (turning those bytes into typed values + validity — the
reference's per-field hot loop, RecordExtractors.scala:49 +
BinaryNumberDecoders.scala:21, BCDNumberDecoders.scala:29,
StringDecoders.scala:154).

Layout stays in XLA: byte ``j`` of every field in a group is one strided
slice `data[:, base+j::stride]` when the group's offsets form an
arithmetic progression (OCCURS arrays — e.g. exp3's `STRATEGY-DETAIL
OCCURS 2000`, TestDataGen4CompaniesWide.scala:37-54), or one gather
`data[:, offsets + j]` for irregular layouts (exp1's 195 heterogeneous
fields). Mosaic (the Pallas TPU compiler) does not support strided lane
slices or u8 lane gathers inside a kernel, so the byte planes are
computed in XLA and flow into the kernel.

Arithmetic is the Pallas kernel: ONE launch decodes every numeric group —
binary two's complement, packed BCD, and zoned DISPLAY (the overpunch
state machine as int32 VPU compare/select math) — over `[BATCH_TILE,
count]` tiles. Values wider than 32 bits (10-18 digit fields, and the
19-38 digit BigDecimal plane) are accumulated in base-2^16 limbs held in
int32 lanes — TPUs have no native int64 — and assembled into int64 /
uint64-pair outputs by XLA after the kernel, so every fused group returns
exactly the tuples the XLA gather path produces (`columnar.
_run_group_jax` contracts). String groups keep the XLA LUT-gather path
(a 256-entry transcode XLA already lowers well); floats and host-fallback
columns are the only other non-fused planes.

Parity is pinned by tests/test_pallas_kernels.py against the numpy
blueprint kernels, on both the interpreter and real TPU.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

BATCH_TILE = 32  # uint8 sublane tile

# 16-bit limbs in int32 lanes: 4 limbs = one 64-bit value, 8 = 128-bit
_LIMBS = {"i32": 1, "i64": 4, "wide": 8}


class StridedGroup:
    """Static decode spec for one fused kernel group.

    base/stride/count describe the offset progression when regular;
    `offsets` carries the raw offsets for irregular groups (the byte
    planes are then XLA gathers). width is the field byte width; kind is
    "binary", "bcd", "display_ebcdic" or "display_ascii"; `out` selects
    the value plane: "i32" (native int32 lanes), "i64" (4x16-bit limbs),
    or "wide" (8x16-bit limbs, the uint128 BigDecimal plane).
    """

    def __init__(self, offsets: Sequence[int], width: int, kind: str,
                 out: str = "i32", signed: bool = False,
                 big_endian: bool = True, allow_dot: bool = False,
                 require_digits: bool = True, dyn_sf: int = 0):
        self.offsets = [int(o) for o in offsets]
        self.count = len(self.offsets)
        self.width = width
        self.kind = kind
        self.out = out
        self.signed = signed
        self.big_endian = big_endian
        self.allow_dot = allow_dot
        self.require_digits = require_digits
        self.dyn_sf = dyn_sf
        self.progression = offsets_progression(self.offsets)

    @property
    def end(self) -> int:
        return max(self.offsets) + self.width

    @property
    def is_display(self) -> bool:
        return self.kind.startswith("display")


def offsets_progression(offsets: Sequence[int]) -> Optional[Tuple[int, int]]:
    """(base, stride) if `offsets` is an increasing arithmetic progression,
    else None. A single column is a progression of stride 0."""
    offs = list(int(o) for o in offsets)
    if not offs:
        return None
    if len(offs) == 1:
        return offs[0], 0
    stride = offs[1] - offs[0]
    if stride <= 0:
        return None
    for a, b in zip(offs, offs[1:]):
        if b - a != stride:
            return None
    return offs[0], stride


def _byte_planes(data, g: StridedGroup):
    """XLA-side layout: byte j of every field in the group, j=0..width-1.
    Strided slice for regular layouts, gather for irregular ones."""
    planes = []
    if g.progression is not None:
        base, stride = g.progression
        for j in range(g.width):
            start = base + j
            if g.count == 1:
                planes.append(jax.lax.slice_in_dim(
                    data, start, start + 1, axis=1))
            else:
                limit = start + (g.count - 1) * stride + 1
                planes.append(jax.lax.slice_in_dim(
                    data, start, limit, stride=stride, axis=1))
    else:
        offs = jnp.asarray(g.offsets, dtype=jnp.int32)
        for j in range(g.width):
            planes.append(jnp.take(data, offs + j, axis=1))
    return planes


# ---------------------------------------------------------------------------
# in-kernel limb arithmetic (base 2^16 in int32 lanes)
# ---------------------------------------------------------------------------

def _limbs_zero(n, shape):
    return [jnp.zeros(shape, dtype=jnp.int32) for _ in range(n)]


def _limbs_mul10_add(limbs, digit, cond=None):
    """limbs <- limbs * 10 + digit, optionally only where `cond`."""
    out = []
    carry = digit
    for l in limbs:
        t = l * 10 + carry          # <= 655350 + 9: fits int32 exactly
        nl = t & 0xFFFF
        carry = t >> 16
        out.append(jnp.where(cond, nl, l) if cond is not None else nl)
    return out


def _limbs_shl8_or(limbs, byte):
    """limbs <- (limbs << 8) | byte (LSB-first limb order)."""
    out = []
    carry = byte
    for l in limbs:
        out.append(((l << 8) | carry) & 0xFFFF)
        carry = l >> 8
    return out


# ---------------------------------------------------------------------------
# in-kernel decode per kind
# ---------------------------------------------------------------------------

def _decode_binary_i32(planes, g: StridedGroup):
    w = g.width
    order = range(w) if g.big_endian else range(w - 1, -1, -1)
    acc = None
    for j in order:
        b = planes[j].astype(jnp.uint32)
        acc = b if acc is None else (acc << 8) | b
    nbits = 8 * w
    valid = jnp.ones(acc.shape, dtype=jnp.bool_)
    if g.signed:
        if nbits == 32:
            values = jax.lax.bitcast_convert_type(acc, jnp.int32)
        else:
            ivals = acc.astype(jnp.int32)
            sign_bit = jnp.uint32(1 << (nbits - 1))
            values = jnp.where((acc & sign_bit) != 0,
                               ivals - jnp.int32(1 << nbits), ivals)
    else:
        # unsigned with the top bit set exceeds the declared precision
        # bucket -> null (BinaryNumberDecoders.scala unsigned-overflow rule)
        if w == 4:
            valid = (acc >> 31) == 0
        # bitcast + typed zero: keeps Mosaic off the x64-promoted int64
        # conversion path; valid values have the top bit clear
        values = jnp.where(valid, jax.lax.bitcast_convert_type(
            acc, jnp.int32), jnp.int32(0))
    return [values, valid]


def _decode_binary_limbs(planes, g: StridedGroup):
    """Two's complement in 16-bit limbs; sign extension at init."""
    n = _LIMBS[g.out]
    w = g.width
    order = range(w) if g.big_endian else range(w - 1, -1, -1)
    first = True
    limbs = _limbs_zero(n, planes[0].shape)
    for j in order:
        b = planes[j].astype(jnp.int32)
        if first and g.signed:
            ext = jnp.where((b & 0x80) != 0, jnp.int32(0xFFFF),
                            jnp.int32(0))
            limbs = [ext for _ in range(n)]
        limbs = _limbs_shl8_or(limbs, b)
        first = False
    valid = jnp.ones(planes[0].shape, dtype=jnp.bool_)
    return limbs + [valid]


def _decode_bcd(planes, g: StridedGroup):
    w = g.width
    shape = planes[0].shape
    if g.out == "i32":
        acc = jnp.zeros(shape, dtype=jnp.int32)
    else:
        limbs = _limbs_zero(_LIMBS[g.out], shape)
    digit_ok = jnp.ones(shape, dtype=jnp.bool_)
    sign = None
    for j in range(w):
        b = planes[j].astype(jnp.int32)
        high = (b >> 4) & 0x0F
        low = b & 0x0F
        digit_ok &= high < 10
        if g.out == "i32":
            acc = acc * 10 + high
        else:
            limbs = _limbs_mul10_add(limbs, high)
        if j + 1 < w:
            digit_ok &= low < 10
            if g.out == "i32":
                acc = acc * 10 + low
            else:
                limbs = _limbs_mul10_add(limbs, low)
        else:
            sign = low
    sign_ok = (sign == 0x0C) | (sign == 0x0D) | (sign == 0x0F)
    valid = digit_ok & sign_ok
    negative = (sign == 0x0D) & valid
    if g.out == "i32":
        values = jnp.where(sign == 0x0D, -acc, acc)
        return [jnp.where(valid, values, jnp.int32(0)), valid]
    limbs = [jnp.where(valid, l, jnp.int32(0)) for l in limbs]
    return limbs + [negative, valid]


def _classify_display_byte(b, ascii_mode: bool):
    """One byte plane -> (is_digit, digit_val, is_sign, is_neg_mark,
    is_dot, is_space, known) as int32/bool lanes (the per-byte rules of
    StringDecoders.decodeEbcdicNumber / decodeAsciiNumber)."""
    # typed zeros throughout: a weak Python 0 inside jnp.where traces as
    # an i64 literal under x64 and Mosaic's convert lowering recurses
    if ascii_mode:
        is_digit = (b >= 0x30) & (b <= 0x39)
        dv = jnp.where(is_digit, b - 0x30, jnp.int32(0))
        is_minus = b == 0x2D
        is_plus = b == 0x2B
        is_dot = (b == 0x2E) | (b == 0x2C)
        is_space = b <= 0x20
        neg_mark = is_minus
        sign_mark = is_minus | is_plus
    else:
        is_f = (b >= 0xF0) & (b <= 0xF9)
        is_c = (b >= 0xC0) & (b <= 0xC9)
        is_d = (b >= 0xD0) & (b <= 0xD9)
        is_digit = is_f | is_c | is_d
        dv = jnp.where(is_f, b - 0xF0,
                       jnp.where(is_c, b - 0xC0,
                                 jnp.where(is_d, b - 0xD0, jnp.int32(0))))
        is_minus = b == 0x60
        is_plus = b == 0x4E
        is_dot = (b == 0x4B) | (b == 0x6B)
        is_space = (b == 0x40) | (b == 0x00)
        neg_mark = is_d | is_minus
        sign_mark = is_c | is_d | is_minus | is_plus
    known = is_digit | sign_mark | is_dot | is_space
    return is_digit, dv, sign_mark, neg_mark, is_dot, is_space, known


def _decode_display(planes, g: StridedGroup):
    """Zoned DISPLAY numeric as VPU compare/select math — the in-kernel
    form of StringDecoders.scala:154 (overpunched signs, separate +/-,
    explicit '.', space skipping, malformed -> null)."""
    ascii_mode = g.kind == "display_ascii"
    shape = planes[0].shape
    zero = jnp.zeros(shape, dtype=jnp.int32)
    if g.out == "i32":
        acc = zero
    else:
        limbs = _limbs_zero(_LIMBS[g.out], shape)
    n_digits = zero
    n_signs = zero
    n_dots = zero
    dots_right = zero
    seen_dot = jnp.zeros(shape, dtype=jnp.bool_)
    negative = jnp.zeros(shape, dtype=jnp.bool_)
    known_all = jnp.ones(shape, dtype=jnp.bool_)

    if ascii_mode:
        # interior-space rule needs lookahead: a space with meaningful
        # bytes on both sides survives into the JVM parse and nulls it
        meaningful = []
        for j in range(g.width):
            b = planes[j].astype(jnp.int32)
            is_digit, _, _, _, is_dot, _, _ = _classify_display_byte(
                b, ascii_mode=True)
            meaningful.append(is_digit | is_dot)
        suffix = [None] * g.width
        later = jnp.zeros(shape, dtype=jnp.bool_)
        for j in range(g.width - 1, -1, -1):
            suffix[j] = later
            later = later | meaningful[j]
        seen_meaningful = jnp.zeros(shape, dtype=jnp.bool_)
        interior_space = jnp.zeros(shape, dtype=jnp.bool_)

    for j in range(g.width):
        b = planes[j].astype(jnp.int32)
        is_digit, dv, sign_mark, neg_mark, is_dot, is_space, known = \
            _classify_display_byte(b, ascii_mode)
        if ascii_mode:
            interior_space |= is_space & seen_meaningful & suffix[j]
            seen_meaningful |= meaningful[j]
        known_all &= known
        seen_dot |= is_dot
        dots_right += (is_digit & seen_dot).astype(jnp.int32)
        n_digits += is_digit.astype(jnp.int32)
        n_dots += is_dot.astype(jnp.int32)
        n_signs += sign_mark.astype(jnp.int32)
        negative |= neg_mark
        if g.out == "i32":
            acc = jnp.where(is_digit, acc * 10 + dv, acc)
        else:
            limbs = _limbs_mul10_add(limbs, dv, cond=is_digit)

    valid = known_all & (n_signs <= 1)
    if ascii_mode:
        valid &= ~interior_space
    if g.require_digits:
        valid &= n_digits >= 1
    valid &= (n_dots <= 1) if g.allow_dot else (n_dots == 0)
    if not g.signed:
        valid &= ~negative
    dots = dots_right if g.dyn_sf >= 0 else (-g.dyn_sf + n_digits)
    dots = jnp.where(valid, dots, zero)
    if g.out == "i32":
        values = jnp.where(negative, -acc, acc)
        return [jnp.where(valid, values, zero), valid, dots]
    limbs = [jnp.where(valid, l, zero) for l in limbs]
    return limbs + [negative & valid, valid, dots]


def _decode_group(planes, g: StridedGroup):
    if g.kind == "binary":
        return (_decode_binary_i32(planes, g) if g.out == "i32"
                else _decode_binary_limbs(planes, g))
    if g.kind == "bcd":
        return _decode_bcd(planes, g)
    return _decode_display(planes, g)


def _out_dtypes(g: StridedGroup):
    """Kernel output dtypes for a group, in _decode_group order."""
    limbs = _LIMBS[g.out]
    if g.kind == "binary":
        return [jnp.int32] * limbs + [jnp.bool_]
    if g.kind == "bcd":
        return ([jnp.int32, jnp.bool_] if g.out == "i32"
                else [jnp.int32] * limbs + [jnp.bool_, jnp.bool_])
    return ([jnp.int32, jnp.bool_, jnp.int32] if g.out == "i32"
            else [jnp.int32] * limbs + [jnp.bool_, jnp.bool_, jnp.int32])


def _fused_kernel(layout, in_ref, o32_ref, obool_ref):
    """ONE kernel for every group: reads each group's byte planes from the
    packed input buffer and writes its outputs into column segments of the
    packed int32 / bool output buffers. Packing matters on TPU: separate
    [batch, count] buffers with tiny counts would each pad to the 128-lane
    tile (a 128x memory blowup for exp1's 1-2 column groups)."""
    for g, in_base, slots in layout:
        planes = [in_ref[:, in_base + j * g.count:
                         in_base + (j + 1) * g.count]
                  for j in range(g.width)]
        for (space, start), arr in zip(slots, _decode_group(planes, g)):
            ref = o32_ref if space == "i32" else obool_ref
            ref[:, start:start + g.count] = arr


# ---------------------------------------------------------------------------
# XLA-side assembly of kernel outputs into the _run_group_jax contracts
# ---------------------------------------------------------------------------

def _assemble_u64(limbs):
    v = jnp.zeros(limbs[0].shape, dtype=jnp.uint64)
    for k in range(3, -1, -1):
        v = (v << 16) | limbs[k].astype(jnp.uint64)
    return v


def _assemble_u128(limbs):
    lo = _assemble_u64(limbs[:4])
    hi = _assemble_u64(limbs[4:8])
    return hi, lo


def _assemble_group(outs, g: StridedGroup):
    """Kernel buffers -> the exact tuple the XLA gather path returns for
    this group (int64 values via x64, uint64 limb pairs for wide)."""
    if g.out == "i32":
        return tuple(outs)
    limbs = outs[:_LIMBS[g.out]]
    rest = outs[_LIMBS[g.out]:]
    if g.kind == "binary":
        (valid,) = rest
        if g.out == "i64":
            v = jax.lax.bitcast_convert_type(_assemble_u64(limbs), jnp.int64)
            if not g.signed and g.width == 8:
                # unsigned 8-byte overflow -> null (JVM Long bucket)
                valid = valid & (v >= 0)
                v = jnp.where(valid, v, jnp.int64(0))
            return v, valid
        hi, lo = _assemble_u128(limbs)
        if g.signed:
            negative = (hi >> 63) != 0
            neg_lo = (~lo) + jnp.uint64(1)
            neg_hi = (~hi) + (neg_lo == 0).astype(jnp.uint64)
            hi = jnp.where(negative, neg_hi, hi)
            lo = jnp.where(negative, neg_lo, lo)
        else:
            negative = jnp.zeros(hi.shape, dtype=jnp.bool_)
        return hi, lo, negative, valid
    # bcd / display carry the magnitude in the limbs and sign separately
    if g.kind == "bcd":
        negative, valid = rest
        tail = ()
    else:
        negative, valid, dots = rest
        tail = (dots,)
    if g.out == "i64":
        # int64 multiply-add wrap semantics == mod-2^64 limb accumulation
        v = jax.lax.bitcast_convert_type(_assemble_u64(limbs), jnp.int64)
        v = jnp.where(negative, -v, v)
        return (v, valid) + tail
    hi, lo = _assemble_u128(limbs)
    return (hi, lo, negative, valid) + tail


def build_fused_decode(groups: Sequence[StridedGroup], record_len: int,
                       interpret: bool | None = None):
    """Returns fn(data: [B, record_len] uint8) -> [group tuples, ...] in
    the `columnar._run_group_jax` output format for each group.

    jit-traceable; pads the batch to the tile size, extracts the byte
    planes in XLA, runs the single fused pallas_call over batch tiles,
    and assembles limb outputs into int64 / uint64-pair planes.
    """
    from jax.experimental import pallas as pl

    from .batch_jax import ensure_x64

    ensure_x64()  # the limb assembly builds int64/uint64 planes
    groups = list(groups)
    need_len = max([record_len] + [g.end for g in groups])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # static layout: each group's byte planes occupy a column range of the
    # packed uint8 input; each output occupies a range of the packed int32
    # or bool output buffer
    layout = []
    in_base = 0
    i32_base = 0
    bool_base = 0
    for g in groups:
        slots = []
        for dtype in _out_dtypes(g):
            if dtype is jnp.bool_:
                slots.append(("bool", bool_base))
                bool_base += g.count
            else:
                slots.append(("i32", i32_base))
                i32_base += g.count
        layout.append((g, in_base, slots))
        in_base += g.width * g.count
    total_in = max(in_base, 1)
    total_i32 = max(i32_base, 1)
    total_bool = max(bool_base, 1)

    def fn(data):
        b = data.shape[0]
        bpad = -b % BATCH_TILE
        lpad = need_len - data.shape[1]
        if bpad or lpad > 0:
            data = jnp.pad(data, ((0, bpad), (0, max(lpad, 0))))
        n_tiles = (b + bpad) // BATCH_TILE

        def batch_row(i):
            # typed zero: under jax_enable_x64 a literal 0 traces as i64
            # and Mosaic rejects the (i32, i64) index tuple
            return (i, jnp.int32(0))

        planes = []
        for g in groups:
            planes.extend(_byte_planes(data, g))
        packed = (jnp.concatenate(planes, axis=1) if planes
                  else data[:, :1])
        o32, obool = pl.pallas_call(
            functools.partial(_fused_kernel, layout),
            grid=(n_tiles,),
            in_specs=[pl.BlockSpec((BATCH_TILE, total_in), batch_row)],
            out_specs=[pl.BlockSpec((BATCH_TILE, total_i32), batch_row),
                       pl.BlockSpec((BATCH_TILE, total_bool), batch_row)],
            out_shape=[jax.ShapeDtypeStruct((b + bpad, total_i32),
                                            jnp.int32),
                       jax.ShapeDtypeStruct((b + bpad, total_bool),
                                            jnp.bool_)],
            interpret=interpret,
        )(packed)
        results = []
        for g, _, slots in layout:
            bufs = []
            for space, start in slots:
                src = o32 if space == "i32" else obool
                bufs.append(src[:b, start:start + g.count])
            results.append(tuple(_assemble_group(bufs, g)))
        return results

    return fn
