"""cobrix_tpu.obs — unified scan telemetry.

Three planes over every execution path (sequential, threaded shard scan,
chunked pipeline, forked multihost):

* **trace** — `Tracer` spans (scan -> shard -> chunk -> stage) with
  Chrome-trace/Perfetto JSON export (`trace_file=` read option) and
  cross-process merge with clock-offset correction;
* **metrics** — `MetricsRegistry` counters/gauges/histograms with
  Prometheus text exposition (`prometheus_text()`);
* **progress** — monotonic `ScanProgress` snapshots pushed to a
  `progress_callback` while the scan runs.

`tools/traceview.py` summarizes a trace file (critical path, stage
utilization, straggler table).
"""
from .context import ObsContext, activate, current
from .fieldcost import FieldCostAccumulator, top_fields
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    prometheus_text,
    scan_metrics,
)
from .progress import ProgressTracker, ScanProgress
from .roofline import (
    cached_bandwidth,
    measured_bandwidth,
    roofline_fraction,
    roofline_summary,
)
from .trace import Tracer, clock_sample, maybe_parent, maybe_span

__all__ = [
    "ObsContext",
    "activate",
    "current",
    "FieldCostAccumulator",
    "top_fields",
    "cached_bandwidth",
    "measured_bandwidth",
    "roofline_fraction",
    "roofline_summary",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "prometheus_text",
    "scan_metrics",
    "ProgressTracker",
    "ScanProgress",
    "Tracer",
    "clock_sample",
    "maybe_parent",
    "maybe_span",
]
