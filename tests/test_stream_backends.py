"""Pluggable stream backends + buffered bounded reads.

Mirrors the reference's FileStreamer/BufferedFSDataInputStream behavior
(FileStreamer.scala:37-130: seek to a partition offset, serve at most
maximumBytes, read storage in large buffered chunks) with a fake remote
backend that records every storage access.
"""
import numpy as np
import pytest

from cobrix_tpu import read_cobol, register_stream_backend
from cobrix_tpu.reader.stream import (BufferedSourceStream, ByteRangeSource,
                                      open_stream, path_scheme)
from cobrix_tpu.testing.generators import (EXP2_COPYBOOK, EXP1_COPYBOOK,
                                           generate_exp1, generate_exp2)


class FakeRemoteSource(ByteRangeSource):
    """In-memory 'remote' object store that logs (offset, n) of every read
    and serves short reads to exercise the readFully loop."""

    store = {}

    def __init__(self, path: str, max_read: int = 0):
        self._path = path
        self._data = self.store[path]
        self._max_read = max_read
        self.reads = []

    def size(self) -> int:
        return len(self._data)

    def read(self, offset: int, n: int) -> bytes:
        self.reads.append((offset, n))
        if self._max_read:
            n = min(n, self._max_read)  # short reads
        return self._data[offset:offset + n]

    @property
    def name(self) -> str:
        return self._path


def test_path_scheme():
    assert path_scheme("s3://bucket/key") == "s3"
    assert path_scheme("file:///tmp/x") == "file"
    assert path_scheme("/tmp/x") is None
    assert path_scheme("C://odd") is None  # drive letters are not schemes


def test_file_scheme_paths_read_like_local(tmp_path):
    data = generate_exp1(4, seed=2)
    p = tmp_path / "f.dat"
    p.write_bytes(data.tobytes())
    kw = dict(copybook_contents=EXP1_COPYBOOK)
    local = read_cobol(str(p), **kw).to_arrow()
    url = read_cobol(f"file://{p}", **kw).to_arrow()
    assert url.equals(local)


def test_unregistered_scheme_raises():
    with pytest.raises(ValueError, match="No stream backend"):
        open_stream("nosuch://x/y")


def test_buffered_stream_seek_bounded_chunked():
    data = bytes(range(256)) * 100  # 25,600 bytes
    src = FakeRemoteSource.__new__(FakeRemoteSource)
    src._path = "fake://x"
    src._data = data
    src._max_read = 0
    src.reads = []
    stream = BufferedSourceStream(src, start_offset=1000,
                                  maximum_bytes=5000, chunk_size=2048)
    assert stream.offset == 1000
    assert stream.size() == 6000          # logical end of the range
    assert stream.true_size == len(data)
    got = b""
    while not stream.is_end_of_stream:
        got += stream.next(100)           # record-sized reads
    assert got == data[1000:6000]
    # storage was hit once per chunk, not once per next()
    assert len(src.reads) == 3            # ceil(5000 / 2048)
    assert src.reads[0] == (1000, 2048)
    # reading past the bound yields nothing
    assert stream.next(10) == b""


def test_buffered_stream_refills_on_short_reads():
    data = b"AB" * 5000
    src = FakeRemoteSource.__new__(FakeRemoteSource)
    src._path = "fake://y"
    src._data = data
    src._max_read = 700                   # storage returns at most 700 B
    src.reads = []
    stream = BufferedSourceStream(src, chunk_size=4096)
    assert stream.next(6000) == data[:6000]
    # the readFully loop re-issued reads until each chunk was full
    assert len(src.reads) >= 6


def test_end_to_end_read_through_registered_backend():
    """read_cobol over a scheme path: variable-length multisegment decode
    through the buffered remote stream equals the local read."""
    register_stream_backend("fake", FakeRemoteSource)
    raw = generate_exp2(3000, seed=6)
    FakeRemoteSource.store["fake://bucket/exp2.dat"] = raw
    kw = dict(copybook_contents=EXP2_COPYBOOK, is_record_sequence="true",
              segment_field="SEGMENT-ID",
              redefine_segment_id_map="STATIC-DETAILS => C",
              redefine_segment_id_map_1="CONTACTS => P",
              segment_id_prefix="R")
    remote = read_cobol("fake://bucket/exp2.dat", **kw).to_arrow()

    import tempfile, os
    p = tempfile.mktemp()
    open(p, "wb").write(raw)
    local = read_cobol(p, **kw).to_arrow()
    os.unlink(p)
    # input file name differs by construction; everything else must match
    drop = [i for i, n in enumerate(remote.schema.names) if n == "File_Name"]
    assert remote.num_rows == local.num_rows == 3000
    assert remote.equals(local)


def test_fixed_length_chunked_read_parity(tmp_path, monkeypatch):
    """The fixed-length path reads in bounded chunks (not one whole-file
    read) and produces identical output."""
    from cobrix_tpu import api

    data = generate_exp1(64, seed=12)
    p = tmp_path / "fixed.dat"
    p.write_bytes(data.tobytes())
    # NB: generate_record_id routes through the var-len reader (reference
    # DefaultSource behavior), bypassing the fixed chunked path
    kw = dict(copybook_contents=EXP1_COPYBOOK)
    whole = read_cobol(str(p), **kw).to_arrow()
    # force chunking: 5 records per chunk
    monkeypatch.setattr(api, "FIXED_READ_CHUNK_BYTES", 5 * data.shape[1])
    chunked = read_cobol(str(p), **kw)
    assert len(chunked._results) > 1
    assert chunked.to_arrow().equals(whole)
