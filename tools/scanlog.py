"""Scan audit-log reader: tail, filter, summarize, and group traces.

The serving tier writes one JSONL ScanRecord per completed / failed /
rejected scan (obs/audit.py, the ``audit_log`` server knob). This tool
is the operator's grep with the schema built in:

    python tools/scanlog.py tail AUDIT.log                  # last 20
    python tools/scanlog.py tail AUDIT.log -n 50 --json
    python tools/scanlog.py tail AUDIT.log --tenant etl \\
                                           --outcome error
    python tools/scanlog.py tail AUDIT.log --trace-id 645c1539...
    python tools/scanlog.py tail AUDIT.log --request-id 0488...
    python tools/scanlog.py summary AUDIT.log               # rollup
    python tools/scanlog.py traceview TRACE.json [...]      # group
    python tools/scanlog.py traceview FLIGHT_DUMP_DIR/      # by id

    # fleet mode: merge per-replica logs (each replica writes its own
    # audit file — point --merge at them or at a glob)
    python tools/scanlog.py summary --merge /var/log/replica-*.log
    python tools/scanlog.py tail --merge r1.log r2.log r3.log \\
                                 --trace-id 645c1539...

* ``tail`` — newest records first, filtered by tenant / outcome /
  trace_id / request_id / breached SLO; resolves "this slow request's
  trace_id" to its audit record (and its flight-recorder dump path,
  when one was written). With ``--merge`` over a fleet's logs, records
  interleave by timestamp and carry a replica column, so one
  ``--trace-id`` (or ``--request-id``) query follows a request ACROSS
  replicas — including failover attempts tied by ``resume_of``.
* ``summary`` — per-tenant and per-outcome counts, latency quantiles
  (queue wait / first batch / e2e), breach counts, byte totals. With
  ``--merge``, a per-replica line each plus the fleet-wide rollup.
* ``traceview`` — loads Chrome-trace artifacts (client-merged files,
  flight-recorder ``trace.json`` dumps, or a directory of either) and
  groups spans by the artifact's ``trace_id``: per request one line of
  span counts, wall span, and the slowest spans — the "which request
  was it" view `tools/traceview.py` (per-artifact deep dive)
  deliberately does not have.

Rotated generations (``AUDIT.log.1`` ...) are included with ``--all``.
Exit code: 0 on success, 1 when a filter matched nothing (so CI can
assert "this request reached the log").
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_records(path: str, include_rotated: bool) -> List:
    from cobrix_tpu.obs.audit import read_audit_log

    return list(read_audit_log(path, include_rotated=include_rotated))


def _expand_paths(paths: List[str]) -> List[str]:
    """Glob-expand each path argument (a fleet points scanlog at
    ``/var/log/replica-*.log``); literal paths pass through so a
    missing file still errors loudly downstream."""
    import glob as _glob

    out: List[str] = []
    for p in paths:
        matches = sorted(_glob.glob(p)) if any(c in p for c in "*?[") \
            else [p]
        for m in (matches or [p]):
            if m not in out:
                out.append(m)
    return out


def _replica_labels(paths: List[str]) -> dict:
    """path -> short replica label: the basename stem when unique
    across the set, else the path relative to the common prefix
    (absolute-normalized first — commonpath refuses mixed
    absolute/relative input)."""
    stems = [os.path.splitext(os.path.basename(p))[0] for p in paths]
    if len(set(stems)) == len(stems):
        return dict(zip(paths, stems))
    resolved = {p: os.path.abspath(p) for p in paths}
    prefix = (os.path.commonpath(list(resolved.values()))
              if len(paths) > 1 else "")
    return {p: (os.path.relpath(r, prefix) if prefix else p)
            for p, r in resolved.items()}


def _args_paths(args) -> tuple:
    """(paths, merge) from an argparse namespace — tolerating the
    pre-fleet single-``path`` shape for programmatic callers (tests
    and scripts drive cmd_tail/cmd_summary with hand-built
    namespaces)."""
    paths = getattr(args, "paths", None)
    if paths is None:
        paths = [args.path]
    return list(paths), bool(getattr(args, "merge", False))


def _load_merged(files: List[str], include_rotated: bool) -> List:
    """Records from every (already-expanded) log file, each stamped
    with its replica label (``rec._replica``), merged oldest-first by
    completion timestamp — the one total order a fleet of
    independently-appending logs has."""
    labels = _replica_labels(files)
    records = []
    for path in files:
        for rec in _load_records(path, include_rotated):
            rec._replica = labels[path]
            records.append(rec)
    records.sort(key=lambda r: r.ts)
    return records


def _fmt_latency(v: Optional[float]) -> str:
    return f"{v * 1000:8.1f}ms" if v is not None else "       - "


def _render(rec, merged: bool = False) -> str:
    flags = ""
    if getattr(rec, "resume_of", ""):
        flags = f" resume_of={rec.resume_of}"
    if rec.slo_breaches:
        flags += " BREACH[" + ",".join(rec.slo_breaches) + "]"
    if rec.dump_path:
        flags += f" dump={rec.dump_path}"
    err = f" err={rec.error}" if rec.error else ""
    replica = (f"{getattr(rec, '_replica', '?'):<12} " if merged
               else "")
    return (f"{replica}{rec.request_id:<17} {rec.tenant:<10} "
            f"{rec.outcome:<8} "
            f"rows={rec.rows:<9} q={_fmt_latency(rec.queue_wait_s)} "
            f"first={_fmt_latency(rec.first_batch_s)} "
            f"e2e={_fmt_latency(rec.e2e_s)} "
            f"trace={rec.trace_id[:12]}{flags}{err}")


def cmd_tail(args) -> int:
    paths, merge = _args_paths(args)
    files = _expand_paths(paths)
    merged = merge or len(files) > 1
    if merged:
        records = _load_merged(files, args.all)
    else:
        records = _load_records(files[0], args.all)
    records.reverse()  # newest first
    out = []
    for rec in records:
        if args.tenant and rec.tenant != args.tenant:
            continue
        if args.outcome and rec.outcome != args.outcome:
            continue
        if args.trace_id and not rec.trace_id.startswith(args.trace_id):
            continue
        if args.request_id and \
                not rec.request_id.startswith(args.request_id) and \
                not getattr(rec, "resume_of",
                            "").startswith(args.request_id):
            # resume_of ties a failover attempt back to the ORIGINAL
            # request_id: one --request-id query shows every attempt
            # of the logical request
            continue
        if args.breached and not rec.slo_breaches:
            continue
        out.append(rec)
        if len(out) >= args.n:
            break
    for rec in out:
        if args.json:
            doc = rec.as_dict()
            if merged:
                doc["replica"] = getattr(rec, "_replica", "?")
            print(json.dumps(doc, sort_keys=True))
        else:
            print(_render(rec, merged=merged))
    if not out:
        print("no matching records", file=sys.stderr)
        return 1
    return 0


def _quantiles(values: List[float]) -> str:
    if not values:
        return "-"
    values = sorted(values)

    def q(f: float) -> float:
        return values[min(len(values) - 1, int(f * len(values)))]

    return (f"p50={q(0.50) * 1000:.1f}ms p95={q(0.95) * 1000:.1f}ms "
            f"p99={q(0.99) * 1000:.1f}ms max={values[-1] * 1000:.1f}ms")


def cmd_summary(args) -> int:
    paths, merge = _args_paths(args)
    files = _expand_paths(paths)
    merged = merge or len(files) > 1
    if merged:
        records = _load_merged(files, args.all)
    else:
        records = _load_records(files[0], args.all)
    if not records:
        print("no records", file=sys.stderr)
        return 1
    if merged:
        # per-replica rollup first: one line each, then the fleet-wide
        # per-tenant view below (the quantiles an SLO is set against)
        by_replica = {}
        for rec in records:
            r = by_replica.setdefault(getattr(rec, "_replica", "?"), {
                "n": 0, "ok": 0, "bad": 0, "rows": 0,
                "queue": [], "first": [], "e2e": []})
            r["n"] += 1
            r["ok" if rec.outcome == "ok" else "bad"] += 1
            r["rows"] += rec.rows
            for key, v in (("queue", rec.queue_wait_s),
                           ("first", rec.first_batch_s),
                           ("e2e", rec.e2e_s)):
                if v is not None:
                    r[key].append(v)
        print(f"fleet: {len(records)} records from "
              f"{len(by_replica)} replica log(s)")
        for replica in sorted(by_replica):
            r = by_replica[replica]
            print(f"replica {replica}: n={r['n']} ok={r['ok']} "
                  f"not_ok={r['bad']} rows={r['rows']}")
            print(f"  queue wait   {_quantiles(r['queue'])}")
            print(f"  first batch  {_quantiles(r['first'])}")
            print(f"  e2e          {_quantiles(r['e2e'])}")
        print("\nfleet-wide:")
    by_tenant = {}
    for rec in records:
        t = by_tenant.setdefault(rec.tenant, {
            "ok": 0, "error": 0, "rejected": 0, "client_gone": 0,
            "rows": 0, "bytes": 0, "pruned": 0, "filtered": 0,
            "queue": [], "first": [], "e2e": [], "breaches": 0})
        t[rec.outcome] = t.get(rec.outcome, 0) + 1
        t["rows"] += rec.rows
        t["bytes"] += rec.bytes_streamed
        t["breaches"] += 1 if rec.slo_breaches else 0
        # filter-pushdown rollup: a tenant whose scans prune heavily is
        # reading few rows because it ASKED for few, not because its
        # files are tiny — the distinction fleet capacity planning needs
        t["pruned"] += getattr(rec, "records_pruned", 0) or 0
        if getattr(rec, "selectivity", None) is not None:
            t["filtered"] += 1
        for key, v in (("queue", rec.queue_wait_s),
                       ("first", rec.first_batch_s),
                       ("e2e", rec.e2e_s)):
            if v is not None:
                t[key].append(v)
    print(f"{len(records)} records, {len(by_tenant)} tenant(s)")
    for tenant in sorted(by_tenant):
        t = by_tenant[tenant]
        line = (f"\ntenant {tenant}: ok={t['ok']} error={t['error']} "
                f"rejected={t['rejected']} "
                f"client_gone={t['client_gone']} rows={t['rows']} "
                f"streamed={t['bytes'] / 1e6:.1f}MB "
                f"slo_breaches={t['breaches']}")
        if t["filtered"]:
            line += (f" filtered_scans={t['filtered']} "
                     f"records_pruned={t['pruned']}")
        print(line)
        print(f"  queue wait   {_quantiles(t['queue'])}")
        print(f"  first batch  {_quantiles(t['first'])}")
        print(f"  e2e          {_quantiles(t['e2e'])}")
    return 0


def _trace_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in files
                           if f.endswith(".json"))
        else:
            out.append(p)
    return sorted(out)


def cmd_traceview(args) -> int:
    """Group Chrome-trace artifacts by trace_id: one summary line per
    request plus its slowest spans."""
    groups = {}
    for path in _trace_files(args.paths):
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            continue  # not a trace artifact (e.g. a dump's record.json)
        trace_id = str(doc.get("trace_id") or "untagged")
        g = groups.setdefault(trace_id, {"files": [], "spans": [],
                                         "meta": {}})
        g["files"].append(path)
        for ev in events:
            if ev.get("ph") != "X":
                continue
            g["spans"].append((ev.get("name", "?"),
                               float(ev.get("dur", 0.0)) / 1e6,
                               float(ev.get("ts", 0.0)) / 1e6,
                               ev.get("pid")))
            ev_args = ev.get("args") or {}
            for key in ("request_id", "tenant"):
                if key in ev_args:
                    g["meta"][key] = ev_args[key]
    if not groups:
        print("no trace artifacts found", file=sys.stderr)
        return 1
    for trace_id in sorted(groups):
        g = groups[trace_id]
        spans = g["spans"]
        t0 = min((s[2] for s in spans), default=0.0)
        t1 = max((s[2] + s[1] for s in spans), default=0.0)
        pids = {s[3] for s in spans}
        meta = " ".join(f"{k}={v}" for k, v in sorted(g["meta"].items()))
        print(f"trace {trace_id}: {len(spans)} spans, "
              f"{len(pids)} process(es), wall {t1 - t0:.3f}s, "
              f"{len(g['files'])} artifact(s) {meta}")
        for name, dur, _ts, _pid in sorted(
                spans, key=lambda s: -s[1])[:args.top]:
            print(f"    {name:<28} {dur * 1000:10.2f}ms")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)

    tail = sub.add_parser("tail", help="newest records, filtered")
    tail.add_argument("paths", nargs="+",
                      help="audit log(s); globs allowed with --merge "
                           "(multiple files imply it)")
    tail.add_argument("--merge", action="store_true",
                      help="merge several replicas' logs by timestamp "
                           "with a replica column (fleet mode)")
    tail.add_argument("-n", type=int, default=20)
    tail.add_argument("--tenant", default="")
    tail.add_argument("--outcome", default="",
                      choices=("", "ok", "error", "rejected",
                               "client_gone"))
    tail.add_argument("--trace-id", default="",
                      help="prefix match on trace_id")
    tail.add_argument("--request-id", default="",
                      help="prefix match on request_id (also matches "
                           "resumed attempts via their resume_of tie)")
    tail.add_argument("--breached", action="store_true",
                      help="only scans that breached an SLO")
    tail.add_argument("--json", action="store_true",
                      help="raw JSONL instead of columns")
    tail.add_argument("--all", action="store_true",
                      help="include rotated generations")
    tail.set_defaults(fn=cmd_tail)

    summary = sub.add_parser("summary", help="per-tenant rollup")
    summary.add_argument("paths", nargs="+",
                         help="audit log(s); globs allowed with "
                              "--merge (multiple files imply it)")
    summary.add_argument("--merge", action="store_true",
                         help="per-replica lines + fleet-wide rollup "
                              "over several replicas' logs")
    summary.add_argument("--all", action="store_true")
    summary.set_defaults(fn=cmd_summary)

    tv = sub.add_parser(
        "traceview",
        help="group Chrome-trace artifacts by trace_id")
    tv.add_argument("paths", nargs="+",
                    help="trace JSON file(s) or directories "
                         "(flight-recorder dumps)")
    tv.add_argument("--top", type=int, default=5,
                    help="slowest spans to list per trace")
    tv.set_defaults(fn=cmd_traceview)

    args = ap.parse_args()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
