"""Admission control: per-tenant quotas + weighted fair-share queueing.

The workload-management shape of production query services (PAPERS.md:
"Amazon Redshift re-invented" WLM): a scan is either admitted
immediately (tenant below its concurrency quota AND the server below
its global cap), queued (bounded depth, bounded wait), or rejected with
a structured reason. When capacity frees, the next scan is picked by
weighted fair share — the waiting tenant with the smallest
served-work/weight virtual time goes first, so a tenant flooding the
queue cannot starve the others, and a tenant with weight 2 drains twice
as fast as one with weight 1.

The second quota dimension is bytes: `max_inflight_bytes` bounds how
much assembled-but-not-yet-written Arrow data one tenant's scans may
hold (the streaming reorder buffer + frames being written). Producers
BLOCK on the byte gate — backpressure, not rejection — and time out
into a scan error only after `byte_wait_timeout_s` of zero drain (a
stuck client must not pin server memory forever).

The third dimension is the process itself: when a memory budget is
configured (utils.pressure — the serve CLI's ``--memory-budget-mb``)
and RSS crosses the SHED watermark, admission stops absorbing work
instead of letting the OOM-killer end every tenant at once. New
requests are refused with a structured ``overloaded`` reason, and
queued waiters are shed lowest-weight-first (the fair-share weight is
also the keep-under-pressure priority) until the queue halves. Scans
already admitted keep running — shedding protects them.

Everything is condition-variable based and deadline-bounded: no wait in
this module is infinite.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, Optional

from ..obs.metrics import serve_metrics


@dataclass(frozen=True)
class TenantQuota:
    """One tenant's admission limits."""

    # scans this tenant may run concurrently
    max_concurrent: int = 4
    # scans this tenant may hold waiting in the admission queue; the
    # (max_queued + 1)-th concurrent request is REJECTED, not queued
    max_queued: int = 16
    # fair-share weight (2.0 drains the queue twice as fast as 1.0)
    weight: float = 1.0
    # bytes of assembled Arrow data this tenant's scans may hold
    # in flight toward clients before producers block (0 = unbounded)
    max_inflight_bytes: int = 256 * 1024 * 1024
    # concurrent follow subscriptions (serve follow=true). Followers
    # are long-lived BY DESIGN — they hold a scan slot for hours — so
    # they get their own, tighter ceiling inside max_concurrent: a
    # tenant cannot park followers on every slot and starve its own
    # bounded scans
    max_followers: int = 2


class AdmissionRejected(Exception):
    """Structured admission refusal; `reason` is machine-readable."""

    def __init__(self, tenant: str, reason: str, detail: str):
        super().__init__(detail)
        self.tenant = tenant
        self.reason = reason


class _Waiter:
    __slots__ = ("tenant", "granted", "abandoned", "shed", "follower")

    def __init__(self, tenant: str, follower: bool = False):
        self.tenant = tenant
        self.granted = False
        self.abandoned = False
        self.shed = False  # evicted by overload shedding
        self.follower = follower  # long-lived follow subscription


class AdmissionController:
    """Admission decisions for one server process.

    `admit(tenant)` blocks (fairly, up to `queue_timeout_s`) until the
    scan may run and returns a ticket to pass to `release`; it raises
    AdmissionRejected when the tenant's queue is full or the wait times
    out. One controller serves every front-end (TCP, flight) of a
    ScanServer."""

    def __init__(self, default_quota: Optional[TenantQuota] = None,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 max_concurrent_scans: int = 16,
                 queue_timeout_s: float = 30.0,
                 byte_wait_timeout_s: float = 60.0,
                 metrics: Optional[dict] = None,
                 pressure=None):
        self.default_quota = default_quota or TenantQuota()
        self.quotas = dict(quotas or {})
        self.max_concurrent_scans = max(1, int(max_concurrent_scans))
        self.queue_timeout_s = max(0.0, float(queue_timeout_s))
        self.byte_wait_timeout_s = max(0.0, float(byte_wait_timeout_s))
        # memory watermark source: an explicit utils.pressure
        # MemoryPressure, else the process-wide monitor (None installed
        # = never sheds)
        self._pressure = pressure
        self.scans_shed = 0
        self._m = metrics if metrics is not None else serve_metrics()
        self._cond = threading.Condition()
        self._active: Dict[str, int] = {}
        # per-tenant FIFO of waiters; OrderedDict keeps tenant order
        # deterministic when virtual times tie
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        # weighted fair share: work served per tenant / weight. New or
        # returning tenants start at the current floor so an idle spell
        # doesn't bank unbounded credit
        self._vtime: Dict[str, float] = {}
        self._inflight_bytes: Dict[str, int] = {}
        # long-lived follow subscriptions currently admitted, per
        # tenant (a subset of _active; bounded by quota.max_followers)
        self._followers: Dict[str, int] = {}

    def quota(self, tenant: str) -> TenantQuota:
        return self.quotas.get(tenant, self.default_quota)

    # -- overload shedding -----------------------------------------------

    def pressure_level(self) -> int:
        from ..utils.pressure import current_level

        if self._pressure is not None:
            return self._pressure.level()
        return current_level()

    def _shed_queued_locked(self) -> int:
        """Evict queued waiters lowest-weight-first until the queue is
        at most half its current depth (admitted scans are untouched —
        shedding exists to let them finish). Evicted waiters' admit()
        calls raise a structured ``overloaded`` rejection, newest
        request first within a tenant (the oldest waiter kept its place
        longest). Returns the count shed."""
        total = sum(len(q) for q in self._queues.values())
        if total == 0:
            return 0
        target = total // 2
        shed = 0
        tenants = sorted(self._queues,
                         key=lambda t: (self.quota(t).weight, t))
        for tenant in tenants:
            q = self._queues.get(tenant)
            while q and total - shed > target:
                waiter = q.pop()  # newest first
                waiter.shed = True
                shed += 1
            if q is not None and not q:
                self._queues.pop(tenant, None)
            if total - shed <= target:
                break
        if shed:
            self.scans_shed += shed
            self._cond.notify_all()
        return shed

    # -- scan admission --------------------------------------------------

    def admit(self, tenant: str, follower: bool = False) -> _Waiter:
        """Block until this scan may run; returns the ticket for
        `release`. Raises AdmissionRejected (queue_full / queue_timeout
        / follower_quota / overloaded) — never hangs past
        `queue_timeout_s`. `follower` marks a long-lived follow
        subscription: it holds an ordinary weighted scan slot, but is
        additionally bounded by the tenant's `max_followers` so parked
        subscriptions cannot starve the tenant's own bounded scans."""
        from ..utils.pressure import LEVEL_SHED

        quota = self.quota(tenant)
        t0 = time.monotonic()
        if follower:
            with self._cond:
                if self._followers.get(tenant, 0) >= quota.max_followers:
                    self._m["rejected"].labels(
                        tenant=tenant, reason="follower_quota").inc()
                    raise AdmissionRejected(
                        tenant, "follower_quota",
                        f"tenant '{tenant}' already holds "
                        f"{self._followers[tenant]} follow "
                        f"subscription(s) "
                        f"(max_followers={quota.max_followers}); close "
                        "one or raise the quota")
        if self.pressure_level() >= LEVEL_SHED:
            # over the memory shed watermark: refuse new work AND shed
            # queued waiters (lowest weight first) so admitted scans
            # keep their memory and finish — the alternative is the
            # OOM-killer ending every tenant at once
            with self._cond:
                shed = self._shed_queued_locked()
            self._m["rejected"].labels(
                tenant=tenant, reason="overloaded").inc()
            raise AdmissionRejected(
                tenant, "overloaded",
                f"server is over its memory budget (shedding load"
                f"{f', evicted {shed} queued scan(s)' if shed else ''});"
                " retry later or on another replica")
        with self._cond:
            if self._can_run_locked(tenant, quota, follower=follower) \
                    and not self._queues.get(tenant):
                self._grant_locked(tenant, follower=follower)
                self._observe_admit(tenant, t0)
                return _Waiter(tenant, follower=follower)
            q = self._queues.setdefault(tenant, deque())
            if len(q) >= quota.max_queued:
                self._m["rejected"].labels(
                    tenant=tenant, reason="queue_full").inc()
                raise AdmissionRejected(
                    tenant, "queue_full",
                    f"tenant '{tenant}' already has {quota.max_concurrent}"
                    f" active scan(s) and {len(q)} queued "
                    f"(max_queued={quota.max_queued}); retry later")
            waiter = _Waiter(tenant, follower=follower)
            q.append(waiter)
            self._m["queued"].inc()
            try:
                deadline = t0 + self.queue_timeout_s
                while not waiter.granted:
                    if waiter.shed:
                        self._prune_vtime_locked(tenant)
                        self._m["rejected"].labels(
                            tenant=tenant, reason="overloaded").inc()
                        raise AdmissionRejected(
                            tenant, "overloaded",
                            f"queued scan for tenant '{tenant}' shed "
                            "under memory pressure; retry later or on "
                            "another replica")
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        waiter.abandoned = True
                        self._remove_waiter_locked(tenant, waiter)
                        self._prune_vtime_locked(tenant)
                        self._m["rejected"].labels(
                            tenant=tenant, reason="queue_timeout").inc()
                        raise AdmissionRejected(
                            tenant, "queue_timeout",
                            f"scan for tenant '{tenant}' waited "
                            f"{self.queue_timeout_s:.1f}s in the "
                            "admission queue without a free slot")
                    self._cond.wait(remaining)
            finally:
                self._m["queued"].dec()
            self._observe_admit(tenant, t0)
            return waiter

    def release(self, ticket: _Waiter) -> None:
        with self._cond:
            tenant = ticket.tenant
            if ticket.follower:
                left = max(0, self._followers.get(tenant, 0) - 1)
                if left:
                    self._followers[tenant] = left
                else:
                    self._followers.pop(tenant, None)
            self._active[tenant] = max(0, self._active.get(tenant, 0) - 1)
            if not self._active[tenant]:
                self._active.pop(tenant)
            self._m["active"].dec()
            self._wake_next_locked()
            self._prune_vtime_locked(tenant)

    def _prune_vtime_locked(self, tenant: str) -> None:
        """Drop a fully-idle tenant's virtual time. Keeping it would (a)
        grow the dict one entry per tenant name ever seen and (b) make
        the stale entry the fair-share floor, handing the tenant banked
        credit when it returns — the opposite of the floor's intent. A
        returning tenant re-enters at the floor of the tenants actually
        competing."""
        if not self._active.get(tenant) and not self._queues.get(tenant):
            self._vtime.pop(tenant, None)

    def _observe_admit(self, tenant: str, t0: float) -> None:
        self._m["admitted"].labels(tenant=tenant).inc()
        self._m["queue_wait"].observe(time.monotonic() - t0)

    def _can_run_locked(self, tenant: str, quota: TenantQuota,
                        follower: bool = False) -> bool:
        total = sum(self._active.values())
        if follower and self._followers.get(tenant, 0) \
                >= quota.max_followers:
            return False
        return (total < self.max_concurrent_scans
                and self._active.get(tenant, 0) < quota.max_concurrent)

    def _grant_locked(self, tenant: str, follower: bool = False) -> None:
        self._active[tenant] = self._active.get(tenant, 0) + 1
        if follower:
            self._followers[tenant] = self._followers.get(tenant, 0) + 1
        self._m["active"].inc()
        # fair-share bookkeeping: one admitted scan = 1/weight of
        # virtual work, floored at the current minimum so returning
        # tenants don't replay banked idle time
        weight = max(1e-6, self.quota(tenant).weight)
        floor = min(self._vtime.values()) if self._vtime else 0.0
        self._vtime[tenant] = max(self._vtime.get(tenant, floor),
                                  floor) + 1.0 / weight

    def _remove_waiter_locked(self, tenant: str, waiter: _Waiter) -> None:
        q = self._queues.get(tenant)
        if q:
            try:
                q.remove(waiter)
            except ValueError:
                pass
            if not q:
                self._queues.pop(tenant, None)

    def _wake_next_locked(self) -> None:
        """Grant freed capacity to queued waiters, tenant-fairly: among
        tenants whose head-of-queue could run, pick the one with the
        lowest virtual time."""
        while True:
            best = None
            for tenant, q in self._queues.items():
                if not q:
                    continue
                if not self._can_run_locked(tenant, self.quota(tenant),
                                            follower=q[0].follower):
                    continue
                floor = min(self._vtime.values()) if self._vtime else 0.0
                vt = self._vtime.get(tenant, floor)
                if best is None or vt < best[1]:
                    best = (tenant, vt)
            if best is None:
                break
            tenant = best[0]
            waiter = self._queues[tenant].popleft()
            if not self._queues[tenant]:
                self._queues.pop(tenant, None)
            if waiter.abandoned:
                continue
            waiter.granted = True
            self._grant_locked(tenant, follower=waiter.follower)
        self._cond.notify_all()

    # -- the in-flight byte gate ----------------------------------------

    def acquire_bytes(self, tenant: str, n: int,
                      timeout_s: Optional[float] = None) -> None:
        """Block until `n` more in-flight bytes fit the tenant's budget
        (backpressure on the assembly stage). A single batch larger
        than the whole budget is admitted alone rather than deadlocking.
        Raises TimeoutError after `timeout_s` (default
        `byte_wait_timeout_s`) without drain — callers that can create
        drain themselves (OrderedBatchEmitter flushing past a
        newly-failed chunk) pass short slices and retry."""
        budget = self.quota(tenant).max_inflight_bytes
        if budget <= 0 or n <= 0:
            return
        wait_s = (self.byte_wait_timeout_s if timeout_s is None
                  else max(0.0, float(timeout_s)))
        deadline = time.monotonic() + wait_s
        last_held = None
        with self._cond:
            while True:
                held = self._inflight_bytes.get(tenant, 0)
                if held + n <= budget or held == 0:
                    self._inflight_bytes[tenant] = held + n
                    return
                if last_held is not None and held < last_held:
                    # the client IS draining, just slowly: observed
                    # progress re-arms the clock — the timeout fires
                    # only after byte_wait_timeout_s of ZERO drain, as
                    # documented
                    deadline = time.monotonic() + wait_s
                last_held = held
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"tenant '{tenant}' held {held} in-flight bytes "
                        f"against a {budget} byte budget for "
                        f"{wait_s:.0f}s without drain "
                        "(client too slow or gone)")
                self._cond.wait(min(remaining, 0.5))

    def inflight_bytes(self, tenant: str) -> int:
        """Current charged bytes — lets slice-waiting callers
        (OrderedBatchEmitter._acquire_gate) observe drain progress
        across their own short acquire attempts."""
        with self._cond:
            return self._inflight_bytes.get(tenant, 0)

    def release_bytes(self, tenant: str, n: int) -> None:
        if n <= 0:
            return
        with self._cond:
            held = self._inflight_bytes.get(tenant, 0)
            held = max(0, held - n)
            if held:
                self._inflight_bytes[tenant] = held
            else:
                self._inflight_bytes.pop(tenant, None)
            self._cond.notify_all()

    # -- introspection (healthz) ----------------------------------------

    def snapshot(self) -> dict:
        with self._cond:
            tenants = sorted(set(self._active) | set(self._queues)
                             | set(self._inflight_bytes))
            out = {
                "active_scans": sum(self._active.values()),
                "queued_scans": sum(len(q) for q in
                                    self._queues.values()),
                "max_concurrent_scans": self.max_concurrent_scans,
                "scans_shed": self.scans_shed,
                "tenants": {
                    t: {"active": self._active.get(t, 0),
                        "queued": len(self._queues.get(t, ())),
                        "followers": self._followers.get(t, 0),
                        "inflight_bytes":
                            self._inflight_bytes.get(t, 0)}
                    for t in tenants},
            }
        monitor = self._pressure
        if monitor is None:
            from ..utils.pressure import process_pressure

            monitor = process_pressure()
        if monitor is not None:
            out["pressure"] = monitor.snapshot()
        return out
