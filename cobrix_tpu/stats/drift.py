"""Ingest drift: successive-generation profile comparison.

The continuous-ingest tailer (streaming/ingest.py) folds every decoded
batch of the LIVE generation into a :class:`GenerationProfile`
(``collect_stats=true``); when the feed rotates, the finished
generation is compared against its predecessor and material shifts
become drift records:

* ``segment_mix``   — L1 distance between normalized segment-id
  distributions above :data:`SEGMENT_MIX_L1`,
* ``null_rate``     — a field's null rate rising by more than
  :data:`NULL_RATE_RISE` (absolute),
* ``out_of_range``  — a field's observed min/max escaping the previous
  generation's envelope,
* ``record_length`` — the average record length shifting by more than
  :data:`RECORD_LENGTH_SHIFT` (relative).

Drift records are observability, not enforcement: they land on the
stream metrics (``cobrix_stats_drift_events_total{kind=...}``), the
stats service registry (the sidecar's ``/stats``), and a JSONL audit
trail under ``<cache_dir>/stats/drift.jsonl`` — the feed itself is
never blocked.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .profile import FieldStats, _encode_value

SEGMENT_MIX_L1 = 0.2
NULL_RATE_RISE = 0.1
RECORD_LENGTH_SHIFT = 0.1


class GenerationProfile:
    """One feed generation's rolled-up statistics, folded batch by
    batch (bounded state: one merged FieldStats per leaf)."""

    def __init__(self, name: str, seg_leaf: str = ""):
        self.name = name
        self.seg_leaf = seg_leaf
        self.records = 0
        self.bytes = 0
        self.fields: Dict[str, FieldStats] = {}
        self.segments: Dict[str, int] = {}

    def fold(self, table, nbytes: int = 0) -> None:
        from .collect import profile_table

        fields, _kinds, segments = profile_table(table, self.seg_leaf)
        self.records += table.num_rows
        self.bytes += int(nbytes)
        for leaf, fs in fields.items():
            prev = self.fields.get(leaf)
            self.fields[leaf] = fs if prev is None else prev.merge(fs)
        for seg, count in segments.items():
            self.segments[seg] = self.segments.get(seg, 0) + count

    def segment_mix(self) -> Dict[str, float]:
        total = sum(self.segments.values())
        if not total:
            return {}
        return {seg: count / total
                for seg, count in self.segments.items()}

    def mean_record_length(self) -> Optional[float]:
        if not self.records or not self.bytes:
            return None
        return self.bytes / self.records

    def summary(self) -> dict:
        out = {"generation": self.name, "records": self.records}
        if self.bytes:
            out["bytes"] = self.bytes
        if self.segments:
            out["segments"] = dict(sorted(self.segments.items()))
        return out


def compare_generations(prev: GenerationProfile,
                        cur: GenerationProfile) -> List[dict]:
    """Material shifts between two finished generations, as drift
    records. Empty generations prove nothing and compare clean."""
    if not prev.records or not cur.records:
        return []
    events: List[dict] = []

    def emit(kind: str, **detail) -> None:
        record = {"kind": kind, "prev_generation": prev.name,
                  "generation": cur.name}
        record.update(detail)
        events.append(record)

    prev_mix, cur_mix = prev.segment_mix(), cur.segment_mix()
    if prev_mix or cur_mix:
        l1 = sum(abs(cur_mix.get(seg, 0.0) - prev_mix.get(seg, 0.0))
                 for seg in set(prev_mix) | set(cur_mix))
        if l1 > SEGMENT_MIX_L1:
            emit("segment_mix", distance=round(l1, 6),
                 prev={k: round(v, 6)
                       for k, v in sorted(prev_mix.items())},
                 cur={k: round(v, 6)
                      for k, v in sorted(cur_mix.items())})

    for leaf in sorted(set(prev.fields) & set(cur.fields)):
        pf, cf = prev.fields[leaf], cur.fields[leaf]
        prev_rate = pf.null_count / prev.records
        cur_rate = cf.null_count / cur.records
        if cur_rate - prev_rate > NULL_RATE_RISE:
            emit("null_rate", field=leaf,
                 prev=round(prev_rate, 6), cur=round(cur_rate, 6))
        if (pf.kind == cf.kind and pf.min is not None
                and cf.min is not None):
            try:
                low = cf.min < pf.min
                high = cf.max > pf.max
            except TypeError:
                continue
            if low or high:
                emit("out_of_range", field=leaf,
                     prev_min=_encode_value(pf.kind, pf.min),
                     prev_max=_encode_value(pf.kind, pf.max),
                     cur_min=_encode_value(cf.kind, cf.min),
                     cur_max=_encode_value(cf.kind, cf.max))

    prev_len, cur_len = prev.mean_record_length(), \
        cur.mean_record_length()
    if prev_len and cur_len:
        shift = abs(cur_len - prev_len) / prev_len
        if shift > RECORD_LENGTH_SHIFT:
            emit("record_length", prev=round(prev_len, 2),
                 cur=round(cur_len, 2), shift=round(shift, 6))
    return events
