"""Per-read observability context with explicit thread propagation.

One `ObsContext` bundles everything a read's execution threads need to
report into — the tracer (None when tracing is off), the metrics
registry's standard metric set, the progress tracker, and the per-read
compile-cache counter scope. `read_cobol` creates it and activates it on
the calling thread; the pipeline executor re-activates the SAME context
on every stage thread it spawns, and the var-len shard pool wraps its
scan closure — so attribution crosses thread pools deliberately instead
of leaking through process-globals (the plan_cache cross-read
contamination this replaces). Fork workers build their own context
(hosts.py) and ship spans home over the result pipes.

`current()` is a single thread-local read; every hot-path call site
gates on it being None, so the tracing-off cost is one attribute lookup.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

_tls = threading.local()


class ObsContext:
    """The read's observability bundle (any member may be None)."""

    __slots__ = ("tracer", "metrics", "progress", "cache_scope",
                 "io_stats", "field_costs", "pass_counts")

    def __init__(self, tracer=None, metrics: Optional[dict] = None,
                 progress=None, cache_scope=None, io_stats=None,
                 field_costs=None, pass_counts=None):
        self.tracer = tracer
        self.metrics = metrics      # obs.metrics.scan_metrics() dict
        self.progress = progress    # obs.progress.ProgressTracker
        self.cache_scope = cache_scope  # plan.cache.CacheStatsScope
        self.io_stats = io_stats    # io.stats.IoStats (remote IO planes)
        # obs.fieldcost.FieldCostAccumulator — per-field/kernel-group
        # cost attribution; None = attribution off (the zero-cost
        # default: every timer site gates on this being None)
        self.field_costs = field_costs
        # profiling.PassCounters — fused-native-pass engagement counts
        # for the read (lands in ReadMetrics.as_dict()["native_passes"])
        self.pass_counts = pass_counts


def current() -> Optional[ObsContext]:
    return getattr(_tls, "ctx", None)


def count_pass(name: str, n: int = 1) -> None:
    """Record `n` engagements of a fused native pass against the active
    read's PassCounters; no-op outside a read (or when the read carries
    no metrics). Post-read assembly sites must NOT use this — the
    context is gone by then; they increment through the PassCounters
    reference their DecodedBatch captured at decode time."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None and ctx.pass_counts is not None:
        ctx.pass_counts.incr(name, n)


@contextlib.contextmanager
def activate(ctx: Optional[ObsContext]):
    """Install `ctx` as the thread's observability context (and its
    cache scope as the thread's cache-counter sink). Pass None for a
    no-op — call sites never need their own guard."""
    if ctx is None:
        yield
        return
    from ..plan.cache import activate_scope, deactivate_scope

    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    prev_scope = (activate_scope(ctx.cache_scope)
                  if ctx.cache_scope is not None else None)
    try:
        yield
    finally:
        _tls.ctx = prev
        if ctx.cache_scope is not None:
            deactivate_scope(prev_scope)
