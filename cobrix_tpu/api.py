"""User-facing API: `read_cobol(path, copybook=..., **options)`.

The equivalent of the reference's Spark DataSource surface
(`spark.read.format("cobol").option(...).load(path)` — DefaultSource.scala:50,
CobolRelation.scala:85, CobolParametersParser.scala:191): the same ~45
string-keyed options, the same pedantic/unused-key auditing and option
incompatibility matrices, a deterministic multi-file ordering with per-file
Record_Id bases, and output as columns/rows/pandas/Arrow instead of an RDD.
"""
from __future__ import annotations

import glob as _glob
import json
import os
from typing import (TYPE_CHECKING, Dict, Iterable, List, Optional,
                    Sequence, Tuple, Union)

if TYPE_CHECKING:  # explain.py imports api; annotation only
    from .explain import ScanReport

import numpy as np

from .copybook.copybook import Copybook
from .copybook.datatypes import (
    CommentPolicy,
    DebugFieldsPolicy,
    FloatingPointFormat,
    SchemaRetentionPolicy,
    TrimPolicy,
)
from .reader.diagnostics import (
    DEFAULT_LEDGER_CAP,
    DEFAULT_RESYNC_WINDOW,
    ReadDiagnostics,
    RecordErrorPolicy,
    ShardErrorPolicy,
    ShardFailureInfo,
)
from .reader.fixed_len_reader import FixedLenReader
from .reader.json_out import rows_to_json
from .reader.parameters import (
    DEFAULT_FILE_RECORD_ID_INCREMENT,
    MultisegmentParameters,
    ReaderParameters,
)
from .profiling import ReadMetrics, stage
from .reader.result import FileResult, rows_file_result
from .reader.schema import CobolOutputSchema, StructType
from .reader.stream import RetryPolicy, open_stream, path_scheme
from .reader.var_len_reader import VarLenReader, default_segment_id_prefix


class Options:
    """Option map wrapper tracking key usage for pedantic-mode auditing
    (reference Parameters.scala:27-98)."""

    def __init__(self, options: Dict[str, object]):
        # Python-native callers pass mappings/lists directly (e.g.
        # occurs_mapping as a dict); the option layer is string-keyed like
        # the reference's .option() map, so structured values carry as
        # JSON. query.Expr filters serialize via their canonical wire
        # form, NOT str() — the grammar spelling cannot express fields
        # named like its own keywords (SEGMENT, IN, NOT, ...)
        self._map = {str(k): (json.dumps(v) if isinstance(v, (dict, list))
                              else v.canonical() if hasattr(v, "canonical")
                              else str(v))
                     for k, v in options.items()}
        self._used = set()

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        if key in self._map:
            self._used.add(key)
            return self._map[key]
        return default

    def __contains__(self, key: str) -> bool:
        return key in self._map

    def mark_used(self, key: str) -> None:
        self._used.add(key)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v is None:
            return default
        return v.strip().lower() in ("true", "1", "yes")

    def get_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        v = self.get(key)
        return default if v is None else int(v)

    def keys(self):
        return self._map.keys()

    def unused_keys(self) -> List[str]:
        return [k for k in self._map if k not in self._used]


_ENUM_PARSERS = {
    "schema_retention_policy": {
        "keep_original": SchemaRetentionPolicy.KEEP_ORIGINAL,
        "collapse_root": SchemaRetentionPolicy.COLLAPSE_ROOT,
    },
    "string_trimming_policy": {
        "none": TrimPolicy.NONE, "left": TrimPolicy.LEFT,
        "right": TrimPolicy.RIGHT, "both": TrimPolicy.BOTH,
    },
    "floating_point_format": {
        "ibm": FloatingPointFormat.IBM,
        "ibm_little_endian": FloatingPointFormat.IBM_LE,
        "ieee754": FloatingPointFormat.IEEE754,
        "ieee754_little_endian": FloatingPointFormat.IEEE754_LE,
    },
    "debug": {
        "false": DebugFieldsPolicy.NONE, "none": DebugFieldsPolicy.NONE,
        "true": DebugFieldsPolicy.HEX, "hex": DebugFieldsPolicy.HEX,
        "raw": DebugFieldsPolicy.RAW,
    },
}


def _normalize_filter_option(value: Optional[str]) -> Optional[str]:
    """The `filter` option (grammar text, wire JSON, or the str() of a
    query.Expr — all strings by the time the option layer sees them)
    -> canonical wire JSON. Raises ValueError with the parse position
    on malformed input, BEFORE any data is read."""
    if not value:
        return None
    from .query.expr import normalize_filter

    try:
        return normalize_filter(value)
    except (ValueError, TypeError) as exc:
        raise ValueError(f"Invalid 'filter' option: {exc}") from exc


def _parse_enum(opts: Options, key: str, default: str):
    value = opts.get(key, default)
    table = _ENUM_PARSERS[key]
    parsed = table.get(value.strip().lower())
    if parsed is None:
        raise ValueError(f"Invalid value '{value}' for '{key}' option.")
    return parsed


def _parse_segment_levels(opts: Options) -> List[str]:
    levels = []
    i = 0
    while True:
        name = f"segment_id_level{i}"
        if name in opts:
            levels.append(opts.get(name))
        elif i == 0 and "segment_id_root" in opts:
            levels.append(opts.get("segment_id_root"))
        else:
            return levels
        i += 1


def _parse_prefixed_map(opts: Options,
                        prefixes: Tuple[str, ...]) -> Dict[str, str]:
    """Parse 'redefine-segment-id-map:N' / 'segment-children:N' options
    ('FIELD => A,B') into {item: field} (segment-id -> redefine name, or
    child -> parent respectively)."""
    from .copybook.ast import transform_identifier
    out: Dict[str, str] = {}
    for key in list(opts.keys()):
        k = key.lower()
        if any(k.startswith(p) for p in prefixes):
            opts.mark_used(key)
            value = opts.get(key)
            parts = value.split("=>")
            if len(parts) != 2:
                raise ValueError(
                    f"Illegal argument for the '{prefixes[0]}' option: '{value}'.")
            field = transform_identifier(parts[0].strip())
            for item in (transform_identifier(s.strip())
                         for s in parts[1].split(",")):
                out[item] = field
    return out


def parse_options(options: Dict[str, object],
                  streaming: bool = False) -> Tuple[ReaderParameters, Options]:
    """String options -> typed ReaderParameters
    (reference CobolParametersParser.parse, :191). `streaming`: relax the
    per-record input-file-column gate — the micro-batch streamer tracks
    file names per batch even for fixed-length records."""
    opts = Options(options)

    encoding = (opts.get("encoding", "") or "").strip().lower()
    if encoding not in ("", "ebcdic", "ascii"):
        raise ValueError(f"Invalid value '{encoding}' for 'encoding' option. "
                         "Should be either 'EBCDIC' or 'ASCII'.")
    is_ebcdic = encoding in ("", "ebcdic")

    comment_policy = CommentPolicy(
        truncate_comments=opts.get_bool("truncate_comments", True),
        comments_up_to_char=opts.get_int("comments_lbound", 6),
        comments_after_char=opts.get_int("comments_ubound", 72))
    if not comment_policy.truncate_comments and (
            "comments_lbound" in options or "comments_ubound" in options):
        raise ValueError(
            "When 'truncate_comments=false' the following parameters cannot be "
            "used: 'comments_lbound', 'comments_ubound'.")

    is_record_sequence = (opts.get_bool("is_xcom") or
                          opts.get_bool("is_record_sequence"))
    if "record_length_field" in opts and (
            "is_record_sequence" in opts or "is_xcom" in opts):
        raise ValueError("Option 'record_length_field' cannot be used together "
                         "with 'is_record_sequence' or 'is_xcom'.")

    multisegment = None
    if "segment_field" in opts:
        filter_str = opts.get("segment_filter")
        multisegment = MultisegmentParameters(
            segment_id_field=opts.get("segment_field"),
            segment_id_filter=filter_str.split(",") if filter_str else None,
            segment_level_ids=_parse_segment_levels(opts),
            segment_id_prefix=opts.get("segment_id_prefix", ""),
            segment_id_redefine_map=_parse_prefixed_map(
                opts, ("redefine-segment-id-map", "redefine_segment_id_map")),
            field_parent_map=_parse_prefixed_map(
                opts, ("segment-children", "segment_children")))

    occurs_mappings = {}
    # the reference README documents the singular key (`occurs_mapping`,
    # README.md:1101); both spellings are accepted, but not together
    occurs_keys = [k for k in ("occurs_mappings", "occurs_mapping")
                   if k in opts]
    if len(occurs_keys) > 1:
        raise ValueError(
            "Options 'occurs_mappings' and 'occurs_mapping' cannot be "
            "specified at the same time")
    if occurs_keys:
        occurs_mappings = {
            k: {sk: int(sv) for sk, sv in v.items()}
            for k, v in json.loads(opts.get(occurs_keys[0])).items()}

    non_terminals = tuple(
        s for s in (opts.get("non_terminals", "") or "").split(",") if s)

    params = ReaderParameters(
        is_ebcdic=is_ebcdic,
        is_text=opts.get_bool("is_text"),
        ebcdic_code_page=opts.get("ebcdic_code_page", "common"),
        ebcdic_code_page_class=opts.get("ebcdic_code_page_class"),
        ascii_charset=opts.get("ascii_charset", "") or "us-ascii",
        is_utf16_big_endian=opts.get_bool("is_utf16_big_endian", True),
        floating_point_format=_parse_enum(opts, "floating_point_format", "ibm"),
        variable_size_occurs=opts.get_bool("variable_size_occurs"),
        record_length_override=opts.get_int("record_length"),
        length_field_name=opts.get("record_length_field"),
        is_record_sequence=is_record_sequence,
        is_rdw_big_endian=opts.get_bool("is_rdw_big_endian"),
        is_rdw_part_of_record_length=opts.get_bool("is_rdw_part_of_record_length"),
        rdw_adjustment=opts.get_int("rdw_adjustment", 0),
        is_index_generation_needed=opts.get_bool("enable_indexes", True),
        input_split_records=opts.get_int("input_split_records"),
        input_split_size_mb=opts.get_int("input_split_size_mb"),
        start_offset=opts.get_int("record_start_offset", 0),
        end_offset=opts.get_int("record_end_offset", 0),
        file_start_offset=opts.get_int("file_start_offset", 0),
        file_end_offset=opts.get_int("file_end_offset", 0),
        generate_record_id=opts.get_bool("generate_record_id"),
        schema_policy=_parse_enum(opts, "schema_retention_policy", "keep_original"),
        string_trimming_policy=_parse_enum(opts, "string_trimming_policy", "both"),
        multisegment=multisegment,
        comment_policy=comment_policy,
        drop_group_fillers=opts.get_bool("drop_group_fillers"),
        drop_value_fillers=opts.get_bool("drop_value_fillers", True),
        non_terminals=non_terminals,
        occurs_mappings=occurs_mappings,
        debug_fields_policy=_parse_enum(opts, "debug", "false"),
        record_header_parser=opts.get("record_header_parser"),
        record_extractor=opts.get("record_extractor"),
        rhp_additional_info=opts.get("rhp_additional_info"),
        re_additional_info=opts.get("re_additional_info", ""),
        input_file_name_column=opts.get("with_input_file_name_col", ""),
        select=tuple(s.strip() for s in opts.get("select", "").split(",")
                     if s.strip()) or None,
        filter=_normalize_filter_option(opts.get("filter")),
        record_error_policy=RecordErrorPolicy.parse(
            opts.get("record_error_policy", "fail_fast")),
        resync_window_bytes=opts.get_int("resync_window",
                                         DEFAULT_RESYNC_WINDOW),
        max_corrupt_ledger_entries=opts.get_int(
            "max_corrupt_ledger_entries", DEFAULT_LEDGER_CAP),
        corrupt_record_column=opts.get("corrupt_record_column", ""),
        io_retry_attempts=opts.get_int("io_retry_attempts", 3),
        io_retry_base_delay=float(
            opts.get_int("io_retry_base_delay_ms", 50)) / 1000.0,
        io_retry_max_delay=float(
            opts.get_int("io_retry_max_delay_ms", 2000)) / 1000.0,
        io_retry_deadline=float(
            opts.get_int("io_retry_deadline_ms", 30000)) / 1000.0,
        cache_dir=opts.get("cache_dir", "") or "",
        cache_max_mb=float(opts.get("cache_max_mb", "") or 1024.0),
        prefetch_blocks=opts.get_int("prefetch_blocks", 2),
        io_block_mb=float(opts.get("io_block_mb", "") or 8.0),
        compression=(opts.get("compression", "auto") or "auto").lower(),
        compress_block_mb=float(
            opts.get("compress_block_mb", "") or 4.0),
        pipeline_workers=opts.get_int("pipeline_workers", 0),
        pipeline_chunk_mb=float(opts.get("chunk_size_mb", "") or 16.0),
        pipeline_max_inflight=opts.get_int("max_inflight_chunks", 0),
        shard_error_policy=ShardErrorPolicy.parse(
            opts.get("shard_error_policy", "fail_fast")),
        shard_timeout_s=float(opts.get("shard_timeout_s", "") or 0.0),
        shard_max_retries=opts.get_int("shard_max_retries", 2),
        speculative_quantile=float(
            opts.get("speculative_quantile", "") or 0.0),
        scan_deadline_s=float(opts.get("scan_deadline_s", "") or 0.0),
        heartbeat_interval_s=float(
            opts.get("heartbeat_interval_s", "") or 0.5),
        trace_file=opts.get("trace_file", "") or "",
        trace_id=opts.get("trace_id", "") or "",
        request_id=opts.get("request_id", "") or "",
        progress_interval_s=float(
            opts.get("progress_interval_s", "") or 0.5),
        stream_batch_rows=opts.get_int("stream_batch_rows", 0),
        field_costs=opts.get_bool("field_costs"),
        collect_stats=opts.get_bool("collect_stats"),
        use_stats=opts.get_bool("use_stats"),
        stats_chunk_mb=float(opts.get("stats_chunk_mb", "") or 4.0),
    )
    # recognized keys consumed later by read_cobol — mark used before the
    # pedantic unused-key audit runs
    opts.get_bool("debug_ignore_file_size")
    opts.get_int("parallelism", 0)
    opts.get_int("hosts", 0)
    # HDFS-locality knobs (LocalityParameters.scala:21-30): accepted for
    # workload compatibility; shard placement here has no HDFS block
    # topology to optimize (SURVEY.md §2.5 — locality consciously
    # dropped). `optimize_allocation` maps to the idle re-allocation
    # pass of the static planner (parallel.planner.balance,
    # LocationBalancer.scala:42-66 analogue) for callers that use it;
    # the supervised multihost scheduler load-balances dynamically and
    # needs no static pass
    opts.get_bool("improve_locality", True)
    opts.get_bool("optimize_allocation")
    _validate_options(opts, params, streaming)
    return params, opts


def _validate_options(opts: Options, params: ReaderParameters,
                      streaming: bool = False) -> None:
    """Option incompatibility matrices + pedantic unused-key audit
    (reference validateSparkCobolOptions, :473-610)."""
    rdw_ish = ["is_text", "record_length", "is_record_sequence", "is_xcom",
               "is_rdw_big_endian", "is_rdw_part_of_record_length",
               "rdw_adjustment", "record_length_field",
               "record_header_parser", "rhp_additional_info"]
    if "record_extractor" in opts:
        bad = [k for k in rdw_ish if k in opts]
        if bad:
            raise ValueError(
                f"Option 'record_extractor' and {', '.join(bad)} cannot be "
                "used together.")
    if "record_length" in opts:
        bad = [k for k in rdw_ish[2:] if k in opts] \
            + (["is_text"] if "is_text" in opts else [])
        if bad:
            raise ValueError(
                f"Option 'record_length' and {', '.join(bad)} cannot be "
                "used together.")
    if params.input_file_name_column and not streaming:
        if not params.is_variable_length:
            raise ValueError(
                "Option 'with_input_file_name_col' is supported only when "
                "one of this holds: 'is_record_sequence' = true or "
                "'variable_size_occurs' = true or one of these options is "
                "set: 'record_length_field', 'file_start_offset', "
                "'file_end_offset' or a custom record extractor is specified")
    if params.corrupt_record_column and not params.is_permissive:
        raise ValueError(
            "Option 'corrupt_record_column' requires "
            "record_error_policy='permissive' or 'drop_malformed' "
            "(under 'fail_fast' the first malformed record raises instead "
            "of being recorded).")
    if params.resync_window_bytes <= 0:
        raise ValueError(
            f"Invalid 'resync_window' of {params.resync_window_bytes} "
            "bytes; it must be a positive byte count.")
    if params.io_retry_attempts < 1:
        raise ValueError(
            f"Invalid 'io_retry_attempts' of {params.io_retry_attempts}; "
            "at least one attempt is required.")
    if params.cache_max_mb < 0:
        raise ValueError(
            f"Invalid 'cache_max_mb' of {params.cache_max_mb}; it must "
            "be >= 0 (0 = unbounded).")
    if params.prefetch_blocks < 0:
        raise ValueError(
            f"Invalid 'prefetch_blocks' of {params.prefetch_blocks}; it "
            "must be >= 0 (0 disables read-ahead).")
    if params.io_block_mb <= 0:
        raise ValueError(
            f"Invalid 'io_block_mb' of {params.io_block_mb}; it must be "
            "a positive block size in megabytes.")
    if params.compression not in ("auto", "none", "off", "raw"):
        from .io.compress import codec_by_name

        try:
            codec_by_name(params.compression)
        except ValueError as exc:
            raise ValueError(f"Invalid 'compression' option: {exc}")
    if params.compress_block_mb <= 0:
        raise ValueError(
            f"Invalid 'compress_block_mb' of {params.compress_block_mb}; "
            "it must be a positive block size in megabytes.")
    if params.cache_dir:
        cache_parent = os.path.dirname(
            os.path.abspath(params.cache_dir)) or "."
        if not os.path.isdir(cache_parent):
            raise ValueError(
                f"Invalid 'cache_dir' '{params.cache_dir}': parent "
                f"directory '{cache_parent}' does not exist.")
    if params.pipeline_chunk_mb <= 0:
        raise ValueError(
            f"Invalid 'chunk_size_mb' of {params.pipeline_chunk_mb}; "
            "it must be a positive size in megabytes.")
    if params.pipeline_max_inflight < 0:
        raise ValueError(
            f"Invalid 'max_inflight_chunks' of "
            f"{params.pipeline_max_inflight}; it must be >= 0 "
            "(0 sizes it from the worker count).")
    if params.shard_timeout_s < 0:
        raise ValueError(
            f"Invalid 'shard_timeout_s' of {params.shard_timeout_s}; "
            "it must be >= 0 (0 disables the per-shard deadline).")
    if params.scan_deadline_s < 0:
        raise ValueError(
            f"Invalid 'scan_deadline_s' of {params.scan_deadline_s}; "
            "it must be >= 0 (0 disables the whole-scan deadline).")
    if params.shard_max_retries < 0:
        raise ValueError(
            f"Invalid 'shard_max_retries' of {params.shard_max_retries}; "
            "it must be >= 0 (0 means a failed shard is never "
            "re-dispatched).")
    if not 0.0 <= params.speculative_quantile < 1.0:
        raise ValueError(
            f"Invalid 'speculative_quantile' of "
            f"{params.speculative_quantile}; it must be in [0, 1) "
            "(0 disables straggler speculation).")
    if params.heartbeat_interval_s <= 0:
        raise ValueError(
            f"Invalid 'heartbeat_interval_s' of "
            f"{params.heartbeat_interval_s}; it must be positive.")
    if params.progress_interval_s < 0:
        raise ValueError(
            f"Invalid 'progress_interval_s' of "
            f"{params.progress_interval_s}; it must be >= 0 "
            "(0 invokes the callback on every completed chunk).")
    if params.stream_batch_rows < 0:
        raise ValueError(
            f"Invalid 'stream_batch_rows' of {params.stream_batch_rows}; "
            "it must be >= 0 (0 streams one batch per assembled chunk).")
    if (params.collect_stats or params.use_stats) \
            and not params.cache_dir:
        raise ValueError(
            "Options 'collect_stats'/'use_stats' require 'cache_dir': "
            "profiles persist in (and load from) the cache directory's "
            "stats plane.")
    if params.stats_chunk_mb <= 0:
        raise ValueError(
            f"Invalid 'stats_chunk_mb' of {params.stats_chunk_mb}; it "
            "must be a positive size in megabytes.")
    if params.trace_file:
        # fail BEFORE the scan, not after minutes of decode: the trace is
        # written at read end, so an unwritable destination would
        # otherwise discard a fully successful read
        trace_dir = os.path.dirname(params.trace_file) or "."
        if not os.path.isdir(trace_dir):
            raise ValueError(
                f"Invalid 'trace_file' '{params.trace_file}': directory "
                f"'{trace_dir}' does not exist.")
        if not os.access(trace_dir, os.W_OK):
            raise ValueError(
                f"Invalid 'trace_file' '{params.trace_file}': directory "
                f"'{trace_dir}' is not writable.")
    seg = params.multisegment
    if seg and seg.field_parent_map and seg.segment_level_ids:
        raise ValueError(
            "Options 'segment_id_level*'/'segment_id_root' and "
            "'segment-children:*' cannot be used together.")
    if seg and seg.field_parent_map and not seg.segment_id_redefine_map:
        raise ValueError(
            "Option 'segment-children:*' requires 'redefine-segment-id-map:*' "
            "to be set as well.")
    pedantic = opts.get_bool("pedantic")  # marks the key used
    unused = opts.unused_keys()
    if unused and pedantic:
        raise ValueError("Redundant or unrecognized option(s) to 'spark-cobol': "
                         + ", ".join(sorted(unused)) + ".")


def load_copybook_contents(copybook, copybook_contents):
    """Resolve the copybook SOURCE the way `read_cobol` does: exactly
    one of `copybook` (path or list of paths) / `copybook_contents`
    (text), with the reference's error messages. Shared with the
    continuous-ingest surface (streaming.ingest) so the loading rules
    can never drift between entry points."""
    if copybook is not None and copybook_contents is not None:
        raise ValueError("Both 'copybook' and 'copybook_contents' options "
                         "cannot be specified at the same time")
    if copybook_contents is not None:
        return copybook_contents
    if copybook is None:
        raise ValueError(
            "COPYBOOK is not provided. Please, provide either 'copybook' "
            "path or 'copybook_contents'.")
    books = [copybook] if isinstance(copybook, str) else list(copybook)
    contents = []
    for b in books:
        if os.path.exists(b) and not os.path.isfile(b):
            raise ValueError(f"The copybook path '{b}' is not a file.")
        with open(b, encoding="utf-8") as f:
            contents.append(f.read())
    return contents if len(contents) > 1 else contents[0]


def list_input_files(path) -> List[str]:
    """Recursive globbed listing skipping hidden files, stable order
    (reference FileUtils.scala:54-228, getListFilesWithOrder)."""
    from .reader.stream import normalize_local, path_scheme, stream_lister

    paths = [path] if isinstance(path, str) else list(path)
    out: List[str] = []
    for p in paths:
        scheme = path_scheme(p)
        if scheme not in (None, "file"):
            # registry-backed storage: backends with a listing capability
            # (the fsspec adapter and anything registered with `lister=`)
            # expand directories/globs remotely; others pass through
            # verbatim as one input
            lister = stream_lister(scheme)
            if lister is not None:
                out.extend(lister(p))
            else:
                out.append(p)
            continue
        # file:// never propagates past listing: downstream os.path
        # consumers see plain local paths
        p = normalize_local(p)
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if not d.startswith((".", "_")))
                for f in sorted(files):
                    if not f.startswith((".", "_")):
                        out.append(os.path.join(root, f))
        elif os.path.isfile(p):
            out.append(p)
        else:
            matched = sorted(_glob.glob(p))
            if not matched:
                raise FileNotFoundError(f"Input path does not exist: {p}")
            for m in matched:
                out.extend(list_input_files(m))
    return out


class CobolData:
    """Decoded result: per-file columnar results + schema, materializable
    as rows, JSON lines, pandas, or Arrow. Arrow tables are built straight
    from the kernel output arrays (reader/arrow_out.py); Python rows are
    materialized only when asked for."""

    def __init__(self, rows, schema: CobolOutputSchema,
                 results: Optional[List["FileResult"]] = None,
                 parallelism: int = 1):
        self._rows = rows
        self._results = results
        self._arrow_tables = None
        self.output_schema = schema
        self.parallelism = parallelism
        # structured per-read metrics (profiling.ReadMetrics); populated by
        # read_cobol
        self.metrics: Optional[ReadMetrics] = None
        # the read's error ledger (permissive policies; None under
        # fail_fast) — aggregated over every file/shard by read_cobol
        self.diagnostics: Optional[ReadDiagnostics] = None
        # copybook plan fingerprint (plan.cache.parse_fingerprint),
        # stamped by read_cobol — the sink's schema-drift sentinel
        self.plan_fingerprint: str = ""

    @classmethod
    def from_results(cls, results: List["FileResult"],
                     schema: CobolOutputSchema,
                     parallelism: int = 1) -> "CobolData":
        return cls(None, schema, results, parallelism=parallelism)

    @classmethod
    def from_arrow_tables(cls, tables, schema: CobolOutputSchema
                          ) -> "CobolData":
        """Multi-host results: the columnar product arrived as Arrow
        tables (one per shard, already in record order)."""
        data = cls(None, schema, None)
        data._arrow_tables = tables
        return data

    @property
    def schema(self) -> StructType:
        return self.output_schema.schema

    def __len__(self) -> int:
        if self._arrow_tables is not None:
            return sum(t.num_rows for t in self._arrow_tables)
        if self._rows is not None:
            return len(self._rows)
        return sum(r.n_rows for r in self._results)

    def to_rows(self) -> List[List[object]]:
        if self._arrow_tables is not None:
            raise NotImplementedError(
                "multi-host (hosts=N) results are Arrow-backed; use "
                "to_arrow()/to_pandas(), or read without `hosts` for "
                "Python row materialization")
        if self._rows is None:
            rows: List[List[object]] = []
            for r in self._results:
                rows.extend(r.to_rows())
            self._rows = rows
        return self._rows

    def to_dicts(self) -> List[dict]:
        names = self.schema.field_names()
        return [dict(zip(names, row)) for row in self.to_rows()]

    def to_json_lines(self) -> List[str]:
        return rows_to_json(self.to_rows(), self.schema)

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def to_dataset(self, dataset_dir: str, file_format: str = "parquet",
                   partition_by=(), target_file_mb: float = 64.0,
                   retry=None):
        """One-shot atomic export into a transactional sink dataset
        (`cobrix_tpu.sink`): every data file is staged and finalized,
        then ONE manifest record commits them all — a crash at any
        instant leaves the dataset exactly as it was. Re-exporting into
        the same dataset appends a new commit; a dataset written under
        a different copybook/schema fingerprint is refused
        (`SinkSchemaError`). Returns the `DatasetSink` (its
        ``recovery`` report and ``to_table()`` read-back included)."""
        from .reader.arrow_out import arrow_schema as _arrow_schema
        from .sink import DatasetSink, schema_fingerprint

        schema = _arrow_schema(self.schema)
        sink = DatasetSink(
            dataset_dir, arrow_schema=schema,
            schema_fp=schema_fingerprint(schema, self.plan_fingerprint),
            file_format=file_format, partition_by=partition_by,
            target_file_mb=target_file_mb, retry=retry)
        sink.commit_table(self.to_arrow(), source="read_cobol")
        return sink

    def to_ebcdic(self, path: Optional[str] = None, *,
                  framing: str = "fixed",
                  rdw_big_endian: bool = False,
                  rdw_adjustment: int = 0,
                  rdw_part_of_record_length: bool = False,
                  variable_size_occurs: bool = False,
                  truncate: bool = True,
                  fill_byte: Optional[int] = None):
        """Encode the decoded records back to mainframe binary (the write
        half of the bridge: the sink emits Parquet, this emits
        fixed-length or RDW-framed EBCDIC/ASCII consumable by the same
        copybook). Generated columns (File_Id/Record_Id/Seg_Id*/input
        file name/corrupt-record) are stripped; the data columns are
        re-encoded through `cobrix_tpu.encode` against this read's
        copybook. Returns the bytes, or writes to `path` and returns
        None."""
        from .encode.encoder import RecordEncoder

        schema = self.output_schema
        enc = RecordEncoder(schema.copybook, policy=schema.policy,
                            variable_size_occurs=variable_size_occurs,
                            fill_byte=fill_byte)
        nseg = schema.generate_seg_id_field_count
        lead = ((3 + nseg) if (schema.generate_record_id
                               and schema.input_file_name_field)
                else (2 + nseg) if schema.generate_record_id
                else (nseg + 1) if schema.input_file_name_field
                else nseg)
        tail = -1 if schema.corrupt_record_field else None

        def bodies():
            for row in self.to_rows():
                yield row[lead:tail]

        import io as _io
        sink = _io.BytesIO() if path is None else open(path, "wb")
        try:
            if framing == "fixed":
                enc.encode_fixed(bodies(), sink)
            elif framing == "rdw":
                enc.encode_rdw(
                    bodies(), sink, big_endian=rdw_big_endian,
                    adjustment=rdw_adjustment,
                    part_of_record_length=rdw_part_of_record_length,
                    truncate=truncate)
            else:
                raise ValueError(f"Unknown framing '{framing}' (fixed|rdw)")
        finally:
            if path is not None:
                sink.close()
        return sink.getvalue() if path is None else None

    def to_arrow(self):
        """pyarrow Table with schema-declared types, built from the kernel
        outputs without row materialization (the reference must feed Spark
        rows, SparkCobolRowType.scala:24; a columnar framework emits
        columns)."""
        table = self._to_arrow_impl()
        if (self.metrics is not None
                and self.metrics.field_costs_acc is not None):
            # sequential assembly ran after the trace was written; fold
            # its accrued per-field costs back into the artifact
            self.metrics.refresh_trace_field_costs()
        return table

    def _to_arrow_impl(self):
        import pyarrow as pa

        from .reader.arrow_out import arrow_schema, rows_to_table

        if self._arrow_tables is not None:
            if not self._arrow_tables:
                return self._stamp(arrow_schema(self.schema).empty_table())
            return self._stamp(
                self._arrow_tables[0] if len(self._arrow_tables) == 1
                else pa.concat_tables(self._arrow_tables))
        if self._results is None:
            return self._stamp(rows_to_table(self._rows, self.schema))
        if self.parallelism > 1 and len(self._results) > 1:
            # per-shard table builds release the GIL inside Arrow; shard
            # order preserves record order, so concat needs no reordering
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=min(self.parallelism,
                                    len(self._results))) as ex:
                tables = list(ex.map(
                    lambda r: r.to_arrow(self.output_schema),
                    self._results))
        else:
            tables = [r.to_arrow(self.output_schema) for r in self._results]
        if not tables:
            return self._stamp(arrow_schema(self.schema).empty_table())
        return self._stamp(tables[0] if len(tables) == 1
                           else pa.concat_tables(tables))

    def _stamp(self, table):
        """Attach the read's error ledger to the Arrow schema metadata
        (key 'cobrix_tpu.read_diagnostics', JSON) so the fault record
        travels with the data through downstream Arrow/Parquet sinks."""
        if self.diagnostics is None:
            return table
        metadata = dict(table.schema.metadata or {})
        metadata[b"cobrix_tpu.read_diagnostics"] = \
            self.diagnostics.to_json().encode()
        return table.replace_schema_metadata(metadata)


def _retry_policy(params: ReaderParameters) -> RetryPolicy:
    """The read's IO retry policy for registry-backed storage."""
    return RetryPolicy(max_attempts=params.io_retry_attempts,
                       base_delay=params.io_retry_base_delay,
                       max_delay=params.io_retry_max_delay,
                       deadline=params.io_retry_deadline)


def _io_config(params: ReaderParameters):
    """The read's remote-IO configuration (None = all features off)."""
    from .io.config import IoConfig

    return IoConfig.from_params(params)


def _total_input_bytes(files: Sequence[str], io_stats=None,
                       io=None, retry: Optional[RetryPolicy] = None,
                       on_retry=None) -> int:
    """LOGICAL input bytes across local AND backend-resolved files
    (progress totals + throughput metrics — decompressed bytes for
    compressed feeds, since every downstream offset lives in that
    space); sizing failures never fail the read — an unknown size just
    reports as 0. This runs before the read's obs context activates, so
    it activates a sizing-scoped context around the probes: remote
    sizes, codec sniffs, and inflate-discovery results all seed the
    read's metadata memo for the planners and validators downstream.
    Probes run under the read's retry policy so transient backend
    failures here are retried AND ledgered like any other IO."""
    from .io.compress import active_codec
    from .obs.context import ObsContext
    from .obs.context import activate as obs_activate
    from .reader.stream import source_size

    total = 0
    with obs_activate(ObsContext(io_stats=io_stats)
                      if io_stats is not None else None):
        for f in files:
            try:
                if path_scheme(f) in (None, "file"):
                    if active_codec(f, io) is not None:
                        total += source_size(f, io=io, retry=retry,
                                             on_retry=on_retry)
                    elif os.path.exists(f):
                        total += os.path.getsize(f)
                else:
                    total += source_size(f, io=io, retry=retry,
                                         on_retry=on_retry)
            except Exception:
                continue
    return total


def _plan_var_len_shards(reader, files, params,
                         retry: Optional[RetryPolicy] = None,
                         on_retry=None, io=None) -> List["WorkShard"]:
    """Byte-range shard plan for a variable-length read (the sparse-index
    chunk planner, engine/chunks.py). Shared by the in-process threaded
    scan, the pipelined executor, and the multi-host (process) executor."""
    from .engine.chunks import plan_var_len_chunks

    return plan_var_len_chunks(reader, files, params, retry, on_retry,
                               io=io)


def _scan_var_len(reader, files, params, backend: str, prefix: str,
                  parallelism: int, metrics=None,
                  retry: Optional[RetryPolicy] = None,
                  on_retry=None, io=None) -> List["FileResult"]:
    """The indexed parallel scan — the reference's flagship execution
    strategy (CobolScanners.buildScanForVarLenIndex, CobolScanners.scala:
    38-55 + IndexBuilder.buildIndex, IndexBuilder.scala:49-66): a sparse
    index per file turns the sequential record stream into byte-range
    shards; shards decode concurrently (each from its own bounded stream,
    Record_Id seeded from the index entry) and results reassemble in
    record order."""
    from .obs.context import activate as obs_activate
    from .obs.context import current as obs_current

    obs = obs_current()
    tracer = obs.tracer if obs is not None else None
    progress = obs.progress if obs is not None else None
    with stage(metrics, "plan_index"):
        shards = _plan_var_len_shards(reader, files, params, retry,
                                      on_retry, io)
    if metrics is not None:
        metrics.shards = len(shards)
    if progress is not None:
        progress.set_plan(chunks_total=len(shards))
    shard_times = None
    if tracer is not None or (metrics is not None
                              and metrics.field_costs_acc is not None):
        # tracing on: per-stage spans from inside the readers (read /
        # frame / decode) via a tracer-wired StageTimes, published on
        # the read metrics like the pipelined path's. Field-cost
        # attribution wants the same stage busy breakdown even
        # untraced — the explain report compares the per-field decode
        # sum against the decode-stage busy time
        from .profiling import StageTimes

        shard_times = StageTimes(tracer=tracer)
        if metrics is not None and metrics.stage_busy is None:
            metrics.stage_busy = shard_times
        if progress is not None and progress.stage_times is None:
            progress.stage_times = shard_times

    def scan(shard) -> "FileResult":
        max_bytes = (0 if shard.offset_to < 0
                     else shard.offset_to - shard.offset_from)
        with open_stream(shard.file_path, start_offset=shard.offset_from,
                         maximum_bytes=max_bytes, retry=retry,
                         on_retry=on_retry, io=io) as stream:
            return reader.read_result_columnar(
                stream, file_id=shard.file_order, backend=backend,
                segment_id_prefix=prefix,
                start_record_id=shard.record_index,
                starting_file_offset=shard.offset_from,
                stage_times=shard_times)

    def run_shard(indexed) -> "FileResult":
        seq, shard = indexed
        # re-activate the read's ObsContext: pool threads must attribute
        # cache events and spans to this read, not to nothing
        with obs_activate(obs):
            if progress is not None:
                progress.chunk_started()
            if tracer is not None:
                with tracer.span("shard", "shard",
                                 args={"seq": seq,
                                       "file": shard.file_path,
                                       "offset_from": shard.offset_from,
                                       "offset_to": shard.offset_to}):
                    result = scan(shard)
            else:
                result = scan(shard)
        if progress is not None:
            from .engine.chunks import shard_progress_bytes

            progress.chunk_done(bytes_done=shard_progress_bytes(shard),
                                records=result.n_rows)
        return result

    if len(shards) == 1 or parallelism <= 1:
        return [run_shard(s) for s in enumerate(shards)]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(parallelism, len(shards))) as ex:
        return list(ex.map(run_shard, enumerate(shards)))


def read_cobol(path=None,
               copybook: Optional[str] = None,
               copybook_contents=None,
               backend: str = "numpy",
               progress_callback=None,
               batch_callback=None,
               explain: bool = False,
               tracer=None,
               **options) -> "Union[CobolData, ScanReport]":
    """Read mainframe file(s) into decoded rows.

    `copybook` is a path (or list of paths) to copybook file(s);
    `copybook_contents` passes the text directly. Remaining keyword options
    use the reference's option names (README.md:1070-1155).

    `progress_callback`: optional callable receiving monotonic
    `obs.ScanProgress` snapshots while the scan runs (throttled by the
    `progress_interval_s` option; the final `done=True` snapshot always
    fires). The `trace_file` option writes a Chrome-trace/Perfetto JSON
    of the whole scan — see the README's Observability section.

    `batch_callback(chunk_index, table)`: optional streaming tap — each
    assembled per-chunk Arrow table is handed out as soon as it exists.
    On the pipelined paths (`pipeline_workers` != 0) batches arrive
    WHILE later chunks are still decoding (chunk completion order;
    re-order by `chunk_index` if record order matters — indexes come
    from the scan plan). A chunk that terminally fails under a partial
    shard policy delivers `(chunk_index, None)` — the gap is permanent;
    reorder buffers must flush past it instead of waiting (may arrive
    on a different thread than table deliveries). Other execution paths
    deliver the per-file/shard tables after the scan, in order. The
    concatenation of all delivered tables in index order equals
    `to_arrow()` minus the diagnostics schema metadata. A callback
    exception aborts the scan under fail_fast (ledgers the chunk under
    a partial shard policy) — the serving tier relies on that to cancel
    scans whose client went away.

    `explain=True` returns a `ScanReport` instead of the bare
    CobolData: the parsed field plan (offsets/widths/codecs), the
    execution plan, cache-plane status, and — because it forces the
    `field_costs` option on — the measured per-field cost table and
    roofline anchoring. The decoded data rides on `report.data`.

    `tracer`: an `obs.Tracer` to record scan spans into instead of
    creating one. The request-scoped surface for embedders (the serving
    tier passes its per-request tracer here so queue-wait and scan
    spans share one timeline and one trace_id); spans are collected
    in memory (`data.metrics.spans`) and only written to disk when
    `trace_file` is also set. The string options `trace_id` /
    `request_id` are the wire-friendly subset: they tag a read's OWN
    tracer with inbound identity.
    """
    if tracer is not None and not hasattr(tracer, "record_span"):
        raise ValueError("'tracer' must be an obs.Tracer (it receives "
                         "scan spans).")
    if progress_callback is not None and not callable(progress_callback):
        raise ValueError("'progress_callback' must be callable (it "
                         "receives ScanProgress snapshots).")
    if batch_callback is not None and not callable(batch_callback):
        raise ValueError("'batch_callback' must be callable (it receives "
                         "(chunk_index, pyarrow.Table) pairs).")
    batch_tap = _BatchTap(batch_callback) if batch_callback else None
    # exclusive-source validation before any option is consumed
    # ('copybook'/'copybook_contents' are named parameters and can never
    # reach **options; only 'copybooks' arrives as an option key —
    # reference CobolParametersValidator.checkSanity combination rules)
    has_multi = "copybooks" in options
    if copybook is not None and copybook_contents is not None:
        raise ValueError("Both 'copybook' and 'copybook_contents' options "
                         "cannot be specified at the same time")
    if has_multi and copybook_contents is not None:
        raise ValueError("Both 'copybooks' and 'copybook_contents' options "
                         "cannot be specified at the same time")
    if copybook is not None and has_multi:
        raise ValueError("Both 'copybook' and 'copybooks' options "
                         "cannot be specified at the same time")
    if has_multi:
        copybook = options.pop("copybooks").split(",")

    copybook_contents = load_copybook_contents(copybook,
                                               copybook_contents)
    if path is None:
        raise ValueError("'path' must be specified for read_cobol.")

    params, opts = parse_options(options)
    if params.filter and backend == "host":
        raise ValueError(
            "The 'filter' option requires a columnar execution path; "
            "backend='host' walks records through the scalar oracle "
            "and does not support pushdown. Drop the filter or use "
            "the numpy/jax backend.")
    if explain and not params.field_costs:
        # explain wants the measured cost table; flip attribution on
        from dataclasses import replace as _dc_replace

        params = _dc_replace(params, field_costs=True)
    debug_ignore_file_size = opts.get_bool("debug_ignore_file_size")
    # local concurrency for the indexed shard scan (the analogue of the
    # reference's executor count; not a reference option)
    parallelism = opts.get_int("parallelism", 0) or min(
        16, os.cpu_count() or 1)
    # hosts > 1: fork one worker process per host and run the shard plan
    # there (parallel/hosts.py — the executor-process analogue); the
    # result is Arrow-backed
    hosts = opts.get_int("hosts", 0)
    files = list_input_files(path)
    if not files:
        raise FileNotFoundError(f"No input files found for path {path}")

    is_var_len = params.needs_var_len_reader

    # chunked pipeline executor (cobrix_tpu.engine): overlap storage read,
    # framing, decode, and Arrow assembly across a bounded thread pool.
    # Off by default (pipeline_workers=0 keeps the sequential path); the
    # host (oracle) backend and the multi-host process executor have their
    # own execution models
    pipe_workers = params.resolved_pipeline_workers()
    use_pipeline = pipe_workers > 0 and hosts <= 1 and backend != "host"
    if use_pipeline and is_var_len:
        from .engine.chunks import auto_split_mb

        split_mb = auto_split_mb(params)
        if split_mb is not None:
            # default the sparse-index split to the pipeline chunk size so
            # mid-size files actually produce multiple chunks (explicit
            # input_split options always win; see auto_split_mb for the
            # configurations where this is pinned row-identical)
            from dataclasses import replace as _dc_replace

            params = _dc_replace(params, input_split_size_mb=split_mb)

    metrics = ReadMetrics(files=len(files), backend=backend,
                          hosts=max(hosts, 1))
    io_cfg = _io_config(params)
    prescan_retries: List[int] = []
    metrics.bytes_read = _total_input_bytes(
        files, metrics.io_stats, io=io_cfg, retry=_retry_policy(params),
        on_retry=lambda: prescan_retries.append(1))
    # sizing-probe retries fold into the read ledger downstream (both
    # execution paths see them via the metrics object)
    metrics.prescan_io_retries = len(prescan_retries)
    if params.field_costs:
        from .obs.fieldcost import FieldCostAccumulator

        metrics.field_costs_acc = FieldCostAccumulator()

    # the read's observability context: per-read cache-counter scope
    # always; tracer/progress only when asked for. Activated on this
    # thread and re-activated by every pool the scan fans out to.
    from .obs.context import activate as obs_activate

    obs_ctx = _build_obs_context(params, metrics, progress_callback,
                                 tracer=tracer)
    try:
        with obs_activate(obs_ctx):
            if hosts > 1:
                if backend != "numpy":
                    raise ValueError(
                        f"hosts={hosts} runs worker processes on the "
                        f"native/numpy kernels; backend={backend!r} is "
                        f"not supported there (drop `hosts` for the "
                        f"{backend!r} backend)")
                data = _read_cobol_multihost(
                    files, copybook_contents, params, hosts,
                    debug_ignore_file_size, metrics)
            else:
                data = _read_cobol_single_host(
                    files, copybook_contents, params, backend,
                    parallelism, pipe_workers, use_pipeline, is_var_len,
                    debug_ignore_file_size, metrics, io_cfg,
                    batch_tap=batch_tap)
    except BaseException:
        # a failed scan still flushes its telemetry: the final done=True
        # progress snapshot fires (a progress bar must not freeze) and
        # the PARTIAL trace — exactly what diagnoses the failure — is
        # written; flush errors never mask the scan's own exception
        _abort_obs(obs_ctx, params)
        raise
    _finish_obs(obs_ctx, params, data)
    if batch_tap is not None and batch_tap.count == 0:
        # execution paths without an incremental tap (sequential,
        # threaded shard scan, host backend, multihost) still honor the
        # streaming contract: the per-result tables go out now, in
        # record order — the callback sees the same batches, just with
        # one-shot latency
        batch_tap.emit_data(data)
    if params.collect_stats:
        # the profiling pass runs AFTER the read (an explicit,
        # separately-billed cost — never hidden inside scan time);
        # warm profiles load instead of rebuilding. Gated on the option
        # so a stats-off read never imports the stats package
        from .stats.collect import build_and_store_profiles

        profiles = build_and_store_profiles(files, copybook_contents,
                                            params, backend,
                                            io=_io_config(params))
        data.stats_profiles = {url: profile.summary()
                               for url, profile in profiles.items()}
    from .plan.cache import parse_fingerprint

    data.plan_fingerprint = parse_fingerprint(copybook_contents, params)
    if explain:
        from .explain import build_scan_report

        return build_scan_report(params, files=files, data=data,
                                 backend=backend)
    return data


class _BatchTap:
    """Adapter between the engine's `on_batch(index, table)` hook and a
    user `batch_callback`: counts deliveries so read_cobol knows whether
    the incremental path already streamed, and provides the whole-result
    fallback for paths without a mid-scan tap."""

    __slots__ = ("callback", "count")

    def __init__(self, callback):
        self.callback = callback
        self.count = 0

    def emit(self, index: int, table) -> None:
        if table is not None:
            # failed-chunk signals (table=None) forward but don't count
            # as deliveries — an all-chunks-failed pipelined scan must
            # still take the whole-result fallback below
            self.count += 1
        self.callback(index, table)

    def emit_data(self, data: "CobolData") -> None:
        """Per-result tables of a finished read, in record order."""
        if data._arrow_tables is not None:
            for i, table in enumerate(data._arrow_tables):
                self.emit(i, table)
        elif data._results:
            for i, result in enumerate(data._results):
                self.emit(i, result.to_arrow(data.output_schema))


def _build_obs_context(params: ReaderParameters, metrics: ReadMetrics,
                       progress_callback, tracer=None):
    """The read's ObsContext: tracer when `trace_file` is set (or one
    was injected by an embedder like the serving tier), progress
    tracker when a callback was passed, the default metrics registry's
    scan metric set, and the metrics object's per-read cache scope."""
    from .obs.context import ObsContext
    from .obs.metrics import scan_metrics

    if tracer is None and params.trace_file:
        from .obs.trace import Tracer

        tracer = Tracer(trace_id=params.trace_id or None)
    if tracer is not None:
        if params.request_id:
            tracer.meta.setdefault("request_id", params.request_id)
        metrics.tracer = tracer
    progress = None
    if progress_callback is not None:
        from .obs.progress import ProgressTracker

        progress = ProgressTracker(
            progress_callback, bytes_total=metrics.bytes_read,
            min_interval_s=params.progress_interval_s)
    return ObsContext(tracer=tracer, metrics=scan_metrics(),
                      progress=progress,
                      cache_scope=metrics.cache_scope,
                      io_stats=metrics.io_stats,
                      field_costs=metrics.field_costs_acc,
                      pass_counts=metrics.pass_counts)


def _finish_obs(obs_ctx, params: ReaderParameters, data) -> None:
    """End-of-read observability: the final done=True progress snapshot
    and the Chrome-trace artifact (metrics.finalize already closed the
    scan-root span and captured the span list)."""
    if obs_ctx.progress is not None:
        obs_ctx.progress.finish(records_total=len(data))
    if obs_ctx.tracer is not None and params.trace_file:
        if data.metrics is not None:
            # lazy post-read assembly refreshes the artifact with its
            # accrued field costs (ReadMetrics.refresh_trace_field_costs)
            data.metrics._trace_file = params.trace_file
        try:
            obs_ctx.tracer.write_chrome_trace(params.trace_file)
        except OSError:
            # the destination was validated up front, but it can still
            # vanish (or the disk fill) during a long scan — a lost
            # trace must not discard a fully successful read
            import logging

            logging.getLogger(__name__).warning(
                "failed to write trace_file %r; the read succeeded",
                params.trace_file, exc_info=True)


def _abort_obs(obs_ctx, params: ReaderParameters) -> None:
    """Best-effort telemetry flush when the scan raised: every step is
    individually guarded so nothing here can shadow the real error."""
    if obs_ctx.progress is not None:
        try:
            obs_ctx.progress.finish()
        except Exception:
            pass
    if obs_ctx.tracer is not None and params.trace_file:
        try:
            obs_ctx.tracer.write_chrome_trace(params.trace_file)
        except Exception:
            pass


def _read_cobol_single_host(files, copybook_contents,
                            params: ReaderParameters, backend: str,
                            parallelism: int,
                            pipe_workers: int, use_pipeline: bool,
                            is_var_len: bool,
                            debug_ignore_file_size: bool,
                            metrics: ReadMetrics,
                            io=None, batch_tap=None) -> "CobolData":
    """The in-process execution paths (sequential, threaded shard scan,
    chunked pipeline) — read_cobol minus option parsing and multihost."""
    on_batch = batch_tap.emit if batch_tap is not None else None
    results: List[FileResult] = []
    copybook_obj: Optional[Copybook] = None
    # attribution on: give the SEQUENTIAL paths a StageTimes too, so the
    # per-field decode costs have a decode-stage busy total to anchor
    # against (pipelined paths attach the executor's own; _scan_var_len
    # builds its shard-pool one)
    seq_stage_times = None
    if (metrics.field_costs_acc is not None and not use_pipeline
            and not is_var_len and backend != "host"):
        from .profiling import StageTimes

        seq_stage_times = StageTimes()
        metrics.stage_busy = seq_stage_times

    with stage(metrics, "parse_copybook"):
        if is_var_len:
            reader = VarLenReader(copybook_contents, params)
        else:
            reader = FixedLenReader(copybook_contents, params)
        copybook_obj = reader.copybook

    if params.use_stats:
        # arm zone-map chunk skipping from warm profiles (stats/skip.py);
        # gated on the option so a stats-off read never imports the
        # stats package at all
        from .stats.skip import maybe_attach_skipper

        maybe_attach_skipper(reader, files, params, io=io)

    # the output schema is a pure function of copybook + options; built
    # before the scan so the pipelined path can assemble per-chunk Arrow
    # tables against it while later chunks are still decoding
    from .reader.schema import output_schema_for

    schema = output_schema_for(copybook_obj, params, is_var_len)

    retry = _retry_policy(params)
    retries_seen: List[int] = []  # list.append is GIL-atomic across shards
    # chunks the supervised pipeline gave up on (partial policy only;
    # fail_fast raises from inside the executor instead)
    shard_failures: List[ShardFailureInfo] = []

    def on_retry():
        retries_seen.append(1)

    with stage(metrics, "scan"):
        if is_var_len:
            prefix = (params.multisegment.segment_id_prefix
                      if params.multisegment
                      and params.multisegment.segment_id_prefix
                      else default_segment_id_prefix())
            if backend == "host":
                for file_order, file_path in enumerate(files):
                    ledger = (params.new_diagnostics()
                              if params.is_permissive else None)
                    reasons: dict = {}
                    with open_stream(file_path, retry=retry,
                                     on_retry=on_retry, io=io) as stream:
                        result = rows_file_result(list(
                            reader.iter_rows(
                                stream, file_id=file_order,
                                segment_id_prefix=prefix,
                                start_record_id=file_order
                                * DEFAULT_FILE_RECORD_ID_INCREMENT,
                                ledger=ledger,
                                corrupt_reasons_out=reasons)))
                    result.diagnostics = ledger
                    result.corrupt_record_field = \
                        params.corrupt_record_column
                    result.corrupt_row_reasons = reasons or None
                    results.append(result)
            elif use_pipeline:
                from .engine.pipeline import pipelined_var_len_scan

                with stage(metrics, "plan_index"):
                    shards = _plan_var_len_shards(reader, files, params,
                                                  retry, on_retry, io)
                metrics.shards = len(shards)
                results, failed = pipelined_var_len_scan(
                    reader, shards, params, backend, prefix, schema,
                    pipe_workers, metrics=metrics, retry=retry,
                    on_retry=on_retry, io=io, on_batch=on_batch)
                shard_failures.extend(failed)
                results = [r for r in results if r is not None]
            else:
                results = _scan_var_len(reader, files, params, backend,
                                        prefix, parallelism,
                                        metrics=metrics, retry=retry,
                                        on_retry=on_retry, io=io)
        elif use_pipeline:
            from .engine.pipeline import pipelined_fixed_scan

            results, failed = pipelined_fixed_scan(
                reader, files, params, backend, schema, pipe_workers,
                ignore_file_size=debug_ignore_file_size, metrics=metrics,
                retry=retry, on_retry=on_retry, io=io, on_batch=on_batch)
            shard_failures.extend(failed)
            results = [r for r in results if r is not None]
        else:
            for file_order, file_path in enumerate(files):
                base = file_order * DEFAULT_FILE_RECORD_ID_INCREMENT
                if backend == "host":
                    ledger = (params.new_diagnostics()
                              if params.is_permissive else None)
                    reasons = {}
                    data = _read_file_bytes(file_path, retry, on_retry,
                                            io)
                    result = rows_file_result(list(
                        reader.iter_rows_host(
                            data, file_id=file_order,
                            first_record_id=base,
                            input_file_name=file_path,
                            ignore_file_size=debug_ignore_file_size,
                            ledger=ledger,
                            corrupt_reasons_out=reasons)))
                    result.diagnostics = ledger
                    result.corrupt_record_field = \
                        params.corrupt_record_column
                    result.corrupt_row_reasons = reasons or None
                    results.append(result)
                else:
                    results.extend(_read_fixed_len_chunked(
                        reader, file_path, params, backend, file_order,
                        base, debug_ignore_file_size, retry, on_retry,
                        io, stage_times=seq_stage_times))

    data = CobolData.from_results(results, schema, parallelism=parallelism)
    data.diagnostics = _aggregate_diagnostics(
        params, results,
        len(retries_seen) + getattr(metrics, "prescan_io_retries", 0),
        shard_failures)
    pushdown = getattr(reader, "pushdown", None)
    if pushdown is not None:
        # pruning counters into the read's metrics BEFORE finalize, so
        # the registry publication (Prometheus) sees them too
        metrics.pushdown = pushdown.stats.as_dict()
    metrics.finalize(data, len(results))
    return data


def _aggregate_diagnostics(params: ReaderParameters,
                           results: List["FileResult"],
                           io_retries: int,
                           shard_failures: Sequence[ShardFailureInfo] = (),
                           ) -> Optional[ReadDiagnostics]:
    """Merge per-file/shard ledgers into the read-level ledger. None under
    fail_fast with no IO incidents and no lost shards (the read either
    succeeded cleanly or raised). Deterministic: entries sort by
    (file, offset) with stable cap truncation (ReadDiagnostics.merged),
    so sequential, threaded, and pipelined scans over the same bytes
    produce byte-identical ledgers."""
    if (not params.is_permissive and io_retries == 0
            and not shard_failures):
        return None
    merged = ReadDiagnostics.merged(
        (getattr(r, "diagnostics", None) for r in results),
        max_entries=params.max_corrupt_ledger_entries)
    merged.io_retries += io_retries
    for failure in shard_failures:
        merged.record_shard_failure(failure)
    return merged


# fixed-length files stream through bounded chunk reads instead of one
# whole-file read(): peak memory stays ~one chunk + its decoded columns
# (FileStreamer.scala:37-130's buffered role on the fixed path)
FIXED_READ_CHUNK_BYTES = 64 * 1024 * 1024


def _read_file_bytes(path: str, retry: Optional[RetryPolicy] = None,
                     on_retry=None, io=None):
    """Whole-file bytes-like payload: a read-only mmap memoryview for
    local files (FSStream.next_view), plain bytes otherwise — consumers
    must stick to buffer-protocol operations (len/slice/np.frombuffer)."""
    from .reader.stream import open_stream

    with open_stream(path, retry=retry, on_retry=on_retry,
                     io=io) as stream:
        return stream.next_view(stream.size())


def _read_fixed_len_chunked(reader, file_path: str, params, backend: str,
                            file_order: int, base_record_id: int,
                            ignore_file_size: bool,
                            retry: Optional[RetryPolicy] = None,
                            on_retry=None, io=None,
                            stage_times=None) -> List["FileResult"]:
    from .obs.context import current as obs_current
    from .reader.stream import open_stream, source_size

    from .engine.chunks import fixed_file_chunkable

    obs = obs_current()
    progress = obs.progress if obs is not None else None

    def track(result, nbytes: int) -> "FileResult":
        if progress is not None:
            progress.chunk_started()
            progress.chunk_done(bytes_done=nbytes,
                                records=result.n_rows)
        return result

    rs = reader.record_size
    size = source_size(file_path, retry=retry, on_retry=on_retry, io=io)
    skipper = getattr(reader, "chunk_skipper", None)
    # fixed chunking is output-invariant (record-aligned strides,
    # absolute Record_Id bases), so with zone-map skipping armed the
    # scan stride shrinks to the profile grid — skip granularity then
    # matches what the profile can actually prove
    stride_bytes = FIXED_READ_CHUNK_BYTES
    if skipper is not None:
        from .reader.parameters import MEGABYTE

        stride_bytes = min(stride_bytes, max(
            rs, int(params.stats_chunk_mb * MEGABYTE) // rs * rs))
    from .io.compress import compressed_chunkable

    # the SAME predicates drive the pipelined chunk planner — the
    # pipelined-vs-sequential parity guarantee needs one split rule
    # (compressed inputs without a decompressed cache plane stay whole:
    # chunk offsets would re-inflate the prefix per chunk)
    if not fixed_file_chunkable(size, rs, params, stride_bytes,
                                ignore_file_size) \
            or not compressed_chunkable(file_path, io):
        if skipper is not None and skipper.should_skip(file_path, 0, -1):
            return []
        return [track(reader.read_result(
            _read_file_bytes(file_path, retry, on_retry, io),
            backend=backend,
            file_id=file_order, first_record_id=base_record_id,
            input_file_name=file_path, ignore_file_size=ignore_file_size,
            stage_times=stage_times),
            size)]
    chunk_bytes = max(rs, (stride_bytes // rs) * rs)
    results: List[FileResult] = []
    if skipper is not None:
        # zone-map skipping armed: bounded per-chunk streams, so a
        # skipped range's bytes are never read at all (the single-stream
        # loop below would have to read past them)
        done = 0
        while done < size:
            nbytes = min(chunk_bytes, size - done)
            if skipper.should_skip(file_path, done, done + nbytes):
                done += nbytes
                continue
            with open_stream(file_path, start_offset=done,
                             maximum_bytes=nbytes, retry=retry,
                             on_retry=on_retry, io=io) as stream:
                data = stream.next_view(nbytes)
                if not data:
                    break
                if len(data) % rs and done + len(data) < size:
                    raise IOError(
                        f"Short read from {file_path} at {done}")
                results.append(track(reader.read_result(
                    data, backend=backend, file_id=file_order,
                    first_record_id=base_record_id + done // rs,
                    input_file_name=file_path,
                    ignore_file_size=ignore_file_size,
                    stage_times=stage_times), len(data)))
            done += len(data)
        return results
    done = 0
    with open_stream(file_path, retry=retry, on_retry=on_retry,
                     io=io) as stream:
        while done < size:
            data = stream.next_view(min(chunk_bytes, size - done))
            if not data:
                break
            if len(data) % rs and done + len(data) < size:
                raise IOError(f"Short read from {file_path} at {done}")
            results.append(track(reader.read_result(
                data, backend=backend, file_id=file_order,
                first_record_id=base_record_id + done // rs,
                input_file_name=file_path,
                ignore_file_size=ignore_file_size,
                stage_times=stage_times), len(data)))
            done += len(data)
    return results


def _read_cobol_multihost(files, copybook_contents, params, hosts: int,
                          debug_ignore_file_size: bool,
                          metrics: Optional[ReadMetrics] = None
                          ) -> "CobolData":
    """The multi-host execution path: plan + fork + reassemble
    (parallel/hosts.multihost_scan). Output is Arrow-backed; row order and
    Record_Ids are byte-identical to the single-process read."""
    from .parallel.hosts import multihost_scan, plan_fixed_len_shards

    is_var_len = params.needs_var_len_reader
    with stage(metrics, "parse_copybook"):
        if is_var_len:
            reader = VarLenReader(copybook_contents, params)
            prefix = (params.multisegment.segment_id_prefix
                      if params.multisegment
                      and params.multisegment.segment_id_prefix
                      else default_segment_id_prefix())
        else:
            reader = FixedLenReader(copybook_contents, params)
            prefix = ""
    if params.use_stats and is_var_len:
        # multihost VRL shards come from the same sparse-index planner
        # as single-host scans, so warm profiles skip there too (fixed
        # multihost shards are host-balanced ranges, left unfiltered)
        from .stats.skip import maybe_attach_skipper

        maybe_attach_skipper(reader, files, params,
                             io=_io_config(params))
    with stage(metrics, "plan_index"):
        if is_var_len:
            shards = _plan_var_len_shards(reader, files, params,
                                          io=_io_config(params))
        else:
            shards = plan_fixed_len_shards(reader, files, params, hosts)
    from .reader.schema import output_schema_for

    schema = output_schema_for(reader.copybook, params, is_var_len)
    with stage(metrics, "scan"):
        tables, shard_failures, supervision = multihost_scan(
            reader, shards, is_var_len, schema, hosts, prefix,
            ignore_file_size=debug_ignore_file_size)
    if metrics is not None:
        metrics.supervision = supervision
        pushdown = getattr(reader, "pushdown", None)
        if pushdown is not None:
            # planning runs in-parent, so chunk-skip counters are real;
            # per-record pruning counters stay in the forked workers
            metrics.pushdown = pushdown.stats.as_dict()
    # merge the per-shard ledgers the workers shipped back as IPC schema
    # metadata (stripped here so shard keys don't leak into — or break
    # concatenation of — the unified table); shard order is canonical, so
    # entry order matches a single-process read. Workers ship a ledger
    # under fail_fast too when IO retries fired, matching
    # _aggregate_diagnostics.
    shard_ledgers: List[ReadDiagnostics] = []
    found = False
    cleaned = []
    for table in tables:
        metadata = dict(table.schema.metadata or {})
        raw = metadata.pop(b"cobrix_tpu.shard_diagnostics", None)
        if raw:
            found = True
            shard_ledgers.append(ReadDiagnostics.from_json(raw))
            table = table.replace_schema_metadata(metadata or None)
        cleaned.append(table)
    diagnostics = ReadDiagnostics.merged(
        shard_ledgers, max_entries=params.max_corrupt_ledger_entries)
    # shards the supervisor gave up on (partial policy): the rows are
    # missing from the output — say so on the read's ledger
    for failure in shard_failures:
        diagnostics.record_shard_failure(failure)
    # sizing-probe retries happened in-parent, before the fork: fold
    # them in here (workers ledger their own retries per shard)
    prescan = (getattr(metrics, "prescan_io_retries", 0)
               if metrics is not None else 0)
    diagnostics.io_retries += prescan
    data = CobolData.from_arrow_tables(cleaned, schema)
    data.diagnostics = (diagnostics
                        if params.is_permissive or found or shard_failures
                        or prescan else None)
    if metrics is not None:
        metrics.finalize(data, len(shards))
    return data
