"""Declarative service-level objectives over the scan audit stream.

An operator declares what "good" means ONCE — as machine-independent
thresholds where possible — and every completed scan is classified
good/bad per objective. Three kinds cover the serving tier:

* ``first_batch`` / ``e2e`` — latency: the scan is good when its
  first-batch (or end-to-end) latency is at or under the threshold.
  Declared as a percentile target (``first_batch_p99=0.5``: 99% of
  scans must see a first batch within 500 ms).
* ``roofline`` — throughput, machine-independently: the scan is good
  when its achieved bytes/s is at least ``threshold`` of the calibrated
  host memory bandwidth (obs.roofline, the decode-throughput-law
  anchor). "The service regressed" and "this machine is slower" stop
  being the same alert. Scans without a calibration are not counted.
* ``error_rate`` — availability: every finished scan is good iff it
  completed ok (``error_rate=0.01`` = 99% objective).

Classification feeds two surfaces:

* Prometheus **good/bad counters** (``cobrix_slo_good_total`` /
  ``cobrix_slo_bad_total``, labeled ``slo``/``tenant``) — the
  burn-rate-friendly shape: ``bad/(good+bad)`` over two windows is the
  standard multi-window burn-rate alert, no histogram quantile math.
* the **status document** (`SloTracker.status()`) served on `/healthz`
  and `/debug/slo`: per-objective totals, the observed good ratio, and
  whether the error budget is currently burning.

Evaluation is one comparison per objective per SCAN (never per record),
so SLO tracking adds nothing to the decode hot path.
"""
from __future__ import annotations

import re
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .metrics import default_registry

_SLO_SYNTAX = re.compile(
    r"^(?:(first_batch|e2e)_p(\d{1,2}(?:\.\d+)?)"
    r"|(roofline)_min|(error)_rate)=([0-9.]+)$")


@dataclass(frozen=True)
class Slo:
    """One objective: `name` is the Prometheus label value, `kind` the
    classifier, `threshold` the per-scan good/bad cut, `objective` the
    target good ratio the error budget is measured against."""

    name: str
    kind: str           # "first_batch" | "e2e" | "roofline" | "error_rate"
    threshold: float
    objective: float = 0.99

    def evaluate(self, record) -> Optional[bool]:
        """True = good, False = bad, None = not applicable to this
        record. Only 'ok' and 'error' outcomes count: rejected means
        admission did its job, client_gone means the CLIENT hung up —
        neither is the scan plane failing its objective. Latency kinds
        also skip scans without the measurement."""
        if record.outcome not in ("ok", "error"):
            return None
        if getattr(record, "resume_of", ""):
            # a recovery attempt of an already-accounted logical
            # request (replica failover): evaluating it again would
            # double-burn latency objectives — a resumed scan's "first
            # batch" sits behind a skip of everything already delivered
            return None
        if getattr(record, "follow", False) \
                and self.kind in ("e2e", "roofline"):
            # a follow session streams a LIVE feed for as long as the
            # subscriber stays — wall-clock duration and aggregate
            # throughput measure the feed, not the server; first-batch
            # and error-rate objectives still apply
            return None
        if self.kind == "error_rate":
            return record.outcome == "ok"
        if record.outcome != "ok":
            # a failed scan has no honest latency sample, but it DID
            # burn the user's budget for this objective too
            return False
        if self.kind == "first_batch":
            v = record.first_batch_s
            return None if v is None else v <= self.threshold
        if self.kind == "e2e":
            v = record.e2e_s
            return None if v is None else v <= self.threshold
        if self.kind == "roofline":
            v = record.roofline_fraction
            return None if v is None else v >= self.threshold
        return None


def parse_slo(spec: str) -> Slo:
    """One CLI/config objective. Accepted shapes::

        first_batch_p99=0.5    99% of scans: first batch within 0.5 s
        e2e_p95=3.0            95% of scans: done within 3 s
        roofline_min=0.05      99% of scans: >= 5% of host bandwidth
        error_rate=0.01        error budget: 1% of scans may fail
    """
    m = _SLO_SYNTAX.match(spec.strip())
    if not m:
        raise ValueError(
            f"unrecognized SLO spec {spec!r}; expected one of "
            "'first_batch_pNN=SECONDS', 'e2e_pNN=SECONDS', "
            "'roofline_min=FRACTION', 'error_rate=FRACTION'")
    latency_kind, pct, roofline, error, value = m.groups()
    value = float(value)
    if latency_kind:
        return Slo(name=f"{latency_kind}_p{pct}", kind=latency_kind,
                   threshold=value, objective=float(pct) / 100.0)
    if roofline:
        if not 0.0 < value <= 1.0:
            raise ValueError(
                f"roofline_min wants a fraction in (0, 1], got {value}")
        return Slo(name="roofline_min", kind="roofline", threshold=value)
    if not 0.0 <= value < 1.0:
        raise ValueError(
            f"error_rate wants a fraction in [0, 1), got {value}")
    return Slo(name="error_rate", kind="error_rate", threshold=value,
               objective=1.0 - value)


def parse_slos(specs: Sequence[str]) -> List[Slo]:
    slos = [parse_slo(s) for s in specs]
    names = [s.name for s in slos]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"duplicate SLO name(s): {sorted(dupes)}")
    return slos


# multi-window burn rates: the standard fast/slow alert pair — the fast
# window catches a cliff (page), the slow window catches a leak
# (ticket). Events are bucketed so the memory is bounded: at most
# slow_window/bucket entries per (slo, tenant) ever exist.
FAST_WINDOW_S = 60.0
SLOW_WINDOW_S = 600.0
_BURN_BUCKET_S = 5.0


class SloTracker:
    """Per-scan evaluation + good/bad counters + status document.

    Besides lifetime totals, the tracker keeps time-bucketed good/bad
    counts per (slo, tenant) so `burn()` can answer "what fraction of
    the error budget is being spent RIGHT NOW" over a fast and a slow
    window — the multi-window burn-rate shape the fleet rollup
    (fleet/federate.py) aggregates across replicas. burn > 1.0 means
    the budget is being spent faster than the objective allows."""

    def __init__(self, slos: Sequence[Slo], registry=None,
                 fast_window_s: float = FAST_WINDOW_S,
                 slow_window_s: float = SLOW_WINDOW_S,
                 clock=None):
        self.slos = list(slos)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self._clock = clock or time.monotonic
        r = registry or default_registry()
        self._good = r.counter(
            "cobrix_slo_good_total",
            "Scans meeting the objective, by SLO and tenant "
            "(burn rate = bad / (good + bad))",
            label_names=("slo", "tenant"))
        self._bad = r.counter(
            "cobrix_slo_bad_total",
            "Scans violating the objective, by SLO and tenant",
            label_names=("slo", "tenant"))
        self._lock = threading.Lock()
        # in-process totals for status(): counter children are labeled
        # per tenant; the health view wants the cross-tenant aggregate
        self._totals: Dict[str, List[int]] = {
            s.name: [0, 0] for s in self.slos}
        # (slo, tenant) -> deque of [bucket_start_s, good, bad]
        self._windows: Dict[Tuple[str, str], deque] = {}

    def _note_window_locked(self, slo_name: str, tenant: str,
                            good: bool) -> None:
        now = self._clock()
        bucket = now - (now % _BURN_BUCKET_S)
        dq = self._windows.setdefault((slo_name, tenant), deque())
        if dq and dq[-1][0] == bucket:
            dq[-1][1 if good else 2] += 1
        else:
            dq.append([bucket, 1 if good else 0, 0 if good else 1])
        horizon = now - self.slow_window_s - _BURN_BUCKET_S
        while dq and dq[0][0] < horizon:
            dq.popleft()

    def observe(self, record) -> List[str]:
        """Classify one ScanRecord against every objective; returns the
        names of the objectives it BREACHED (for the flight recorder).
        Also stamps ``record.slo_breaches``."""
        breaches: List[str] = []
        for slo in self.slos:
            good = slo.evaluate(record)
            if good is None:
                continue
            (self._good if good else self._bad).labels(
                slo=slo.name, tenant=record.tenant).inc()
            with self._lock:
                self._totals[slo.name][0 if good else 1] += 1
                self._note_window_locked(slo.name, record.tenant, good)
            if not good:
                breaches.append(slo.name)
        record.slo_breaches = breaches
        return breaches

    def _window_counts_locked(self, slo_name: str, window_s: float,
                              tenant: Optional[str] = None
                              ) -> Tuple[int, int]:
        now = self._clock()
        horizon = now - window_s - _BURN_BUCKET_S
        good = bad = 0
        for (name, t), dq in self._windows.items():
            if name != slo_name:
                continue
            if tenant is not None and t != tenant:
                continue
            for bucket, g, b in dq:
                if bucket >= horizon:
                    good += g
                    bad += b
        return good, bad

    def burn(self, slo: Slo, window_s: float,
             tenant: Optional[str] = None) -> dict:
        """Error-budget burn over a trailing window: ``ratio`` is the
        bad fraction of scans in the window, ``burn`` that ratio over
        the budget fraction ``1 - objective`` (the conventional burn
        rate: 1.0 = spending exactly at the objective's allowance).
        None fields when the window saw no evaluated scans."""
        with self._lock:
            good, bad = self._window_counts_locked(
                slo.name, window_s, tenant)
        seen = good + bad
        ratio = (bad / seen) if seen else None
        budget = 1.0 - slo.objective
        rate = (None if ratio is None
                else (ratio / budget if budget > 0
                      else (0.0 if ratio == 0 else float("inf"))))
        return {"window_s": window_s, "good": good, "bad": bad,
                "ratio": round(ratio, 6) if ratio is not None else None,
                "burn": round(rate, 4) if rate is not None else None}

    def status(self) -> dict:
        """Per-objective summary for /healthz + /debug/slo: lifetime
        totals plus the fast/slow window burn rates."""
        out = {}
        with self._lock:
            totals = {k: tuple(v) for k, v in self._totals.items()}
        for slo in self.slos:
            good, bad = totals[slo.name]
            seen = good + bad
            ratio = (good / seen) if seen else None
            out[slo.name] = {
                "kind": slo.kind,
                "threshold": slo.threshold,
                "objective": slo.objective,
                "good": good,
                "bad": bad,
                "ratio": round(ratio, 6) if ratio is not None else None,
                # burning: the observed ratio is under the objective —
                # the budget is being spent faster than allowed
                "burning": bool(seen and ratio < slo.objective),
                "burn_fast": self.burn(slo, self.fast_window_s),
                "burn_slow": self.burn(slo, self.slow_window_s),
            }
        return out
