"""fsspec byte-range backend: any `scheme://` URL fsspec can open.

The reference streams mainframe files from HDFS/S3 through Hadoop's
FSDataInputStream (FileStreamer.scala:37-130); the Python ecosystem's
equivalent of that pluggable-filesystem layer is fsspec, so one adapter
covers `s3://`, `gs://`, `az://`, `hdfs://`, `memory://`, `http(s)://`
and anything else with an installed protocol.

Design points:

* **Stateless range reads.** Every read is `fs.cat_file(path, start,
  end)` — no long-lived file handle, so a source object never carries
  an fd across a fork. The filesystem object itself is rebuilt lazily
  per process (`skip_instance_cache` after a pid change): fsspec's
  class-level instance cache would otherwise hand a forked multihost
  worker its parent's live connections.
* **Fingerprints**, not timestamps-as-config: `fs.ukey()` (etag/inode
  hash) when the backend implements it, else a size+mtime/etag digest
  from `fs.info()` — the key the block cache and sparse-index store
  version their entries by.
* **Listing and sizing** route through the same filesystem, so a remote
  *directory* (or glob) scan works end to end: `fsspec_listing` mirrors
  the local lister's hidden-file rules and deterministic order.
* fsspec is an **optional dependency**: everything imports lazily and a
  missing module surfaces one actionable ImportError, not a stack of
  attribute errors.
"""
from __future__ import annotations

import hashlib
import os
from typing import List, Optional

from ..reader.stream import (ByteRangeSource, path_scheme,
                             register_stream_backend)

_IMPORT_HINT = (
    "reading '{url}' requires the optional dependency 'fsspec' "
    "(pip install fsspec; object stores also need their protocol "
    "package, e.g. s3fs or gcsfs)")


def _fsspec(url: str):
    try:
        import fsspec
    except ImportError as exc:
        raise ImportError(_IMPORT_HINT.format(url=url)) from exc
    return fsspec


# the pid whose fsspec class-level instance cache is trustworthy: a
# forked child inherits this module (and fsspec's cache) from the
# parent, so a pid mismatch means every cached filesystem may hold the
# parent's sockets/event-loop threads — async backends (s3fs/gcsfs)
# wedge forever on them. Detected here because the inherited value
# still names the parent.
_INSTANCE_CACHE_PID = os.getpid()


def _inherited_cache() -> bool:
    return os.getpid() != _INSTANCE_CACHE_PID


def _fresh_fs(fsspec, url: str, **storage_options):
    """A filesystem built OUTSIDE fsspec's instance cache (fork-safe)."""
    scheme = path_scheme(url) or "file"
    fs = fsspec.filesystem(scheme, skip_instance_cache=True,
                           **storage_options)
    return fs, fs._strip_protocol(url)


def _split(url: str):
    """(filesystem, backend path) for one URL; the filesystem comes from
    fsspec's per-process instance cache — bypassed in a forked child,
    where the cache is the parent's."""
    fsspec = _fsspec(url)
    if _inherited_cache():
        return _fresh_fs(fsspec, url)
    fs, path = fsspec.core.url_to_fs(url)
    return fs, path


def known_protocol(scheme: str) -> bool:
    """True when fsspec (if installed) knows `scheme` — the gate for
    auto-registering a backend for an unhandled URL scheme."""
    try:
        from fsspec.registry import known_implementations, registry
    except ImportError:
        return False
    return scheme in registry or scheme in known_implementations


class FsspecSource(ByteRangeSource):
    """ByteRangeSource over one fsspec URL. Fork-safe: the filesystem
    object is (re)built lazily whenever the owning pid changes."""

    def __init__(self, url: str, **storage_options):
        self._url = url
        self._options = storage_options
        self._fs = None
        self._path = None
        self._pid = -1
        self._size: Optional[int] = None
        self._fingerprint: Optional[str] = None

    def _filesystem(self):
        pid = os.getpid()
        if self._fs is None or pid != self._pid:
            fsspec = _fsspec(self._url)
            if (self._fs is None and not self._options
                    and not _inherited_cache()):
                fs, path = fsspec.core.url_to_fs(self._url)
            else:
                # bypass the instance cache when this source object
                # crossed a fork, when the whole PROCESS inherited the
                # cache from a fork parent (a source built fresh in a
                # worker would otherwise resolve to the parent's live
                # filesystem), or with explicit options: a cached object
                # may hold another process's sockets/event loops
                fs, path = _fresh_fs(fsspec, self._url, **self._options)
            self._fs, self._path, self._pid = fs, path, pid
        return self._fs, self._path

    def size(self) -> int:
        if self._size is None:
            fs, path = self._filesystem()
            self._size = int(fs.size(path))
        return self._size

    def read(self, offset: int, n: int) -> bytes:
        size = self.size()
        if offset >= size or n <= 0:
            return b""
        fs, path = self._filesystem()
        return fs.cat_file(path, start=offset,
                           end=min(offset + n, size))

    def fingerprint(self) -> str:
        """Stable content-version key: ukey when the backend has one,
        else a digest of the info() entry's etag/checksum/mtime/size."""
        if self._fingerprint is None:
            fs, path = self._filesystem()
            try:
                self._fingerprint = str(fs.ukey(path))
            except (NotImplementedError, AttributeError, OSError):
                info = fs.info(path)
                token = repr((info.get("ETag") or info.get("etag")
                              or info.get("checksum"),
                              info.get("mtime") or info.get("created")
                              or info.get("LastModified"),
                              info.get("size")))
                self._fingerprint = hashlib.sha256(
                    token.encode("utf-8", "replace")).hexdigest()
        return self._fingerprint

    @property
    def name(self) -> str:
        return self._url

    def close(self) -> None:
        self._fs = None  # stateless reads: nothing else to release


def open_fsspec_source(url: str, **storage_options) -> FsspecSource:
    """Open one fsspec URL as a ByteRangeSource (raises the actionable
    ImportError immediately when fsspec is missing, and the backend's
    own error when the object does not exist)."""
    source = FsspecSource(url, **storage_options)
    source.size()  # existence probe: fail at open, not first read
    return source


def _hidden(rel_path: str) -> bool:
    """Mirror the local lister: any path component below the listing
    root starting with '.' or '_' hides the file."""
    return any(part.startswith((".", "_"))
               for part in rel_path.split("/") if part)


def fsspec_listing(url: str) -> List[str]:
    """Recursive file listing of one fsspec URL (file, directory, or
    glob) with local-lister semantics: hidden files skipped, stable
    sorted order, FileNotFoundError when nothing matches. Returned
    entries are full URLs of the same scheme."""
    fs, path = _split(url)
    scheme = path_scheme(url)

    def rebuild(p: str) -> str:
        # keep backend-absolute paths absolute ('local:///tmp/x' must
        # not collapse to 'local://tmp/x', a cwd-relative read)
        return f"{scheme}://{p}"

    def expand_dir(root: str) -> List[str]:
        files = []
        root_norm = root.rstrip("/")
        for p in fs.find(root_norm):
            rel = p[len(root_norm):].lstrip("/")
            if not _hidden(rel):
                files.append(p)
        return files

    if fs.isfile(path):
        return [rebuild(path)]
    if fs.isdir(path):
        return [rebuild(p) for p in sorted(expand_dir(path))]
    matched = sorted(fs.glob(path))
    if not matched:
        raise FileNotFoundError(f"Input path does not exist: {url}")
    out: List[str] = []
    for m in matched:
        if os.path.basename(str(m).rstrip("/")).startswith((".", "_")):
            continue
        if fs.isdir(m):
            out.extend(rebuild(p) for p in sorted(expand_dir(str(m))))
        else:
            out.append(rebuild(str(m)))
    return out


def fsspec_size(url: str) -> int:
    """Byte size of one fsspec URL (the listing/planning sizer)."""
    fs, path = _split(url)
    return int(fs.size(path))


def register_fsspec_backend(scheme: str, **storage_options) -> None:
    """Register `scheme://` to resolve through fsspec (source + lister +
    sizer). `open_stream`/`list_input_files` call this automatically for
    any scheme fsspec knows, so it is only needed to pin non-default
    `storage_options` (credentials, endpoints) to a scheme."""
    if storage_options:
        def factory(url: str) -> FsspecSource:
            return open_fsspec_source(url, **storage_options)
    else:
        factory = open_fsspec_source
    register_stream_backend(scheme, factory, lister=fsspec_listing,
                            sizer=fsspec_size)
