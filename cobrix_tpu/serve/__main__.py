"""`python -m cobrix_tpu.serve` — run a scan server from the CLI.

Exit code 0 = drained clean on SIGTERM/SIGINT; 1 = in-flight scans had
to be abandoned after `--drain-timeout` seconds.
"""
import sys

from .server import main

sys.exit(main())
