"""Native-assembly smoke check: fused decode->Arrow vs pure Python.

The native layer (cobrix_tpu/native/columnar.cpp) emits Arrow buffers
straight from record bytes — validity bitmaps, int32/int64/float data
buffers, decimal128 values — with the GIL released. A wrong-bytes fast
path would be a silent correctness bug wearing a speedup, so this check
reads every profile twice in one process — native dispatch ON, then
forced OFF (`native.set_disabled`) — and asserts rows, Arrow tables,
schema metadata, and error ledgers are identical.

    python tools/asmcheck.py                  # quick (~1-2 MB/profile)
    python tools/asmcheck.py --records 200    # tiny record-count mode
    python tools/asmcheck.py --sweep          # adds pipelined/multihost
                                              # modes + permissive-policy
                                              # corrupt-input fuzz (slow;
                                              # tier-1 runs quick)

Exit code 0 = byte-identical everywhere; 1 = any mismatch (or the
native library is unavailable — this check exists to exercise it).
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DECIMALS_COPYBOOK = """
       01  REC.
           05  ID        PIC 9(6).
           05  AMT-BCD   PIC S9(11)V99 COMP-3.
           05  AMT-WIDE  PIC S9(20)V9(4) COMP-3.
           05  RATE      PIC S9(3)V9(2).
           05  QTY       PIC S9(8) COMP.
           05  PRICE     COMP-2.
           05  NAME      PIC X(12).
"""


def _decimals_data(n: int, seed: int = 11) -> bytes:
    import numpy as np

    rng = np.random.default_rng(seed)
    out = bytearray()
    for i in range(n):
        rec = bytearray()
        rec += bytes(0xF0 + int(d) for d in f"{i % 999999:06d}")
        # S9(11)V99 COMP-3: 13 digits -> 7 bytes
        v = int(rng.integers(-10**12, 10**12))
        rec += _bcd(v, 7)
        # S9(20)V9(4) COMP-3: 24 digits -> 13 bytes (wide plane)
        w = int(rng.integers(-10**17, 10**17)) * int(rng.integers(1, 999))
        rec += _bcd(w, 13)
        # S9(3)V9(2) zoned: 5 digits, trailing overpunch
        r = int(rng.integers(-99999, 99999))
        rec += _zoned(r, 5)
        rec += int(rng.integers(-10**7, 10**7)).to_bytes(
            4, "big", signed=True)
        import struct

        rec += struct.pack(">d", float(rng.normal()))
        rec += f"NAME{i:08d}".encode("cp037")
        out += rec
    return bytes(out)


def _bcd(value: int, width: int) -> bytes:
    digits = str(abs(value)).zfill(width * 2 - 1)[-(width * 2 - 1):]
    nibbles = [int(d) for d in digits] + [0x0D if value < 0 else 0x0C]
    return bytes((nibbles[i] << 4) | nibbles[i + 1]
                 for i in range(0, len(nibbles), 2))


def _zoned(value: int, width: int) -> bytes:
    digits = str(abs(value)).zfill(width)[-width:]
    body = bytes(0xF0 + int(d) for d in digits[:-1])
    last = int(digits[-1])
    return body + bytes([(0xD0 if value < 0 else 0xC0) + last])


def _profiles(records: int | None, mb: float):
    from cobrix_tpu.testing import generators as g

    n1 = records or max(64, int(mb * 1024 * 1024) // 1493)
    n3 = records or max(64, int(mb * 1024 * 1024 / 5350))
    nh = (records // 4 if records else max(40, int(mb * 1024 * 1024 / 1350)))
    seg_opts = {f"redefine_segment_id_map:{i}": f"{name} => {sid}"
                for i, (sid, name) in enumerate(
                    g.HIERARCHICAL_SEGMENT_MAP.items())}
    child_opts = {f"segment-children:{i}": f"{parent} => {child}"
                  for i, (child, parent) in enumerate(
                      g.HIERARCHICAL_PARENT_MAP.items())}
    # each profile names the fused native passes a healthy build MUST
    # engage (ReadMetrics native_passes counters) — a silent fallback to
    # the multi-pass shape then fails the check instead of reading as a
    # slowdown. Asserted on the native-ON read of quick mode only
    # (multihost workers count in their own processes).
    return [
        ("exp1_fixed", g.generate_exp1(n1, seed=7).tobytes(),
         dict(copybook_contents=g.EXP1_COPYBOOK),
         {"fused_assembly", "string_transcode", "take_elided"}),
        ("exp3_multiseg", g.generate_exp3(n3, seed=7),
         dict(copybook_contents=g.EXP3_COPYBOOK,
              is_record_sequence="true", segment_field="SEGMENT-ID",
              redefine_segment_id_map="STATIC-DETAILS => C",
              redefine_segment_id_map_1="CONTACTS => P"),
         {"fused_frame", "fused_assembly", "string_transcode",
          "take_elided"}),
        ("exp3_pruned_occurs", g.generate_exp3(n3, seed=7),
         dict(copybook_contents=g.EXP3_COPYBOOK,
              is_record_sequence="true", segment_field="SEGMENT-ID",
              redefine_segment_id_map="STATIC-DETAILS => C",
              redefine_segment_id_map_1="CONTACTS => P",
              select="SEGMENT-ID,COMPANY-ID,COMPANY-NAME"),
         {"fused_frame", "string_transcode", "take_elided"}),
        ("hierarchical", g.generate_hierarchical(nh, seed=7),
         dict(copybook_contents=g.HIERARCHICAL_COPYBOOK,
              is_record_sequence="true", segment_field="SEGMENT-ID",
              **seg_opts, **child_opts),
         {"fused_frame"}),
        ("decimals", _decimals_data(records or 1500),
         dict(copybook_contents=DECIMALS_COPYBOOK),
         {"fused_assembly", "string_transcode", "take_elided"}),
    ]


def _snapshot(path: str, kw: dict):
    from cobrix_tpu import read_cobol

    t0 = time.perf_counter()
    out = read_cobol(path, **kw)
    table = out.to_arrow()
    dt = time.perf_counter() - t0
    diag = out.diagnostics.as_dict() if out.diagnostics is not None else None
    # multihost results are Arrow-backed by contract (no Python rows)
    rows = None if "hosts" in kw else out.to_rows()
    # counters accumulate through to_arrow's captured references, so the
    # snapshot is taken AFTER the Arrow build
    passes = (out.metrics.pass_counts.as_dict()
              if getattr(out, "metrics", None) is not None else {})
    return rows, table, diag, dt, passes


def check_profile(name: str, data: bytes, kw: dict,
                  expect_passes=None) -> dict:
    from cobrix_tpu import native

    if not native.available():
        raise RuntimeError("native library unavailable — asmcheck "
                           "exists to exercise it (rebuild via "
                           "python -m cobrix_tpu.native.build)")
    with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
        f.write(data)
        path = f.name
    try:
        rows_n, table_n, diag_n, dt_n, passes_n = _snapshot(path, kw)
        native.set_disabled(True)
        try:
            rows_p, table_p, diag_p, dt_p, _ = _snapshot(path, kw)
        finally:
            native.set_disabled(False)
    finally:
        os.unlink(path)
    if rows_n != rows_p:
        raise AssertionError(f"{name}: row mismatch native vs python")
    if not table_n.equals(table_p):
        raise AssertionError(f"{name}: Arrow table mismatch")
    if table_n.schema.metadata != table_p.schema.metadata:
        raise AssertionError(f"{name}: schema metadata mismatch")
    if diag_n != diag_p:
        raise AssertionError(f"{name}: diagnostics ledger mismatch")
    if expect_passes:
        missing = sorted(p for p in expect_passes
                         if not passes_n.get(p))
        if missing:
            raise AssertionError(
                f"{name}: fused native pass(es) did not engage: "
                f"{missing} (counters: {passes_n or '{}'}) — the "
                f"multi-pass fallback shape is a failure here, not a "
                f"slowdown")
    return {"rows": table_n.num_rows, "native_s": round(dt_n, 3),
            "python_s": round(dt_p, 3), "passes": passes_n}


def run_quick(records: int | None, mb: float) -> int:
    failures = 0
    for name, data, kw, expect in _profiles(records, mb):
        try:
            stats = check_profile(name, data, kw, expect_passes=expect)
        except Exception as exc:
            failures += 1
            print(f"FAIL {name}: {exc}")
            continue
        print(f"ok   {name:<20} rows={stats['rows']:<8} "
              f"native={stats['native_s']}s python={stats['python_s']}s "
              f"passes={','.join(sorted(stats['passes'])) or '-'}")
    return failures


def run_sweep(records: int | None, mb: float) -> int:
    """Adds execution modes and a permissive-policy corrupt read."""
    failures = run_quick(records, mb)
    modes = [("pipelined", dict(pipeline_workers="2",
                                chunk_size_mb="0.5")),
             ("multihost", dict(hosts="2"))]
    for name, data, kw, _expect in _profiles(records or 400, mb):
        if name == "hierarchical":
            continue  # single-shard layouts: modes covered by tests
        for mode, extra in modes:
            # no expect_passes: multihost workers count in their own
            # processes, and the pipelined chunking changes pass shapes
            try:
                stats = check_profile(f"{name}/{mode}",
                                      data, dict(kw, **extra))
            except Exception as exc:
                failures += 1
                print(f"FAIL {name}/{mode}: {exc}")
                continue
            print(f"ok   {name + '/' + mode:<26} rows={stats['rows']}")
    # permissive policy: a corrupted record must null/ledger identically
    from cobrix_tpu.testing import generators as g

    data = bytearray(g.generate_exp1((records or 400), seed=3).tobytes())
    data[100:108] = b"\xff" * 8  # stomp numeric fields of record 0
    try:
        stats = check_profile(
            "exp1_permissive", bytes(data),
            dict(copybook_contents=g.EXP1_COPYBOOK,
                 record_error_policy="permissive"))
        print(f"ok   exp1_permissive           rows={stats['rows']}")
    except Exception as exc:
        failures += 1
        print(f"FAIL exp1_permissive: {exc}")
    return failures


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mb", type=float, default=1.5,
                    help="approx MB per profile (default 1.5)")
    ap.add_argument("--records", type=int, default=None,
                    help="tiny record-count mode (overrides --mb)")
    ap.add_argument("--sweep", action="store_true",
                    help="add pipelined/multihost modes + permissive fuzz")
    args = ap.parse_args()
    failures = (run_sweep(args.records, args.mb) if args.sweep
                else run_quick(args.records, args.mb))
    if failures:
        print(f"asmcheck: {failures} FAILURE(S)")
        return 1
    print("asmcheck: native and pure-Python assembly byte-identical")
    return 0


if __name__ == "__main__":
    sys.exit(main())
