"""The chunked pipeline executor: overlap IO, framing, decode, assembly.

The bench trajectory showed the raw columnar kernels running ~4x faster
than the end-to-end to-Arrow paths — the engine was assembly/IO-bound,
not decode-bound, because the stages ran serially. Here a scan is split
into chunks (engine/chunks.py) and executed as a producer/consumer
pipeline:

    reader thread:  chunk.read()  ──►  bounded queue  ──►  worker pool:
                                      (backpressure)       frame -> decode
                                                           -> Arrow table

Threads, not processes: the numpy/native kernels and Arrow builders
release the GIL, and a fork pool is known to hang intermittently in some
container environments (CHANGES.md). The bounded queue is the
backpressure valve — at most `max_inflight` chunks of raw bytes are held
at once, so a fast reader cannot balloon RSS ahead of slow decoders.

Determinism: results are collected into a slot per chunk index and
returned in chunk order regardless of completion order, so per-chunk
RecordBatches concatenate exactly like the sequential scan's, and
per-chunk error ledgers merge in offset order downstream
(ReadDiagnostics.merged).

Per-stage busy time (read/frame/decode/assemble) accumulates in a shared
`profiling.StageTimes`; the executor reports wall time, busy total, their
ratio (the overlap factor), and the peak queue depth so a pipeline win is
attributable instead of anecdotal.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from ..profiling import ReadMetrics, StageTimes, timed_stage
from ..reader.stream import RetryPolicy, open_stream
from .chunks import FixedChunk, plan_fixed_chunks


def _cap_omp_width(workers: int) -> None:
    """Split the machine's cores across concurrent pipeline threads: each
    worker's native kernels get cpu_count // workers OpenMP threads
    (min 1). Without the cap every concurrent chunk decode spawns an
    all-core OMP team and the teams thrash each other — measured locally
    that inversion alone made the pipeline slower than sequential."""
    import os

    from .. import native

    per = max(1, (os.cpu_count() or 1) // max(1, workers))
    native.set_thread_omp_width(per)


class PipelineExecutor:
    """Bounded-thread chunk pipeline with backpressure and ordered output.

    `run(tasks)` takes (read_fn, process_fn[, finalize_fn]) tuples:

    * `read_fn()` produces the chunk's payload on the reader thread
      (stage "read");
    * `process_fn(payload)` frames/decodes on the worker pool (timing its
      own stages through the shared StageTimes);
    * `finalize_fn(result)` — optional — runs on ONE dedicated stage
      thread (Arrow assembly). Assembly is deliberately not fanned out:
      its numpy/pyarrow glue is GIL-heavy and measurably ANTI-scales
      across threads, while the decode kernels (ctypes + OpenMP, GIL
      released) scale — so the shape that wins is a decode pool overlapped
      with a single assembler, not symmetric workers doing everything.

    Results return in task order regardless of completion order.
    """

    def __init__(self, workers: int, max_inflight: int = 0,
                 stage_times: Optional[StageTimes] = None):
        self.workers = max(1, workers)
        self.max_inflight = max_inflight if max_inflight > 0 \
            else self.workers + 2
        self.stage_times = stage_times if stage_times is not None \
            else StageTimes()
        self.report: dict = {}

    def run(self, tasks: Sequence[tuple]) -> List[object]:
        n = len(tasks)
        results: List[object] = [None] * n
        if n == 0:
            return results
        has_finalize = any(len(t) > 2 and t[2] is not None for t in tasks)
        t_start = time.perf_counter()
        q: "queue.Queue" = queue.Queue(maxsize=self.max_inflight)
        # decoded chunks waiting for the assembler; bounded so decode
        # cannot balloon RSS ahead of a slow assembly stage
        fq: "queue.Queue" = queue.Queue(maxsize=self.max_inflight)
        stop = threading.Event()
        errors: List[Tuple[int, BaseException]] = []
        err_lock = threading.Lock()
        peak_queue = [0]

        def fail(index: int, exc: BaseException) -> None:
            with err_lock:
                errors.append((index, exc))
            stop.set()

        def reader_loop() -> None:
            try:
                for i, task in enumerate(tasks):
                    if stop.is_set():
                        break
                    try:
                        with self.stage_times.timed("read"):
                            payload = task[0]()
                    except BaseException as exc:
                        fail(i, exc)
                        break
                    # blocks when max_inflight chunks are already queued
                    # or being processed — the backpressure bound
                    q.put((i, task, payload))
                    depth = q.qsize()
                    if depth > peak_queue[0]:
                        peak_queue[0] = depth
            finally:
                for _ in range(self.workers):
                    q.put(None)

        def worker_loop() -> None:
            _cap_omp_width(self.workers)
            while True:
                item = q.get()
                if item is None:
                    return
                i, task, payload = item
                if stop.is_set():
                    # drain so the reader can unblock; payloads may be
                    # OPEN resources (var-len chunks carry streams whose
                    # close normally happens in process_fn) — release
                    # them or a failed read leaks one fd per chunk
                    close = getattr(payload, "close", None)
                    if close is not None:
                        try:
                            close()
                        except Exception:
                            pass
                    continue
                try:
                    result = task[1](payload)
                    results[i] = result
                    if has_finalize:
                        finalize_fn = task[2] if len(task) > 2 else None
                        fq.put((i, finalize_fn, result))
                        depth = fq.qsize()
                        if depth > peak_queue[0]:
                            peak_queue[0] = depth
                except BaseException as exc:
                    fail(i, exc)

        def finalizer_loop() -> None:
            _cap_omp_width(self.workers)
            while True:
                item = fq.get()
                if item is None:
                    return
                i, finalize_fn, result = item
                if stop.is_set() or finalize_fn is None:
                    continue
                try:
                    finalize_fn(result)
                except BaseException as exc:
                    fail(i, exc)

        threads = [threading.Thread(target=reader_loop,
                                    name="cobrix-pipe-read", daemon=True)]
        threads += [threading.Thread(target=worker_loop,
                                     name=f"cobrix-pipe-{k}", daemon=True)
                    for k in range(self.workers)]
        finalizer = None
        if has_finalize:
            finalizer = threading.Thread(target=finalizer_loop,
                                         name="cobrix-pipe-assemble",
                                         daemon=True)
            finalizer.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if finalizer is not None:
            fq.put(None)
            finalizer.join()
        wall = time.perf_counter() - t_start
        busy = sum(self.stage_times.busy_s.values())
        self.report = {
            "workers": self.workers,
            "chunks": n,
            "max_inflight": self.max_inflight,
            "peak_queue": peak_queue[0],
            "wall_s": round(wall, 6),
            "busy_s": round(busy, 6),
            "overlap": round(busy / wall, 3) if wall > 0 else 0.0,
        }
        if errors:
            # deterministic-ish error choice: the failing chunk with the
            # lowest index among those observed before the stop. (A later
            # chunk may fail before an earlier one is reached — the
            # sequential scan would have surfaced the earlier failure
            # first; both surface A failure for the same corrupt input.)
            errors.sort(key=lambda e: e[0])
            raise errors[0][1]
        return results

    def attach(self, metrics: Optional[ReadMetrics]) -> None:
        """Publish the run report + stage busy times on the read metrics."""
        if metrics is None:
            return
        metrics.stage_busy = self.stage_times
        if metrics.pipeline is None:
            metrics.pipeline = self.report
        else:
            # multiple pipelined phases in one read: keep the widest shape
            prev = metrics.pipeline
            merged = dict(self.report)
            merged["chunks"] += prev.get("chunks", 0)
            merged["peak_queue"] = max(merged["peak_queue"],
                                       prev.get("peak_queue", 0))
            merged["wall_s"] = round(merged["wall_s"]
                                     + prev.get("wall_s", 0.0), 6)
            merged["busy_s"] = round(merged["busy_s"]
                                     + prev.get("busy_s", 0.0), 6)
            if merged["wall_s"] > 0:
                merged["overlap"] = round(
                    merged["busy_s"] / merged["wall_s"], 3)
            metrics.pipeline = merged


def _assemble(result, output_schema, stage_times: StageTimes):
    """Stage 4: per-chunk Arrow table, built on the worker and cached on
    the FileResult so CobolData.to_arrow concatenates without rebuilding."""
    with stage_times.timed("assemble"):
        table = result.to_arrow(output_schema)
    result._arrow_cache = table
    result._arrow_cache_schema = output_schema
    return result


def pipelined_fixed_scan(reader, files, params, backend: str,
                         output_schema, workers: int,
                         ignore_file_size: bool = False,
                         metrics: Optional[ReadMetrics] = None,
                         retry: Optional[RetryPolicy] = None,
                         on_retry=None,
                         assemble: bool = True) -> List["FileResult"]:
    """Fixed-length files through the chunk pipeline: record-aligned byte
    strides read concurrently, decoded by the batched kernels, and
    assembled into per-chunk Arrow tables — row-identical to the
    sequential `_read_fixed_len_chunked` path (same chunkability rules,
    same per-chunk `read_result` decode)."""
    chunk_bytes = max(1, int(params.pipeline_chunk_mb * 1024 * 1024))
    chunks = plan_fixed_chunks(reader, files, params, chunk_bytes,
                               ignore_file_size, retry, on_retry)
    ex = PipelineExecutor(workers, params.pipeline_max_inflight,
                          stage_times=StageTimes())

    def read_fn(c: FixedChunk):
        def read() -> object:
            with open_stream(c.file_path, start_offset=c.offset,
                             maximum_bytes=c.nbytes, retry=retry,
                             on_retry=on_retry) as stream:
                want = stream.size() - c.offset
                data = stream.next_view(want)
            if len(data) != want and not c.whole_file:
                raise IOError(
                    f"Short read from {c.file_path} at {c.offset}")
            return data
        return read

    def process_fn(c: FixedChunk):
        def process(data) -> object:
            return reader.read_result(
                data, backend=backend, file_id=c.file_order,
                first_record_id=c.first_record_id,
                input_file_name=c.file_path,
                ignore_file_size=ignore_file_size,
                stage_times=ex.stage_times)
        return process

    finalize = ((lambda result: _assemble(result, output_schema,
                                          ex.stage_times))
                if assemble else None)
    results = ex.run([(read_fn(c), process_fn(c), finalize)
                      for c in chunks])
    ex.attach(metrics)
    if metrics is not None:
        metrics.shards = max(metrics.shards, len(chunks))
    return results


def pipelined_var_len_scan(reader, shards, params, backend: str,
                           prefix: str, output_schema, workers: int,
                           metrics: Optional[ReadMetrics] = None,
                           retry: Optional[RetryPolicy] = None,
                           on_retry=None,
                           assemble: bool = True) -> List["FileResult"]:
    """Variable-length shards (sparse-index byte ranges) through the
    pipeline. The shard plan is EXACTLY the sequential indexed scan's
    (api._scan_var_len), so record framing, Record_Ids, and per-shard
    ledgers match; the pipeline only overlaps stage execution and adds
    the per-shard Arrow assembly stage."""
    ex = PipelineExecutor(workers, params.pipeline_max_inflight,
                          stage_times=StageTimes())

    def read_fn(shard):
        def read() -> object:
            max_bytes = (0 if shard.offset_to < 0
                         else shard.offset_to - shard.offset_from)
            # open only: variable-length framing consumes the stream
            # incrementally; the bulk next_view inside fast framing is
            # attributed to the "read" stage by the reader itself
            return open_stream(shard.file_path,
                               start_offset=shard.offset_from,
                               maximum_bytes=max_bytes, retry=retry,
                               on_retry=on_retry)
        return read

    def process_fn(shard):
        def process(stream) -> object:
            try:
                return reader.read_result_columnar(
                    stream, file_id=shard.file_order, backend=backend,
                    segment_id_prefix=prefix,
                    start_record_id=shard.record_index,
                    starting_file_offset=shard.offset_from,
                    stage_times=ex.stage_times)
            finally:
                stream.close()
        return process

    finalize = ((lambda result: _assemble(result, output_schema,
                                          ex.stage_times))
                if assemble else None)
    results = ex.run([(read_fn(s), process_fn(s), finalize)
                      for s in shards])
    ex.attach(metrics)
    return results
