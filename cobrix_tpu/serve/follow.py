"""Serve follow mode: subscribe a client to a LIVE source.

A ``follow=true`` request turns a scan into a subscription: the server
runs a `streaming.ContinuousIngestor` over the requested path and
streams every micro-batch to the client as source bytes stabilize —
growth, rotation, and truncation handled by the ingest layer, Arrow
batches on the same 'D'-frame wire as ordinary scans.

Recovery is the PR-9 resume protocol extended with the source
watermark: every resume token carries ``{plan, records, watermark}``
where `watermark` is the ingestor's per-source state
(`ContinuousIngestor.watermark()`). A client losing its replica
mid-follow reconnects elsewhere with the token; the new replica seeds
its ingestor from the watermark, skips the few records delivered after
the last token, and the subscriber's record stream continues exactly
once — no duplicates, no gaps, monotone Record_Ids.

The durable state lives with the CLIENT (its last token), not the
server: follow sessions are stateless on the serving side, which is
what makes replica failover trivial. Consumers that need crash-durable
server-side checkpoints run `ContinuousIngestor` with a
``checkpoint_dir`` in their own process instead.
"""
from __future__ import annotations

import hashlib
import json
import time
from typing import Callable, Optional

from .protocol import ServeError
from .session import NON_PLAN_OPTIONS, ScanRequest

# follow knobs a client may set inside the request's "follow" object;
# everything else in there is refused loudly
FOLLOW_OPTIONS = ("poll_interval_s", "idle_timeout_s", "max_batches",
                  "batch_max_mb", "tail_grace_s", "truncation_policy")

# how often an idle follow session proves the subscriber is still there
# (a keepalive token write; its failure is the disconnect signal)
KEEPALIVE_INTERVAL_S = 1.0


def follow_plan_fingerprint(files, read_kwargs: dict) -> str:
    """The follow-mode plan identity a resume token carries. Unlike a
    bounded scan's fingerprint, it does NOT pin file content versions —
    a follow target grows by design; the source WATERMARK (offsets +
    head CRCs) carries version identity instead. What must match across
    replicas is the request shape: the files spec and every row-shaping
    option."""
    opts = {k: v for k, v in read_kwargs.items()
            if k not in NON_PLAN_OPTIONS}
    payload = json.dumps(["follow", list(files), opts], sort_keys=True,
                         default=str)
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()[:24]


class FollowSession:
    """One admitted follow subscription: ingest -> ordered batches ->
    `write_table`, until the subscriber leaves (ClientGone), the row
    cap is reached, or the request's `idle_timeout_s` passes with no
    source progress. Interface-compatible with `ScanSession` where the
    handler needs it (plan_fp, resume_token, degraded, metrics,
    result_schema)."""

    def __init__(self, request: ScanRequest,
                 server_options: Optional[dict] = None,
                 controller=None,
                 on_progress: Optional[Callable] = None,
                 tracer=None,
                 force_progress: bool = False,
                 force_field_costs: bool = False,
                 on_plan: Optional[Callable] = None,
                 keepalive: Optional[Callable] = None):
        self.request = request
        self.server_options = server_options
        self.controller = controller
        self.on_progress = on_progress
        self.tracer = tracer
        self.force_progress = force_progress
        self.on_plan = on_plan
        # called during idle gaps with the current resume token; must
        # RAISE ClientGone when the subscriber is unreachable — it is
        # the only disconnect signal while no data flows
        self.keepalive = keepalive
        self.metrics = None
        self.result_schema = None
        self.plan_fp = ""
        self.degraded = False
        self.emitter = None
        self._ingestor = None
        self._rows_emitted = 0
        self._last_watermark: dict = {}

    # -- resume surface (handler-compatible with ScanSession) -----------

    def delivered_records(self) -> int:
        return self.request.resume_records + self._rows_emitted

    def resume_token(self) -> dict:
        token = {"plan": self.plan_fp,
                 "records": self.delivered_records()}
        if self._ingestor is not None:
            self._last_watermark = self._ingestor.watermark()
        if self._last_watermark:
            token["watermark"] = self._last_watermark
        return token

    # -- the subscription loop ------------------------------------------

    def _follow_kwargs(self) -> dict:
        raw = self.request.follow
        if raw is True:
            raw = {}
        bad = [k for k in raw if k not in FOLLOW_OPTIONS]
        if bad:
            raise ServeError(
                f"unknown follow option(s): {', '.join(sorted(bad))} "
                f"(accepted: {', '.join(FOLLOW_OPTIONS)})",
                code="protocol")
        out = {}
        for key in ("poll_interval_s", "idle_timeout_s", "tail_grace_s",
                    "batch_max_mb"):
            if raw.get(key) is not None:
                out[key] = float(raw[key])
        if raw.get("max_batches") is not None:
            out["max_batches"] = int(raw["max_batches"])
        if raw.get("truncation_policy") is not None:
            out["truncation_policy"] = str(raw["truncation_policy"])
        return out

    def run(self, write_table: Callable) -> dict:
        from ..streaming.ingest import ContinuousIngestor

        req = self.request
        kwargs = req.read_kwargs(self.server_options)
        # kwargs carries the session default pipeline_workers=-1 on
        # purpose: the ingest layer frames incrementally either way and
        # only engages the pipelined executor for multi-window catch-up
        # backlogs — exactly when a follow subscription wants it
        follow_kwargs = self._follow_kwargs()
        idle_timeout = follow_kwargs.pop("idle_timeout_s", None)
        max_batches = follow_kwargs.pop("max_batches", None)
        self.plan_fp = follow_plan_fingerprint(req.files, kwargs)
        if req.is_resume and req.resume_plan != self.plan_fp:
            raise ServeError(
                "follow resume token does not match this server's plan "
                "(files or row-shaping options changed); re-subscribe "
                "from a fresh request", code="resume_mismatch")
        ingestor = ContinuousIngestor(
            req.files if len(req.files) > 1 else req.files[0],
            checkpoint_dir=None, **follow_kwargs, **kwargs)
        self._ingestor = ingestor
        if req.resume_watermark:
            ingestor.seed_watermark(req.resume_watermark)
        # records the client received AFTER its last watermark token:
        # re-derived by the seeded ingestor, dropped here before the
        # wire — the subscriber sees each record exactly once
        skip = max(0, req.resume_records
                   - ingestor.delivered_records)
        if self.on_plan is not None:
            self.on_plan(self.plan_fp)
        max_records = req.max_records
        remaining = (None if max_records is None
                     else max(0, max_records - req.resume_records))
        t0 = time.monotonic()
        last_progress = t0
        tables_emitted = 0
        batches_seen = 0
        # short inner idle window: batches() returns after it so the
        # session can heartbeat the subscriber and enforce the
        # REQUEST-level idle timeout; the ingestor keeps its state
        # across calls
        ingestor.idle_timeout_s = KEEPALIVE_INTERVAL_S
        last_delivery = time.monotonic()
        try:
            while True:
                for batch in ingestor.batches():
                    batches_seen += 1
                    table = batch.to_arrow()
                    if skip > 0:
                        if table.num_rows <= skip:
                            skip -= table.num_rows
                            table = None
                        else:
                            table = table.slice(skip)
                            skip = 0
                    if table is not None and remaining is not None:
                        if table.num_rows > remaining:
                            table = table.slice(0, remaining)
                    if table is not None and table.num_rows:
                        write_table(table)
                        self._rows_emitted += table.num_rows
                        tables_emitted += 1
                        last_delivery = time.monotonic()
                        if remaining is not None:
                            remaining -= table.num_rows
                    self._emit_progress(ingestor, t0)
                    if remaining is not None and remaining <= 0:
                        raise _FollowDone()
                    if max_batches is not None \
                            and batches_seen >= max_batches:
                        raise _FollowDone()
                # idle gap: prove the subscriber is still there (the
                # keepalive raises ClientGone when it is not) and
                # enforce the request-level idle timeout
                if self.keepalive is not None:
                    self.keepalive()
                self._emit_progress(ingestor, t0)
                if idle_timeout is not None and \
                        time.monotonic() - last_delivery >= idle_timeout:
                    raise _FollowDone()
        except _FollowDone:
            pass
        finally:
            ingestor.close()
        from ..reader.arrow_out import arrow_schema

        self.result_schema = arrow_schema(ingestor.schema.schema)
        summary = {
            "rows": self._rows_emitted,
            "tables": tables_emitted,
            "records_total": self.delivered_records(),
            "scan_s": round(time.monotonic() - t0, 6),
            "request_id": req.request_id,
            "trace_id": req.trace_id,
            "diagnostics": None,
            "follow": True,
            "lag_bytes": ingestor.lag_bytes(),
            "resume_token": self.resume_token(),
        }
        if req.is_resume:
            summary["resume_of"] = req.resume_of or req.request_id
            summary["rows_skipped"] = req.resume_records
        return summary

    def _emit_progress(self, ingestor, t0: float) -> None:
        if self.on_progress is None:
            return
        from ..obs.progress import ScanProgress

        self.on_progress(ScanProgress(
            records_done=self._rows_emitted,
            chunks_done=ingestor._delivered_batches,
            elapsed_s=time.monotonic() - t0,
            lag_bytes=ingestor.lag_bytes()))


class _FollowDone(Exception):
    """Internal: the subscription reached its requested bound."""
