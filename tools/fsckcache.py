"""Offline verifier for the persistent cache planes (fsck for caches).

Walks a `cache_dir` (the directory `read_cobol(..., cache_dir=...)` and
the serving tier share) and verifies every durable artifact the way the
read path would — without running a scan:

* **blocks**  — each `<start>-<end>.blk` must carry the integrity
  header (magic + crc32) and a payload matching both its checksum and
  its aligned-range key;
* **index**   — each sparse-index payload must be decodable JSON whose
  embedded crc matches its canonical serialization;
* **orphans** — stale `.tmp-*` files from writers that died between
  mkstemp and rename;
* **quarantine** — previously-detected corrupt entries held for
  inspection.

Modes:

    python tools/fsckcache.py /var/cache/cobrix          # report only
    python tools/fsckcache.py /var/cache/cobrix --repair # quarantine
                                                         # corrupt entries,
                                                         # sweep orphans
    python tools/fsckcache.py --smoke                    # self-test: build
                                                         # a cache, corrupt
                                                         # it, verify
                                                         # detection (no
                                                         # network; tier-1)

Exit code: 0 = every entry verified (or was repaired), 1 = corruption
found without --repair (or the smoke test failed). A clean cache prints
one summary line per plane.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _iter_files(root: str, suffix: str):
    for dirpath, _dirs, files in os.walk(root):
        for name in sorted(files):
            if name.endswith(suffix):
                yield os.path.join(dirpath, name)


def check_blocks(cache_dir: str, repair: bool) -> dict:
    from cobrix_tpu.io.integrity import quarantine, unframe_block

    root = os.path.join(cache_dir, "blocks")
    stats = {"ok": 0, "corrupt": 0, "unparseable_name": 0}
    bad = []
    for path in _iter_files(root, ".blk"):
        name = os.path.basename(path)
        try:
            start, end = (int(x) for x in name[:-4].split("-"))
        except ValueError:
            stats["unparseable_name"] += 1
            bad.append((path, "unparseable range name"))
            continue
        data = open(path, "rb").read()
        if unframe_block(data, end - start) is None:
            stats["corrupt"] += 1
            bad.append((path, f"{len(data)}B for range [{start},{end})"))
        else:
            stats["ok"] += 1
    if repair:
        for path, _why in bad:
            quarantine(path, os.path.join(cache_dir, "quarantine"))
        stats["repaired"] = len(bad)
    stats["bad_entries"] = [p for p, _ in bad]
    return stats


def check_index(cache_dir: str, repair: bool) -> dict:
    from cobrix_tpu.io.integrity import quarantine, verify_json_payload

    root = os.path.join(cache_dir, "index")
    stats = {"ok": 0, "corrupt": 0, "stale_format": 0}
    bad = []
    for path in _iter_files(root, ".json"):
        try:
            payload = json.loads(open(path, encoding="utf-8").read())
        except ValueError:
            stats["corrupt"] += 1
            bad.append((path, "undecodable JSON"))
            continue
        if not isinstance(payload, dict) or "crc" not in payload:
            # pre-integrity format: never served (format bump), just old
            stats["stale_format"] += 1
            continue
        if verify_json_payload(payload):
            stats["ok"] += 1
        else:
            stats["corrupt"] += 1
            bad.append((path, "checksum mismatch"))
    if repair:
        for path, _why in bad:
            quarantine(path, os.path.join(cache_dir, "quarantine"))
        stats["repaired"] = len(bad)
    stats["bad_entries"] = [p for p, _ in bad]
    return stats


def check_compress(cache_dir: str, repair: bool) -> dict:
    """Verify the seekable inflate-index plane (io.compress_index):
    each entry must be CRC-clean AND structurally sane — checkpoints
    sorted, in-range, and restartable (compressed offsets within the
    recorded member size). A bad entry only costs a re-inflation on the
    next scan, but silent drift here would quietly serve stale
    decompressed sizes to planners, so it is checked like the others."""
    from cobrix_tpu.io.integrity import quarantine, verify_json_payload

    root = os.path.join(cache_dir, "compress")
    stats = {"ok": 0, "corrupt": 0, "stale_format": 0}
    bad = []
    for path in _iter_files(root, ".json"):
        try:
            payload = json.loads(open(path, encoding="utf-8").read())
        except ValueError:
            stats["corrupt"] += 1
            bad.append((path, "undecodable JSON"))
            continue
        if not isinstance(payload, dict) or "crc" not in payload:
            stats["stale_format"] += 1
            continue
        if not verify_json_payload(payload):
            stats["corrupt"] += 1
            bad.append((path, "checksum mismatch"))
            continue
        defect = _inflate_entry_defect(payload)
        if defect:
            stats["corrupt"] += 1
            bad.append((path, defect))
        else:
            stats["ok"] += 1
    if repair:
        for path, _why in bad:
            quarantine(path, os.path.join(cache_dir, "quarantine"))
        stats["repaired"] = len(bad)
    stats["bad_entries"] = [p for p, _ in bad]
    return stats


def _inflate_entry_defect(payload: dict):
    """Structural defect in a CRC-clean inflate-index payload, or None."""
    try:
        total = int(payload["total"])
        comp_size = int(payload["comp_size"])
        cps = [(int(c), int(d)) for c, d in payload["checkpoints"]]
    except (KeyError, TypeError, ValueError):
        return "malformed fields"
    if total < 0 or comp_size < 0:
        return "negative sizes"
    last_d = -1
    for comp, dec in cps:
        if not (0 <= comp <= comp_size) or not (0 <= dec <= total):
            return f"checkpoint ({comp},{dec}) out of range"
        if dec <= last_d:
            return "checkpoints not strictly increasing"
        last_d = dec
    return None


def check_orphans(cache_dir: str, repair: bool) -> dict:
    from cobrix_tpu.io.integrity import sweep_cache_root

    stats = {"tmp_orphans": 0}
    for sub in ("blocks", "index", "compress"):
        root = os.path.join(cache_dir, sub)
        for path in _iter_files(root, ""):
            if os.path.basename(path).startswith(".tmp-"):
                stats["tmp_orphans"] += 1
    if repair:
        removed = {"tmp_orphans": 0, "truncated": 0}
        for sub in ("blocks", "index", "compress"):
            got = sweep_cache_root(os.path.join(cache_dir, sub))
            for k in removed:
                removed[k] += got[k]
        stats["swept"] = removed
    return stats


def check_checkpoints(checkpoint_dir: str, repair: bool) -> dict:
    """Verify the continuous-ingest checkpoint plane: every ``*.ckpt``
    slot must be a CRC-clean payload (streaming.checkpoint). A stream
    whose BOTH slots are corrupt restarts from record zero — exactly
    once still, but a full re-drive — so flagging one bad slot early is
    the whole point."""
    from cobrix_tpu.io.integrity import quarantine
    from cobrix_tpu.streaming.checkpoint import (checkpoint_files,
                                                 verify_checkpoint_file)

    stats = {"ok": 0, "corrupt": 0}
    bad = []
    for path in checkpoint_files(checkpoint_dir):
        defect = verify_checkpoint_file(path)
        if defect is None:
            stats["ok"] += 1
        else:
            stats["corrupt"] += 1
            bad.append((path, defect))
    if repair:
        for path, _why in bad:
            quarantine(path, os.path.join(checkpoint_dir, "quarantine"))
        stats["repaired"] = len(bad)
    stats["bad_entries"] = [p for p, _ in bad]
    return stats


def check_sink(dataset_dir: str, repair: bool,
               out=sys.stdout) -> bool:
    """Verify (and optionally repair) one transactional sink dataset
    (cobrix_tpu.sink): meta CRC, every manifest record, every committed
    data file against its manifest length+CRC, staging orphans, and
    finalized files no record references. ``--repair`` truncates the
    manifest at the first unverifiable record and quarantines every
    orphan — reader consistency is restored; a stream whose checkpoint
    committed past the truncation refuses to resume (loudly) and must
    be restarted explicitly."""
    from cobrix_tpu.sink import fsck_sink

    stats = fsck_sink(dataset_dir, repair=repair)
    print(f"sink   : meta {'ok' if stats['meta_ok'] else 'CORRUPT'}, "
          f"{stats['commits']} commit(s), {stats['data_ok']} file(s) "
          f"ok, {stats['data_corrupt']} corrupt, "
          f"{stats['data_missing']} missing", file=out)
    if stats["manifest_defect"]:
        print(f"  MANIFEST {stats['manifest_defect']}"
              + (f"  [truncated {stats['truncated_bytes']}B]"
                 if repair else ""), file=out)
    print(f"  orphans: {stats['staging_orphans']} staged, "
          f"{stats['data_orphans']} unreferenced"
          + (f"; quarantined {stats['quarantined']}" if repair else ""),
          file=out)
    print(f"  quarantine: {stats['quarantine_held']} held entr(ies)",
          file=out)
    if not repair:
        return bool(stats["clean"])
    # a repair only succeeds if the dataset actually verifies clean
    # afterwards (a corrupt _sink_meta.json, for one, is unrepairable)
    after = fsck_sink(dataset_dir, repair=False)
    if not after["clean"]:
        print("  REPAIR INCOMPLETE: dataset still unclean "
              f"({ {k: v for k, v in after.items() if v and k != 'clean'} })",
              file=out)
    return bool(after["clean"])


def check_quarantine(cache_dir: str) -> dict:
    root = os.path.join(cache_dir, "quarantine")
    try:
        names = os.listdir(root)
    except OSError:
        names = []
    return {"held": len(names)}


def fsck(cache_dir: str, repair: bool = False,
         out=sys.stdout, checkpoint_dir: str = "") -> bool:
    """Verify one cache root (and optionally a checkpoint dir — it
    also runs automatically when ``<cache_dir>/checkpoints`` exists);
    True when clean (or repaired)."""
    if not os.path.isdir(cache_dir):
        print(f"fsckcache: {cache_dir} is not a directory", file=out)
        return False
    blocks = check_blocks(cache_dir, repair)
    index = check_index(cache_dir, repair)
    compress = check_compress(cache_dir, repair)
    orphans = check_orphans(cache_dir, repair)
    quarantined = check_quarantine(cache_dir)
    ckpt_root = checkpoint_dir or os.path.join(cache_dir, "checkpoints")
    ckpts = (check_checkpoints(ckpt_root, repair)
             if os.path.isdir(ckpt_root)
             else {"ok": 0, "corrupt": 0, "bad_entries": []})
    print(f"blocks : {blocks['ok']} ok, {blocks['corrupt']} corrupt, "
          f"{blocks['unparseable_name']} unparseable", file=out)
    print(f"index  : {index['ok']} ok, {index['corrupt']} corrupt, "
          f"{index['stale_format']} stale-format", file=out)
    print(f"inflate: {compress['ok']} ok, {compress['corrupt']} corrupt, "
          f"{compress['stale_format']} stale-format", file=out)
    print(f"ckpts  : {ckpts['ok']} ok, {ckpts['corrupt']} corrupt",
          file=out)
    print(f"orphans: {orphans['tmp_orphans']} temp file(s)"
          + (f", swept {orphans['swept']}" if repair else ""), file=out)
    print(f"quarantine: {quarantined['held']} held entr(ies)", file=out)
    for path in (blocks["bad_entries"] + index["bad_entries"]
                 + compress["bad_entries"] + ckpts["bad_entries"]):
        print(f"  CORRUPT {path}"
              + ("  [quarantined]" if repair else ""), file=out)
    corrupt = (blocks["corrupt"] + blocks["unparseable_name"]
               + index["corrupt"] + compress["corrupt"]
               + ckpts["corrupt"])
    return corrupt == 0 or repair


def smoke() -> bool:
    """Self-test: build a real cache through a scan, corrupt entries of
    both planes, assert fsck finds exactly them, repair, assert clean.
    No network — a memory:// input via the test chaos registry."""
    import tempfile

    from cobrix_tpu import read_cobol
    from cobrix_tpu.testing.faults import (cache_write_faults,
                                           corrupt_cache_entry,
                                           register_chaos_backend)
    from cobrix_tpu.testing.generators import (EXP1_COPYBOOK,
                                               generate_exp1)

    ok = True

    def fail(msg):
        nonlocal ok
        ok = False
        print(f"  FAILED: {msg}")

    workdir = tempfile.mkdtemp(prefix="fsckcache-smoke-")
    cache_dir = os.path.join(workdir, "cache")
    data = generate_exp1(4096, seed=3).tobytes()
    scheme = "fsckcachesmoke"
    register_chaos_backend(scheme, data)
    opts = dict(copybook_contents=EXP1_COPYBOOK, cache_dir=cache_dir,
                io_block_mb="0.25", prefetch_blocks="0")
    base = read_cobol(f"{scheme}://input", **opts).to_arrow()

    if not fsck(cache_dir, out=open(os.devnull, "w")):
        fail("fresh cache did not verify clean")
    # corrupt one block entry; fsck must flag exactly the block plane
    corrupt_cache_entry(cache_dir, "block", "bitflip")
    blocks = check_blocks(cache_dir, repair=False)
    if blocks["corrupt"] != 1:
        fail(f"block corruption not detected: {blocks}")
    if fsck(cache_dir, out=open(os.devnull, "w")):
        fail("fsck reported a corrupt cache as clean")
    # ... and the READ path must self-heal: same table, counter bumped
    healed = read_cobol(f"{scheme}://input", **opts)
    if not healed.to_arrow().equals(base):
        fail("self-healed scan diverged from clean scan")
    if healed.metrics.as_dict()["io"].get("block_corrupt", 0) < 1:
        fail("self-heal did not count the corruption")
    # repair mode quarantines whatever is still bad
    corrupt_cache_entry(cache_dir, "block", "truncate")
    if not fsck(cache_dir, repair=True, out=open(os.devnull, "w")):
        fail("--repair did not leave the cache clean")
    if not fsck(cache_dir, out=open(os.devnull, "w")):
        fail("cache not clean after repair")
    # checkpoint plane: a committed ingest checkpoint verifies, a
    # corrupted slot is flagged and --repair quarantines it
    ckpt_dir = os.path.join(cache_dir, "checkpoints")
    from cobrix_tpu.streaming import CheckpointStore, StreamCheckpoint

    store = CheckpointStore(ckpt_dir)
    store.commit(StreamCheckpoint(delivered_records=7))
    ckpts = check_checkpoints(ckpt_dir, repair=False)
    if ckpts["ok"] != 1 or ckpts["corrupt"]:
        fail(f"fresh checkpoint did not verify: {ckpts}")
    corrupt_cache_entry(ckpt_dir, "checkpoint", "bitflip")
    if fsck(cache_dir, out=open(os.devnull, "w")):
        fail("corrupt checkpoint slot reported clean")
    if not fsck(cache_dir, repair=True, out=open(os.devnull, "w")):
        fail("--repair did not clear the checkpoint plane")
    # sink plane: build a dataset, kill a commit mid-protocol, assert
    # fsck detects the orphan + torn manifest, repair, assert clean and
    # the committed table unchanged
    from cobrix_tpu.sink import fsck_sink, read_dataset
    from cobrix_tpu.testing.faults import (SinkFaultPlan, SinkKilled,
                                           corrupt_sink_manifest)

    sink_dir = os.path.join(workdir, "sinkds")
    sink = read_cobol(f"{scheme}://input", **opts).to_dataset(sink_dir)
    committed = read_dataset(sink_dir)
    extra = committed.slice(0, 16)
    sink.commit_table(extra)  # commit 2: the record the tear destroys
    plan = SinkFaultPlan(workdir, action="raise").kill("pre_commit")
    with plan.installed():
        try:
            sink.commit_table(extra)  # commit 3 dies mid-protocol
            fail("sink kill plan did not fire")
        except SinkKilled:
            pass
    if check_sink(sink_dir, repair=False, out=open(os.devnull, "w")):
        fail("fsck missed the killed commit's orphaned data file")
    corrupt_sink_manifest(sink_dir, mode="torn", which=-1)
    if not check_sink(sink_dir, repair=True, out=open(os.devnull, "w")):
        fail("--repair did not clear the sink plane")
    if not check_sink(sink_dir, repair=False, out=open(os.devnull, "w")):
        fail("sink not clean after repair")
    if not read_dataset(sink_dir).equals(committed):
        fail("sink repair did not preserve the committed prefix")
    # ENOSPC on cache writes degrades, never fails the scan
    import shutil

    shutil.rmtree(cache_dir)
    with cache_write_faults("enospc") as faults:
        t = read_cobol(f"{scheme}://input", **opts).to_arrow()
    if not t.equals(base):
        fail("scan under ENOSPC cache writes diverged")
    if faults.write_attempts < 1:
        fail("ENOSPC injector saw no cache writes")
    leftover = [n for n in os.listdir(os.path.join(cache_dir, "blocks"))
                if n.startswith(".tmp-")] \
        if os.path.isdir(os.path.join(cache_dir, "blocks")) else []
    if leftover:
        fail(f"ENOSPC writes leaked temp files: {leftover}")
    shutil.rmtree(workdir, ignore_errors=True)
    print("fsckcache --smoke: "
          + ("detection + self-heal + repair + ENOSPC-degrade all hold"
             if ok else "FAILED"))
    return ok


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("cache_dir", nargs="?", default="",
                    help="cache root to verify")
    ap.add_argument("--repair", action="store_true",
                    help="quarantine corrupt entries and sweep orphans")
    ap.add_argument("--checkpoint-dir", default="",
                    help="continuous-ingest checkpoint dir to verify "
                         "(default: <cache_dir>/checkpoints when it "
                         "exists)")
    ap.add_argument("--sink", default="",
                    help="transactional sink dataset dir to verify "
                         "(cobrix_tpu.sink; may be given with or "
                         "without a cache_dir)")
    ap.add_argument("--smoke", action="store_true",
                    help="self-test on a throwaway cache (no network)")
    args = ap.parse_args()
    if args.smoke:
        return 0 if smoke() else 1
    if not args.cache_dir and not args.sink:
        ap.error("give a cache_dir, --sink, or --smoke")
    ok = True
    if args.cache_dir:
        ok = fsck(args.cache_dir, repair=args.repair,
                  checkpoint_dir=args.checkpoint_dir)
    if args.sink:
        ok = check_sink(args.sink, repair=args.repair) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
