"""A ``pyarrow.dataset``-shaped scan surface over mainframe files.

``dataset(path, copybook=...)`` returns a :class:`CobolDataset` that
duck-types the pyarrow Dataset API — ``schema``, ``scanner(columns=,
filter=)``, ``to_table``, ``to_batches``, ``head``, ``count_rows``,
``get_fragments`` — with one file per :class:`CobolFragment`. The
scanner accepts filters in any of three forms:

* a ``query.Expr`` (or its string grammar / wire JSON),
* a **pyarrow compute expression** (``pc.field("A") == "x"``) — lowered
  into the query AST through its canonical string form, so the same
  pushdown pipeline (plan pruning, pre-decode drops, late
  materialization) runs under engines that speak pyarrow expressions,
* nothing.

A pyarrow expression outside the supported subset falls back to a
post-hoc in-memory filter (correct, unpruned) rather than failing.

DuckDB / Polars worked example (README "Query pushdown")::

    dset = cobrix_tpu.query.dataset("companies.dat", copybook="c.cob",
                                    is_record_sequence=True)
    reader = dset.scanner(columns=["COMPANY_NAME"],
                          filter=pc.field("SEGMENT_ID") == "C"
                          ).to_reader()
    duckdb.sql("SELECT count(*) FROM reader")

This is the modern analogue of the reference's Spark DataSource L5/L6
layer (PAPER.md layer map): a standard query-engine surface whose
predicate/projection pruning the engine gets for free.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from .expr import Expr, normalize_filter, parse_filter


def _lower_filter(filter_):
    """(wire string | None, posthoc pyarrow expression | None)."""
    if filter_ is None:
        return None, None
    if isinstance(filter_, (Expr, str)):
        return normalize_filter(filter_), None
    # a pyarrow compute expression: its repr is a parseable spelling of
    # the supported subset; anything else falls back to post-hoc
    try:
        return normalize_filter(parse_filter(str(filter_))), None
    except (ValueError, TypeError):
        return None, filter_


class CobolScanner:
    """One materialization plan over a dataset (or one fragment)."""

    def __init__(self, ds: "CobolDataset", files: List[str],
                 columns: Optional[Sequence[str]],
                 filter_=None, batch_size: int = 131072):
        self.dataset = ds
        self.files = files
        self.columns = list(columns) if columns is not None else None
        if self.columns is not None:
            known = set(ds.schema.names)
            bad = [c for c in self.columns if c not in known]
            if bad:
                raise KeyError(
                    f"column(s) {bad} not in the dataset schema")
        self.batch_size = int(batch_size)
        self._wire, self._posthoc = _lower_filter(filter_)
        if self._wire is not None:
            from .expr import from_wire

            expr = from_wire(self._wire)
            if any(f in ds.generated_columns for f in expr.fields()):
                # predicates on generated columns (Record_Id, File_Id,
                # Seg_Id*) have no copybook field to push down against;
                # honor the documented contract and filter post-hoc
                self._wire = None
                self._posthoc = expr.to_pyarrow()

    @property
    def projected_schema(self):
        schema = self.dataset.schema
        if self.columns is None:
            return schema
        import pyarrow as pa

        return pa.schema([schema.field(c) for c in self.columns])

    def _read_table(self, files: List[str]):
        from ..api import read_cobol

        options = dict(self.dataset.options)
        if self.columns is not None:
            options["select"] = ",".join(
                c for c in self.columns
                if c not in self.dataset.generated_columns)
        if self._wire is not None:
            options["filter"] = self._wire
        data = read_cobol(files if len(files) > 1 else files[0],
                          copybook_contents=self.dataset.copybook_contents,
                          backend=self.dataset.backend, **options)
        table = data.to_arrow()
        if self._posthoc is not None:
            import pyarrow.dataset as pads

            table = pads.dataset(table).to_table(filter=self._posthoc)
        if self.columns is not None:
            table = table.select(self.columns)
        return table

    def to_table(self):
        return self._read_table(self.files)

    def to_batches(self):
        # ONE read over every file, like to_table: per-file reads would
        # restart File_Id/Record_Id bases at 0 for each file and the
        # two materialization paths would disagree on record identity
        table = self._read_table(self.files)
        yield from table.to_batches(max_chunksize=self.batch_size)

    def to_reader(self):
        import pyarrow as pa

        return pa.RecordBatchReader.from_batches(
            self.projected_schema, self.to_batches())

    def count_rows(self) -> int:
        return self.to_table().num_rows

    def head(self, num_rows: int):
        return self.to_table().slice(0, num_rows)


class CobolFragment:
    """One input file of the dataset (the pyarrow Fragment analogue);
    its scanner runs the same pushdown pipeline over just that file."""

    def __init__(self, ds: "CobolDataset", path: str):
        self.dataset = ds
        self.path = path

    @property
    def physical_schema(self):
        return self.dataset.schema

    def scanner(self, columns: Optional[Sequence[str]] = None,
                filter=None, batch_size: int = 131072,
                **_ignored) -> CobolScanner:
        return CobolScanner(self.dataset, [self.path], columns, filter,
                            batch_size)

    def to_table(self, columns: Optional[Sequence[str]] = None,
                 filter=None):
        return self.scanner(columns, filter).to_table()

    def count_rows(self, filter=None) -> int:
        return self.scanner(self.dataset._narrowest_columns(filter),
                            filter).count_rows()

    def __repr__(self) -> str:
        return f"<CobolFragment {self.path!r}>"


class CobolDataset:
    """Duck-typed ``pyarrow.dataset.Dataset`` over mainframe files."""

    def __init__(self, files: List[str], copybook_contents,
                 backend: str, options: dict, schema,
                 generated_columns: frozenset):
        self.files = files
        self.copybook_contents = copybook_contents
        self.backend = backend
        self.options = dict(options)
        self.schema = schema
        self.generated_columns = generated_columns

    def scanner(self, columns: Optional[Sequence[str]] = None,
                filter=None, batch_size: int = 131072,
                **_ignored) -> CobolScanner:
        """The pyarrow Scanner analogue. `columns` projects (and prunes
        the decode plan); `filter` pushes down (see module docs)."""
        return CobolScanner(self, self.files, columns, filter,
                            batch_size)

    def get_fragments(self, filter=None) -> List[CobolFragment]:
        return [CobolFragment(self, f) for f in self.files]

    def to_table(self, columns: Optional[Sequence[str]] = None,
                 filter=None):
        return self.scanner(columns, filter).to_table()

    def to_batches(self, columns: Optional[Sequence[str]] = None,
                   filter=None, batch_size: int = 131072):
        return self.scanner(columns, filter, batch_size).to_batches()

    def head(self, num_rows: int,
             columns: Optional[Sequence[str]] = None, filter=None):
        return self.scanner(columns, filter).head(num_rows)

    def _narrowest_columns(self, filter_) -> Optional[List[str]]:
        """A minimal projection for count_rows: the filter's own
        fields when there is a filter, else the first schema column —
        row counts never pay a full-width decode."""
        wire, posthoc = _lower_filter(filter_)
        if posthoc is not None:
            return None  # post-hoc filters need whatever they need
        if wire is not None:
            from .expr import from_wire

            names = [n for n in from_wire(wire).fields()
                     if n in set(self.schema.names)]
            if names:
                return names
        return [self.schema.names[0]] if self.schema.names else None

    def count_rows(self, filter=None) -> int:
        if filter is None:
            fast = self._aggregate_from_stats([("count", None)])
            if fast is not None:
                return fast["count"]
        return self.scanner(self._narrowest_columns(filter),
                            filter).count_rows()

    def aggregate(self, aggs: Sequence[str], filter=None) -> dict:
        """Evaluate simple aggregates over the dataset.

        `aggs` is a list of specs: ``"count"``, ``"min:FIELD"``,
        ``"max:FIELD"``, ``"sum:FIELD"``. Returns ``{spec: value}``
        (``None`` = SQL NULL over no values; nulls are ignored by
        min/max/sum, counted by count).

        With ``use_stats=true``, no filter, and a warm profile for
        EVERY input file, the answer comes from persisted statistics
        without decoding a byte (stats/aggregate.py) — and is exact by
        construction: anything short of proof (missing profile,
        NaN-tainted chunk, float sum, unknown field) silently falls
        back to the decode path below, never an approximate answer.
        """
        from ..stats.aggregate import parse_specs

        specs = parse_specs(aggs)
        if filter is None:
            fast = self._aggregate_from_stats(specs)
            if fast is not None:
                return fast
        return self._aggregate_by_decode(specs, filter)

    def _aggregate_from_stats(self, specs) -> Optional[dict]:
        """Stats-only answer, or None (then the caller decodes)."""
        from ..api import parse_options

        params, _opts = parse_options(dict(self.options))
        if not params.use_stats:
            return None
        from ..plan.cache import copybook_for_params
        from ..stats.aggregate import (aggregates_from_profiles,
                                       load_all_profiles)

        profiles = load_all_profiles(self.files, self.copybook_contents,
                                     params)
        if profiles is None:
            return None
        copybook = copybook_for_params(self.copybook_contents, params)
        return aggregates_from_profiles(profiles, copybook, specs)

    def _aggregate_by_decode(self, specs, filter_) -> dict:
        """The ground-truth path: decode, then pyarrow compute. The
        semantics here DEFINE what the stats path must reproduce."""
        import pyarrow.compute as pc

        from ..stats.collect import leaf_columns

        wanted = sorted({field for _, field in specs if field})
        known = set(self.schema.names)
        cols = (wanted if wanted and all(f in known for f in wanted)
                else None)  # nested leaves need the full-width decode
        table = self.to_table(columns=cols, filter=filter_)
        leaves = leaf_columns(table)
        out: dict = {}
        for fn, field in specs:
            if fn == "count":
                out["count"] = table.num_rows
                continue
            if field not in leaves:
                raise KeyError(
                    f"aggregate field {field!r} is not a primitive "
                    "column of the decoded output")
            _kind, col = leaves[field]
            if fn == "sum":
                out[f"sum:{field}"] = pc.sum(col).as_py()
            else:
                mm = pc.min_max(col).as_py()
                out[f"{fn}:{field}"] = mm[fn]
        return out

    def __repr__(self) -> str:
        return (f"<CobolDataset files={len(self.files)} "
                f"columns={len(self.schema.names)}>")


def dataset(path, copybook: Optional[str] = None,
            copybook_contents=None, backend: str = "numpy",
            **options) -> CobolDataset:
    """Open mainframe file(s) as a pyarrow-dataset-shaped object.

    `path`/`copybook`/`options` follow ``read_cobol``; the returned
    dataset's schema is derived up front from the copybook + options
    (no data is read until a scanner materializes)."""
    from ..api import (list_input_files, load_copybook_contents,
                       parse_options)
    from ..plan.cache import copybook_for_params
    from ..reader.arrow_out import arrow_schema
    from ..reader.schema import output_schema_for

    contents = load_copybook_contents(copybook, copybook_contents)
    files = list_input_files(path)
    if not files:
        raise FileNotFoundError(f"No input files found for path {path}")
    # schema derivation must see the caller's options, but select/filter
    # belong to each SCANNER, not the dataset identity
    probe_options = {k: v for k, v in options.items()
                     if k not in ("select", "filter")}
    params, _opts = parse_options(dict(probe_options))
    copybook_obj = copybook_for_params(contents, params)
    output_schema = output_schema_for(copybook_obj, params,
                                      params.needs_var_len_reader)
    schema = arrow_schema(output_schema.schema)
    generated = frozenset(
        n for n in schema.names
        if n in ("File_Id", "Record_Id", "Record_Byte_Length")
        or n.startswith("Seg_Id")
        or (params.input_file_name_column
            and n == params.input_file_name_column)
        or (params.corrupt_record_column
            and n == params.corrupt_record_column))
    return CobolDataset(files, contents, backend, probe_options, schema,
                        generated)
