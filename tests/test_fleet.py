"""Fleet observability plane (ISSUE 12): replica registry heartbeats,
Prometheus exposition parsing/validation/federation, cluster SLO
rollup with multi-window burn, autoscaling signals, the /fleet HTTP
surface, and the zero-overhead contract when fleet mode is off.

The federation edge-case matrix the issue names: stale-heartbeat
expiry, a replica dying mid-scrape (partial view, never a crash or a
hang), clock skew between replicas (the registry reuses PR 4's
common-clock-plus-offset idea via file mtime), and histogram
bucket-boundary mismatch raising a structured error.
"""
import importlib.util
import json
import os
import subprocess
import sys
import tempfile
import time

import pytest

from cobrix_tpu.fleet.federate import (
    FleetFederator,
    FleetMergeError,
    FleetView,
    ReplicaScrape,
    merge_expositions,
)
from cobrix_tpu.fleet.registry import (
    EXPIRE_FACTOR,
    LIVE_FACTOR,
    FingerprintHeat,
    Heartbeater,
    ReplicaRecord,
    ReplicaRegistry,
    ReplicaStatus,
)
from cobrix_tpu.fleet.signals import derive_signals
from cobrix_tpu.obs import promparse
from cobrix_tpu.obs.metrics import (
    FLEET_GAUGE_MERGE,
    MetricsRegistry,
    default_registry,
    prometheus_text,
    scan_metrics,
    serve_metrics,
    update_process_metrics,
)
from cobrix_tpu.obs.slo import SloTracker, parse_slo

from util import hard_timeout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

COPYBOOK = """
        01  R.
            05  KEY    PIC 9(7) COMP.
            05  NAME   PIC X(9).
"""


def make_records(n: int) -> bytes:
    return b"".join(
        i.to_bytes(4, "big") + f"ROW{i % 1000000:06d}".encode("ascii")
        for i in range(n))


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# promparse: round-trip parser + validator (the federation contract)
# ---------------------------------------------------------------------------

def test_own_exposition_is_validator_clean():
    """The exposition every replica serves must parse clean — lint at
    the source, because federation correctness depends on it."""
    m = scan_metrics()
    s = serve_metrics()
    m["scans"].inc()
    m["chunk_latency"].observe(0.02)
    m["cache"].labels(cache="copybook", result="hit").inc()
    s["admitted"].labels(tenant="fleet-test").inc()
    s["queue_wait"].observe(0.004)
    update_process_metrics(open_scans=0)
    text = prometheus_text()
    issues = promparse.validate_text(text)
    assert issues == [], issues
    families = promparse.parse_text(text)
    # round trip: render(parse(x)) parses back identical
    assert promparse.parse_text(promparse.render(families)) == families
    assert families["cobrix_scans_total"].kind == "counter"
    assert families["cobrix_chunk_latency_seconds"].kind == "histogram"


def test_validator_catches_structural_breaks():
    dup = "# TYPE x counter\n# TYPE x counter\nx 1\nx 1\n"
    issues = promparse.validate_text(dup)
    assert any("declared twice" in i for i in issues)
    assert any("duplicate series" in i for i in issues)

    noncum = ("# TYPE h histogram\n"
              'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\n'
              'h_bucket{le="+Inf"} 6\nh_sum 1\nh_count 6\n')
    assert any("not cumulative" in i
               for i in promparse.validate_text(noncum))

    inf_mismatch = ("# TYPE h histogram\n"
                    'h_bucket{le="+Inf"} 6\nh_sum 1\nh_count 7\n')
    assert any("disagrees with _count" in i
               for i in promparse.validate_text(inf_mismatch))

    bad_escape = '# TYPE c counter\nc{a="x\\q"} 1\n'
    assert any("escape" in i for i in promparse.validate_text(bad_escape))

    late_type = "c 1\n# TYPE c counter\nc{a=\"y\"} 1\n"
    assert any("after its samples" in i
               for i in promparse.validate_text(late_type))


def test_label_escaping_round_trips():
    fam = promparse.Family(name="c", kind="counter")
    nasty = 'quo"te\\back\nline'
    fam.samples.append(promparse.Sample("c", (("path", nasty),), 2.0))
    text = promparse.render({"c": fam})
    back = promparse.parse_text(text)
    assert back["c"].samples[0].labels == (("path", nasty),)
    assert promparse.validate_text(text) == []


def test_histogram_bucket_boundaries_pinned_per_registry():
    """The federation invariant at its source: one metric name = one
    bucket layout, asserted at registration."""
    r = MetricsRegistry()
    r.histogram("h", buckets=(1.0, 2.0))
    r.histogram("h", buckets=(2.0, 1.0))  # same set, different order: ok
    with pytest.raises(ValueError, match="federation"):
        r.histogram("h", buckets=(1.0, 3.0))


def test_every_registered_gauge_declares_fleet_merge():
    """Adding a gauge must come with a fleet merge policy (sum/max) —
    the declaration lives next to the metric definitions."""
    from cobrix_tpu.obs.metrics import (Gauge, process_metrics,
                                        stream_metrics)

    scan_metrics()
    serve_metrics()
    stream_metrics()
    process_metrics()
    undeclared = [
        name for name, metric in default_registry()._metrics.items()
        if isinstance(metric, Gauge) and name not in FLEET_GAUGE_MERGE]
    assert undeclared == [], (
        f"gauges without a FLEET_GAUGE_MERGE policy: {undeclared}")


# ---------------------------------------------------------------------------
# replica registry: heartbeats, liveness, corruption, clock skew
# ---------------------------------------------------------------------------

def _registry(tmp_path, interval_s=0.5):
    return ReplicaRegistry(str(tmp_path / "fleet"),
                           interval_s=interval_s)


def _record(rid="r0", interval_s=0.5, **kw):
    now = time.time()
    defaults = dict(replica_id=rid, pid=1, host="h",
                    http_address=["127.0.0.1", 1],
                    started_at=now - 10, heartbeat_at=now,
                    interval_s=interval_s)
    defaults.update(kw)
    return ReplicaRecord(**defaults)


def test_heartbeat_roundtrip_and_liveness_states(tmp_path):
    reg = _registry(tmp_path)
    reg.write(_record("alpha", active_scans=2,
                      heat=[{"key": "plan:x", "count": 4}]))
    statuses = reg.read()
    assert [s.record.replica_id for s in statuses] == ["alpha"]
    assert statuses[0].state == "live"
    assert statuses[0].record.active_scans == 2
    assert statuses[0].record.heat == [{"key": "plan:x", "count": 4}]
    path = reg.path_for("alpha")
    # stale: older than LIVE_FACTOR intervals but unexpired
    stale_age = 0.5 * (LIVE_FACTOR + 1)
    os.utime(path, (time.time() - stale_age, time.time() - stale_age))
    assert reg.read()[0].state == "stale"
    # expired: past EXPIRE_FACTOR intervals -> gone from the view
    old = time.time() - 0.5 * (EXPIRE_FACTOR + 2)
    os.utime(path, (old, old))
    assert reg.read() == []
    # unregister removes the file entirely
    reg.write(_record("alpha"))
    reg.unregister("alpha")
    assert reg.read() == []
    assert not os.path.exists(path)


def test_corrupt_heartbeat_is_quarantined_never_a_phantom(tmp_path):
    from cobrix_tpu.io.integrity import corruption_counter

    reg = _registry(tmp_path)
    reg.write(_record("good"))
    reg.write(_record("evil"))
    # valid JSON, wrong crc: flipped payload INSIDE a well-formed file
    path = reg.path_for("evil")
    doc = json.loads(open(path).read())
    doc["active_scans"] = 999
    open(path, "w").write(json.dumps(doc))
    before = corruption_counter().value(plane="fleet")
    statuses = reg.read()
    assert [s.record.replica_id for s in statuses] == ["good"]
    assert corruption_counter().value(plane="fleet") == before + 1
    assert not os.path.exists(path)  # quarantined away
    q_dir = os.path.join(reg.root, "quarantine")
    assert os.path.isdir(q_dir) and os.listdir(q_dir)
    # plain garbage is skipped too (second read: file already gone)
    open(reg.path_for("noise"), "w").write("\x00\x01 not json")
    assert [s.record.replica_id for s in reg.read()] == ["good"]


def test_clock_skew_surfaces_instead_of_lying(tmp_path):
    """A replica with a wall clock an hour ahead still heartbeats
    fresh mtimes: liveness is judged on the COMMON clock (file mtime,
    PR 4's shared-axis idea) and the writer's offset is surfaced as
    clock_skew_s — corrected uptime, not a phantom-stale replica."""
    reg = _registry(tmp_path)
    skew = 3600.0
    now = time.time()
    reg.write(_record("skewed", heartbeat_at=now + skew,
                      started_at=now + skew - 50))
    status = reg.read()[0]
    assert status.state == "live"          # mtime fresh -> live
    assert abs(status.clock_skew_s - skew) < 5.0
    doc = status.as_dict()
    # started_at corrected by the offset: ~50s of uptime, not -59min
    assert 40 < doc["uptime_s"] < 70


def test_heartbeater_thread_writes_and_unregisters(tmp_path):
    reg = _registry(tmp_path, interval_s=0.05)
    beats = []

    def record_fn():
        beats.append(1)
        return _record("beating", interval_s=0.05)

    hb = Heartbeater(reg, record_fn, interval_s=0.05).start()
    with hard_timeout(30, "heartbeater"):
        deadline = time.monotonic() + 10
        while len(beats) < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
    assert len(beats) >= 3
    assert reg.read()[0].record.replica_id == "beating"
    hb.stop(unregister=True)
    assert reg.read() == []


def test_fingerprint_heat_bounded():
    heat = FingerprintHeat(max_keys=16)
    for i in range(100):
        heat.bump([f"file:f{i}"])
    for _ in range(5):
        heat.bump(["plan:hot"])
    top = heat.top(3)
    assert top[0] == {"key": "plan:hot", "count": 5}
    assert len(heat._counts) <= 16


# ---------------------------------------------------------------------------
# federation merge: sums, declared gauge policies, bucket mismatch,
# partial views
# ---------------------------------------------------------------------------

def _exposition(scans: int, rss: float, age: float,
                buckets=((0.1, 1), (1.0, 2))) -> str:
    text = ("# TYPE cobrix_scans_total counter\n"
            f"cobrix_scans_total {scans}\n"
            "# TYPE cobrix_process_rss_bytes gauge\n"
            f"cobrix_process_rss_bytes {rss}\n"
            "# TYPE cobrix_stream_watermark_age_seconds gauge\n"
            f"cobrix_stream_watermark_age_seconds {age}\n"
            "# TYPE cobrix_slo_good_total counter\n"
            'cobrix_slo_good_total{slo="error_rate",tenant="t1"}'
            " 3\n"
            "# TYPE w histogram\n")
    cum = 0
    for le, n in buckets:
        cum += n
        text += f'w_bucket{{le="{le}"}} {cum}\n'
    text += (f'w_bucket{{le="+Inf"}} {cum}\n'
             f"w_sum 0.5\nw_count {cum}\n")
    return text


def test_merge_counters_sum_gauges_by_policy_histograms_bucketwise():
    per = {"a": promparse.parse_text(_exposition(5, 100, 7.0)),
           "b": promparse.parse_text(_exposition(9, 50, 3.0))}
    merged = merge_expositions(per)
    # counters: exact sum + per-replica labeled series
    scans = merged["cobrix_scans_total"]
    assert scans.value(()) == 14.0
    assert scans.value((("replica", "a"),)) == 5.0
    assert scans.value((("replica", "b"),)) == 9.0
    # declared gauge policies: rss sums, watermark age is a max
    assert merged["cobrix_process_rss_bytes"].value(()) == 150.0
    assert merged["cobrix_stream_watermark_age_seconds"] \
        .value(()) == 7.0
    # labeled counters keep their label sets
    assert merged["cobrix_slo_good_total"].value(
        (("slo", "error_rate"), ("tenant", "t1"))) == 6.0
    # histograms merge bucket-wise; +Inf == _count on the cluster series
    w = merged["w"]
    assert w.value((("le", "+Inf"),), suffix="_bucket") == 6.0
    assert w.value((), suffix="_count") == 6.0
    # and the merged exposition is itself scrapeable + lint-clean
    text = promparse.render(merged)
    assert promparse.validate_text(text) == []
    assert promparse.parse_text(text)["cobrix_scans_total"] \
        .value(()) == 14.0


def test_histogram_bucket_mismatch_raises_structured_error():
    per = {"a": promparse.parse_text(_exposition(1, 1, 1)),
           "b": promparse.parse_text(
               _exposition(1, 1, 1, buckets=((0.2, 1),)))}
    with pytest.raises(FleetMergeError) as exc:
        merge_expositions(per)
    assert exc.value.metric == "w"
    assert set(exc.value.replicas) == {"a", "b"}
    assert "bucket boundaries differ" in str(exc.value)


def _fed(tmp_path, responses: dict, interval_s=0.5):
    """A federator whose fetch is a dict lookup: replica_id ->
    (metrics_text, healthz, slo) or an Exception to raise."""
    reg = ReplicaRegistry(str(tmp_path / "fleet"), interval_s=interval_s)
    for rid in responses:
        reg.write(_record(rid, interval_s=interval_s))

    def fetch(status):
        r = responses[status.record.replica_id]
        if isinstance(r, Exception):
            raise r
        return r

    return FleetFederator(reg, timeout_s=1.0, cache_ttl_s=0.0,
                          fetcher=fetch)


def test_replica_death_mid_scrape_yields_partial_view(tmp_path):
    """A SIGKILLed replica whose heartbeat has not expired yet answers
    the scrape with a connection error: the fleet view stays PARTIAL
    and every product (exposition, slo, signals) still works."""
    fed = _fed(tmp_path, {
        "up": (_exposition(5, 1, 1), {"active_scans": 0}, {"slo": {}}),
        "dead": ConnectionRefusedError("connection refused"),
    })
    with hard_timeout(60, "partial scrape"):
        view = fed.view()
    assert len(view.replicas) == 2
    assert len(view.reachable()) == 1
    doc = view.replicas_doc()
    dead = [r for r in doc["replicas"]
            if r["replica_id"] == "dead"][0]
    assert dead["reachable"] is False
    assert "ConnectionRefusedError" in dead["scrape_error"]
    # the exposition only carries the reachable replica — no crash
    text = fed.cluster_exposition(view)
    assert 'replica="up"' in text and "dead" not in text
    rollup = fed.slo_rollup(view)
    assert rollup["replicas_reporting"] == 1
    sig = derive_signals(view, history=fed.history())
    assert sig["known_replicas"] == 2


def test_stale_heartbeat_expires_out_of_the_scrape_set(tmp_path):
    fed = _fed(tmp_path, {
        "fresh": (_exposition(1, 1, 1), {}, {"slo": {}}),
        "gone": (_exposition(1, 1, 1), {}, {"slo": {}}),
    })
    old = time.time() - 0.5 * (EXPIRE_FACTOR + 2)
    os.utime(fed.registry.path_for("gone"), (old, old))
    view = fed.view()
    assert [r.replica_id for r in view.replicas] == ["fresh"]


def test_slo_rollup_sums_per_replica_documents(tmp_path):
    slo_doc = lambda good, bad: {"slo": {  # noqa: E731
        "error_rate": {
            "kind": "error_rate", "threshold": 0.01,
            "objective": 0.99, "good": good, "bad": bad,
            "ratio": None, "burning": bad > 0,
            "burn_fast": {"window_s": 60.0, "good": good, "bad": bad},
            "burn_slow": {"window_s": 600.0, "good": good,
                          "bad": bad}}}}
    fed = _fed(tmp_path, {
        "a": (_exposition(1, 1, 1), {}, slo_doc(8, 2)),
        "b": (_exposition(1, 1, 1), {}, slo_doc(5, 0)),
    })
    rollup = fed.slo_rollup()
    er = rollup["slo"]["error_rate"]
    assert (er["good"], er["bad"]) == (13, 2)
    assert er["replicas"]["a"] == {"good": 8, "bad": 2,
                                   "burning": True}
    # fleet burn over the budget: 2/15 bad over a 1% budget
    assert er["burn_fast"]["burn"] == pytest.approx(
        (2 / 15) / 0.01, rel=1e-3)
    assert er["burning"] is True
    # per-tenant totals come from the scraped counter series (3 per
    # replica in the synthetic exposition)
    assert er["tenants"]["t1"]["good"] == 6


# ---------------------------------------------------------------------------
# multi-window SLO burn
# ---------------------------------------------------------------------------

def test_multiwindow_burn_fast_vs_slow():
    clock = [1000.0]
    tracker = SloTracker([parse_slo("error_rate=0.1")],
                         registry=MetricsRegistry(),
                         fast_window_s=60, slow_window_s=600,
                         clock=lambda: clock[0])

    class R:
        outcome = "ok"
        tenant = "t"
        resume_of = ""
        follow = False
        slo_breaches = []

    # old window: 20 good scans, 10 minutes ago
    for _ in range(20):
        tracker.observe(R())
    clock[0] += 590
    bad = R()
    bad.outcome = "error"
    for _ in range(5):
        tracker.observe(bad)
    status = tracker.status()["error_rate"]
    # fast window: only the 5 errors -> ratio 1.0, burn 10x
    assert status["burn_fast"]["bad"] == 5
    assert status["burn_fast"]["good"] == 0
    assert status["burn_fast"]["burn"] == pytest.approx(10.0)
    # slow window: 5 bad / 25 seen -> burn 2x
    assert status["burn_slow"]["good"] == 20
    assert status["burn_slow"]["burn"] == pytest.approx(2.0)
    # beyond the slow window everything ages out
    clock[0] += 700
    status = tracker.status()["error_rate"]
    assert status["burn_slow"]["ratio"] is None
    assert status["good"] == 20  # lifetime totals keep history


# ---------------------------------------------------------------------------
# autoscaling signals
# ---------------------------------------------------------------------------

def _view_with(queue_buckets, rejections=0, active=0, cap=2,
               queued=0, n=2, pressure="ok"):
    text = "# TYPE cobrix_serve_queue_wait_seconds histogram\n"
    cum = 0
    for le, c in queue_buckets:
        cum += c
        text += (f'cobrix_serve_queue_wait_seconds_bucket'
                 f'{{le="{le}"}} {cum}\n')
    text += (f'cobrix_serve_queue_wait_seconds_bucket{{le="+Inf"}} '
             f"{cum}\n"
             f"cobrix_serve_queue_wait_seconds_sum 1\n"
             f"cobrix_serve_queue_wait_seconds_count {cum}\n")
    if rejections:
        text += ("# TYPE cobrix_serve_scans_rejected_total counter\n"
                 f'cobrix_serve_scans_rejected_total'
                 f'{{reason="queue_full",tenant="t"}} {rejections}\n')
    view = FleetView(scraped_at=time.time())
    for i in range(n):
        rec = ReplicaRecord(replica_id=f"r{i}",
                            max_concurrent_scans=cap,
                            active_scans=active, queued_scans=queued,
                            pressure=pressure)
        view.replicas.append(ReplicaScrape(
            status=ReplicaStatus(record=rec, state="live", age_s=0.1,
                                 clock_skew_s=0.0),
            families=promparse.parse_text(text),
            healthz={}, slo={}))
    return view


def test_signals_scale_up_on_queue_wait():
    calm = _view_with([("0.01", 2)])
    hot = _view_with([("0.01", 2), ("2.5", 10)], active=2, queued=4)
    history = [(time.monotonic() - 10, calm), (time.monotonic(), hot)]
    sig = derive_signals(hot, history=history, queue_wait_target_s=0.5)
    assert sig["desired_replicas"] > sig["live_replicas"]
    assert any("queue_wait" in r for r in sig["reasons"])
    assert sig["inputs"]["queue_wait_p90_s"] == 2.5
    assert sig["actuates"] is False


def test_signals_scale_up_on_rejections_and_pressure():
    base = _view_with([("0.01", 2)])
    shed = _view_with([("0.01", 2)], rejections=3, pressure="shed")
    history = [(time.monotonic() - 10, base), (time.monotonic(), shed)]
    sig = derive_signals(shed, history=history)
    assert sig["desired_replicas"] > sig["live_replicas"]
    joined = " ".join(sig["reasons"])
    assert "rejection" in joined and "pressure" in joined


def test_signals_scale_down_only_when_fully_idle():
    idle = _view_with([("0.01", 2)], active=0, n=3)
    history = [(time.monotonic() - 10, idle), (time.monotonic(), idle)]
    sig = derive_signals(idle, history=history)
    assert sig["desired_replicas"] == 2  # one step down, min 1
    busy = _view_with([("0.01", 2)], active=1, n=3)
    sig2 = derive_signals(
        busy, history=[(time.monotonic() - 10, busy),
                       (time.monotonic(), busy)])
    assert sig2["desired_replicas"] == 3  # 50% utilization: steady


def test_signals_without_baseline_stay_conservative():
    """Lifetime counters must not read as present pressure on the
    very first scrape (no window baseline)."""
    view = _view_with([("2.5", 100)], rejections=50)
    sig = derive_signals(view, history=[(time.monotonic(), view)])
    assert sig["inputs"]["window_has_baseline"] is False
    assert sig["inputs"]["queue_wait_p90_s"] is None
    assert sig["inputs"]["rejections_in_window"] == 0
    assert sig["desired_replicas"] == sig["live_replicas"]


def test_signals_baseline_falls_back_beyond_window():
    """A consumer polling SLOWER than the fast window (a 60s+
    autoscaler loop) must still get rate signals: the delta baseline
    falls back to the newest prior snapshot outside the window, and
    the observed span is reported."""
    calm = _view_with([("0.01", 2)])
    hot = _view_with([("0.01", 2), ("2.5", 10)], active=2, queued=4)
    history = [(time.monotonic() - 300, calm), (time.monotonic(), hot)]
    sig = derive_signals(hot, history=history, queue_wait_target_s=0.5,
                         fast_window_s=60.0)
    assert sig["inputs"]["window_has_baseline"] is True
    assert sig["inputs"]["window_observed_s"] >= 299
    assert sig["inputs"]["queue_wait_p90_s"] == 2.5
    assert sig["desired_replicas"] > sig["live_replicas"]


def test_signals_cache_affinity_hints():
    view = _view_with([("0.01", 1)], n=2)
    view.replicas[0].status.record.heat = [
        {"key": "plan:abc", "count": 9}]
    view.replicas[1].status.record.heat = [
        {"key": "plan:abc", "count": 2},
        {"key": "file:/x", "count": 5}]
    sig = derive_signals(view, history=[])
    hints = {h["key"]: h for h in sig["cache_affinity"]}
    assert hints["plan:abc"]["replica"] == "r0"
    assert hints["plan:abc"]["fleet_count"] == 11
    assert hints["file:/x"]["replica"] == "r1"


# ---------------------------------------------------------------------------
# serve integration: the /fleet surface on a live (single-replica) server
# ---------------------------------------------------------------------------

def _http_json(addr, path):
    import urllib.request

    with urllib.request.urlopen(
            f"http://{addr[0]}:{addr[1]}{path}", timeout=10) as r:
        return json.loads(r.read())


def _http_text(addr, path):
    import urllib.request

    with urllib.request.urlopen(
            f"http://{addr[0]}:{addr[1]}{path}", timeout=10) as r:
        return r.read().decode()


def test_fleet_server_serves_cluster_view(tmp_path):
    from cobrix_tpu.serve import ScanServer, fetch_table

    data = tmp_path / "feed.dat"
    data.write_bytes(make_records(500))
    with hard_timeout(120, "fleet server"):
        srv = ScanServer(
            port=0, http_port=0,
            server_options={"cache_dir": str(tmp_path / "cache")},
            slos=["error_rate=0.01"],
            fleet=True, replica_id="solo",
            heartbeat_interval_s=0.2).start()
        try:
            table = fetch_table(srv.address, str(data), tenant="etl",
                                copybook_contents=COPYBOOK)
            assert table.num_rows == 500
            # wait for the post-scan heartbeat (heat + counters)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                doc = _http_json(srv.http_address, "/fleet/replicas")
                heat = doc["replicas"][0].get("heat") or []
                if heat:
                    break
                time.sleep(0.1)
            assert doc["live"] == 1
            rep = doc["replicas"][0]
            assert rep["replica_id"] == "solo"
            assert rep["state"] == "live" and rep["reachable"]
            keys = {h["key"] for h in rep["heat"]}
            assert f"file:{data}" in keys
            assert any(k.startswith("plan:") for k in keys)
            # federated exposition: validator-clean; the single
            # replica's cluster totals equal its own /metrics
            fleet_text = _http_text(srv.http_address, "/fleet/metrics")
            assert promparse.validate_text(fleet_text) == []
            fleet = promparse.parse_text(fleet_text)
            own = promparse.parse_text(
                _http_text(srv.http_address, "/metrics"))
            own_admitted = own["cobrix_serve_scans_admitted_total"] \
                .value((("tenant", "etl"),))
            assert own_admitted >= 1
            assert fleet["cobrix_serve_scans_admitted_total"].value(
                (("tenant", "etl"),)) == own_admitted
            assert fleet["cobrix_serve_scans_admitted_total"].value(
                (("replica", "solo"), ("tenant", "etl"))) \
                == own_admitted
            # /fleet/slo matches /debug/slo
            fleet_slo = _http_json(srv.http_address, "/fleet/slo")
            own_slo = _http_json(srv.http_address, "/debug/slo")
            assert fleet_slo["slo"]["error_rate"]["good"] \
                == own_slo["slo"]["error_rate"]["good"] >= 1
            # signals answer and never actuate
            sig = _http_json(srv.http_address, "/fleet/signals")
            assert sig["live_replicas"] == 1
            assert sig["actuates"] is False
            hb_path = srv._fleet["registry"].path_for("solo")
            assert os.path.exists(hb_path)
        finally:
            srv.stop()
        # clean stop unregisters the replica record
        assert not os.path.exists(hb_path)


def test_fleet_mode_requires_shared_cache_dir():
    from cobrix_tpu.serve import ScanServer

    with pytest.raises(ValueError, match="cache_dir"):
        ScanServer(port=0, enable_http=False, fleet=True)


def test_fleet_off_is_zero_overhead_counter_asserted(tmp_path):
    """Fleet mode off: the fleet package is never imported, no
    heartbeat file exists, HEARTBEAT_WRITES never moves — asserted in
    a FRESH interpreter so this test is immune to import order."""
    data = tmp_path / "feed.dat"
    data.write_bytes(make_records(50))
    cache = tmp_path / "cache"
    code = f"""
import sys
sys.path.insert(0, {REPO!r})
from cobrix_tpu.serve import ScanServer, fetch_table
srv = ScanServer(port=0, http_port=0,
                 server_options={{"cache_dir": {str(cache)!r}}}).start()
t = fetch_table(srv.address, {str(data)!r}, tenant="t",
                copybook_contents={COPYBOOK!r})
srv.stop()
assert t.num_rows == 50
import os
assert not any(m.startswith("cobrix_tpu.fleet") for m in sys.modules)
assert not os.path.exists({str(cache / 'fleet')!r})
import urllib.request, urllib.error
print("NOFLEET_OK")
"""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    with hard_timeout(180, "zero-overhead subprocess"):
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True,
                             timeout=170)
    assert out.returncode == 0 and "NOFLEET_OK" in out.stdout, (
        out.stdout, out.stderr[-2000:])


# ---------------------------------------------------------------------------
# tools: scanlog --merge, fleetcheck (the tier-1 smoke)
# ---------------------------------------------------------------------------

def test_scanlog_merge_follows_request_across_replicas(tmp_path):
    recs = {
        "r1.log": [
            {"request_id": "req-A", "trace_id": "abc123" * 5,
             "tenant": "etl", "outcome": "error", "ts": 100.0,
             "rows": 5, "e2e_s": 0.2},
            {"request_id": "req-B", "trace_id": "zzz" * 10,
             "tenant": "bi", "outcome": "ok", "ts": 102.0, "rows": 7},
        ],
        "r2.log": [
            {"request_id": "req-A2", "trace_id": "abc123" * 5,
             "tenant": "etl", "outcome": "ok", "ts": 101.0,
             "rows": 5, "resume_of": "req-A"},
        ],
    }
    for name, rows in recs.items():
        with open(tmp_path / name, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    # one --request-id query follows the failover tie across replicas
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scanlog.py"),
         "tail", "--merge", str(tmp_path / "r*.log"),
         "--request-id", "req-A"],
        capture_output=True, text=True, env=env, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "r1" in out.stdout and "r2" in out.stdout
    assert "resume_of=req-A" in out.stdout
    assert "req-B" not in out.stdout
    # merged summary: per-replica lines + the fleet-wide rollup
    out2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scanlog.py"),
         "summary", str(tmp_path / "r1.log"), str(tmp_path / "r2.log")],
        capture_output=True, text=True, env=env, timeout=60)
    assert out2.returncode == 0
    assert "replica r1" in out2.stdout and "replica r2" in out2.stdout
    assert "fleet-wide" in out2.stdout
    # single-log invocation unchanged (no replica column)
    out3 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scanlog.py"),
         "tail", str(tmp_path / "r1.log")],
        capture_output=True, text=True, env=env, timeout=60)
    assert out3.returncode == 0
    assert not out3.stdout.startswith("r1 ")


def test_fleetcheck_three_replica_smoke():
    """The ISSUE 12 acceptance harness: 3 subprocess replicas, one
    cache_dir — byte-exact federated counters, SLO rollup parity,
    signals responding to induced pressure, zero-overhead off path,
    and SIGKILL degrading the view within a heartbeat interval."""
    fleetcheck = _load_tool("fleetcheck")
    with hard_timeout(420, "fleetcheck"):
        assert fleetcheck.check_fleet(sweep=False)


# ---------------------------------------------------------------------------
# bench satellite: the bounded, cached device probe
# ---------------------------------------------------------------------------

def test_bench_probe_hard_deadline_cache_and_skip_reason(
        tmp_path, monkeypatch):
    monkeypatch.setenv("COBRIX_JAX_PROBE_CACHE",
                       str(tmp_path / "probe.json"))
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)
    import bench

    calls = []

    def timeout_run(cmd, timeout=None, **kw):
        calls.append(timeout)
        raise subprocess.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr(bench.subprocess, "run", timeout_run)
    platform, probe = bench._probe_jax(deadline_s=3)
    assert platform is None
    assert probe["skip_reason"] == "init_timeout"
    assert probe["deadline_s"] == 3 and probe["cached"] is False
    assert len(calls) == 1  # ONE bounded attempt, no escalation ladder
    # failure cached: the next run skips the wait, reason preserved
    platform2, probe2 = bench._probe_jax(deadline_s=3)
    assert len(calls) == 1
    assert probe2["skip_reason"] == "cached_failure"
    assert "init_timeout" in probe2["error"]
    # use_cache=False forces a fresh probe (the end-of-run retry)
    bench._probe_jax(deadline_s=3, use_cache=False)
    assert len(calls) == 2

    def ok_run(cmd, timeout=None, **kw):
        calls.append(timeout)

        class R:
            returncode = 0
            stdout = "tpu\n"
            stderr = ""

        return R()

    monkeypatch.setattr(bench.subprocess, "run", ok_run)
    platform3, probe3 = bench._probe_jax(deadline_s=3, use_cache=False)
    assert platform3 == "tpu" and probe3 is None
    # success cached across runs: detection without a subprocess
    monkeypatch.setattr(bench.subprocess, "run", timeout_run)
    n = len(calls)
    platform4, probe4 = bench._probe_jax(deadline_s=3)
    assert platform4 == "tpu" and probe4 is None and len(calls) == n
    doc = json.loads((tmp_path / "probe.json").read_text())
    assert list(doc.values())[0]["platform"] == "tpu"


def test_bench_probe_init_error_skip_reason(tmp_path, monkeypatch):
    monkeypatch.setenv("COBRIX_JAX_PROBE_CACHE",
                       str(tmp_path / "probe.json"))
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)
    import bench

    def fail_run(cmd, timeout=None, **kw):
        class R:
            returncode = 1
            stdout = ""
            stderr = "RuntimeError: no backend"

        return R()

    monkeypatch.setattr(bench.subprocess, "run", fail_run)
    platform, probe = bench._probe_jax(deadline_s=3, use_cache=False)
    assert platform is None
    assert probe["skip_reason"] == "init_error"
    assert "no backend" in probe["error"]
