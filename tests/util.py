"""Shared helpers for golden-parity tests."""
import contextlib
import glob
import os
import signal

import pytest

REFERENCE_DATA = "/root/reference/data"

# decorator for tests that touch the reference golden fixtures via
# explicit paths (tests calling read_copybook/read_binary/
# read_golden_lines skip automatically): on machines without the
# dataset the parity matrix SKIPS visibly instead of failing
needs_reference_data = pytest.mark.skipif(
    not os.path.isdir(REFERENCE_DATA),
    reason=f"reference golden fixtures absent ({REFERENCE_DATA}): "
           "parity against the upstream dataset cannot run here")


def require_reference_data():
    """Skip the calling test when the golden dataset is absent."""
    if not os.path.isdir(REFERENCE_DATA):
        pytest.skip(f"reference golden fixtures absent ({REFERENCE_DATA})")


@contextlib.contextmanager
def hard_timeout(seconds: float, label: str = "test"):
    """SIGALRM-backed hard per-test deadline: a hung test FAILS loud
    (TimeoutError with `label`) instead of wedging the whole CI run.
    Main-thread only (pytest runs tests there); plain pass-through where
    SIGALRM is unavailable. The distributed-execution tests wrap
    themselves in this so no fork/pipe bug can ever hang the suite —
    the in-code deadlines (shard_timeout_s / scan_deadline_s) are the
    first line of defense, this is the backstop."""
    if not hasattr(signal, "SIGALRM"):  # pragma: no cover - non-POSIX
        yield
        return

    def on_alarm(signum, frame):
        raise TimeoutError(
            f"{label} exceeded the hard {seconds:.0f}s test deadline "
            "(a distributed wait is unbounded somewhere)")

    previous = signal.signal(signal.SIGALRM, on_alarm)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, previous)


def read_copybook(name: str) -> str:
    require_reference_data()
    with open(os.path.join(REFERENCE_DATA, name), encoding="utf-8") as f:
        return f.read()


def read_binary(name: str) -> bytes:
    """Read a data file; reference data entries may be directories of .bin files."""
    require_reference_data()
    path = os.path.join(REFERENCE_DATA, name)
    if os.path.isdir(path):
        chunks = []
        for f in sorted(glob.glob(os.path.join(path, "*"))):
            base = os.path.basename(f)
            if base.startswith((".", "_")):
                continue
            with open(f, "rb") as fh:
                chunks.append(fh.read())
        return b"".join(chunks)
    with open(path, "rb") as f:
        return f.read()


def read_golden_lines(name: str):
    require_reference_data()
    with open(os.path.join(REFERENCE_DATA, name), encoding="iso-8859-1") as f:
        return f.read().splitlines()
