"""Lakehouse-sink smoke check: exactly-once commits under SIGKILL.

Drives cobrix_tpu.sink end to end the way the crash matrix demands:

  1. a LiveAppender grows a fixed-length file in torn, non-record-
     aligned increments while a consumer SUBPROCESS runs
     ``sink_cobol(tail_cobol(...), dataset_dir)`` with a durable
     checkpoint dir;
  2. a `SinkFaultPlan` kills the consumer (os._exit, SIGKILL-shaped)
     once in EACH commit window — pre_stage, post_stage, pre_commit,
     post_commit — across successive restarts (O_EXCL once-markers
     coordinate the sweep), plus one parent SIGKILL at a random
     instant;
  3. after the feed drains, `read_dataset` MUST be byte-identical to a
     one-shot `read_cobol(...).to_arrow()` of the final file: zero
     duplicates, zero gaps, across every kill window;
  4. the kills that landed after staging/finalize MUST have left
     quarantined orphans (the recovery evidence), and
     `fsck_sink` must report the dataset clean afterwards.

    python tools/sinkcheck.py             # quick (4-window sweep)
    python tools/sinkcheck.py --sweep     # + VRL + random-seq kill
                                          # fuzz (slow; tier-1 runs
                                          # quick)

Exit code 0 = every assertion held; 1 otherwise.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

COPYBOOK = """
        01  R.
            05  REGION PIC X(2).
            05  KEY    PIC 9(7) COMP.
            05  NAME   PIC X(9).
"""

RDW_COPYBOOK = """
        01  R.
            05  K  PIC X(6).
"""


def make_records(n: int, start: int = 0) -> bytes:
    return b"".join(
        ("EU" if i % 3 else "US").encode("cp037")
        + i.to_bytes(4, "big")
        + f"ROW{i % 1000000:06d}".encode("cp037")
        for i in range(start, start + n))


def make_rdw_records(n: int, start: int = 0) -> bytes:
    out = []
    for i in range(start, start + n):
        payload = f"K{i:05d}".encode("cp037")
        out.append(bytes([0, 0, len(payload) % 256,
                          len(payload) // 256]) + payload)
    return b"".join(out)


def consume(source: str, checkpoint_dir: str, dataset_dir: str,
            fault_dir: str, kill_points, options: dict) -> int:
    """The consumer subprocess body: recover + sink until the feed is
    idle, dying wherever the installed fault plan says. Exit 0 = feed
    idle (the caller decides whether it is truly drained)."""
    from cobrix_tpu.sink import sink_cobol
    from cobrix_tpu.streaming import tail_cobol
    from cobrix_tpu.testing.faults import SinkFaultPlan

    plan = SinkFaultPlan(fault_dir, action="exit")
    for point in kill_points:
        plan.kill(point)
    ing = tail_cobol(source, checkpoint_dir=checkpoint_dir,
                     poll_interval_s=0.05, idle_timeout_s=1.0,
                     finalize_on_idle=True, **options)
    with plan.installed():
        sink_cobol(ing, dataset_dir, target_file_mb=0.1)
    return 0


def _spawn_consumer(source, checkpoint_dir, dataset_dir, fault_dir,
                    kill_points, options) -> subprocess.Popen:
    import json as _json

    code = (
        "import sys, json; sys.path.insert(0, {root!r});\n"
        "import importlib.util as iu;\n"
        "spec = iu.spec_from_file_location('sinkcheck', {me!r});\n"
        "m = iu.module_from_spec(spec); spec.loader.exec_module(m);\n"
        "sys.exit(m.consume({src!r}, {ckpt!r}, {ds!r}, {faults!r}, "
        "json.loads({kp!r}), json.loads({opts!r})))"
    ).format(root=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        me=os.path.abspath(__file__), src=source, ckpt=checkpoint_dir,
        ds=dataset_dir, faults=fault_dir,
        kp=_json.dumps(list(kill_points)),
        opts=_json.dumps(options))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen([sys.executable, "-c", code], env=env)


def check_kill_matrix(tag: str, payload: bytes, options: dict,
                      parent_kill: bool = True) -> bool:
    """Grow a file tornly; kill/restart the sinking consumer through
    every commit window; assert dataset == one-shot read + recovery
    evidence + fsck-clean."""
    import pyarrow as pa  # noqa: F401 — fail fast if missing

    from cobrix_tpu import read_cobol
    from cobrix_tpu.sink import fsck_sink, read_dataset
    from cobrix_tpu.testing.faults import SINK_KILL_POINTS, LiveAppender

    work = tempfile.mkdtemp(prefix=f"sinkcheck-{tag}-")
    src = os.path.join(work, "feed.dat")
    ckpt = os.path.join(work, "ckpt")
    faults = os.path.join(work, "faults")
    dataset = os.path.join(work, "dataset")
    os.makedirs(faults)
    open(src, "wb").write(payload[:len(payload) // 4])
    appender = LiveAppender(src, payload[len(payload) // 4:],
                            slice_sizes=(7, 3, 11, 2, 29),
                            pause_s=0.003).start()
    cycles = 0
    deadline = time.monotonic() + 240
    while True:
        proc = _spawn_consumer(src, ckpt, dataset, faults,
                               SINK_KILL_POINTS, options)
        if parent_kill and cycles == 1:
            # one cycle dies by PARENT SIGKILL at a random instant on
            # top of the deterministic window sweep
            time.sleep(0.2 + 0.3 * (cycles % 2))
            proc.send_signal(signal.SIGKILL)
        rc = proc.wait()
        cycles += 1
        if rc == 0 and appender.done:
            break
        if time.monotonic() > deadline:
            print(f"FAIL [{tag}]: kill/restart loop did not drain "
                  f"within 240s (rc={rc})")
            return False
    fired = sorted(os.listdir(faults))
    if len(fired) < len(SINK_KILL_POINTS):
        print(f"FAIL [{tag}]: only {fired} kill window(s) fired")
        return False
    got = read_dataset(dataset)
    want = read_cobol(src, **options).to_arrow() \
        .replace_schema_metadata(None)
    if not got.equals(want):
        print(f"FAIL [{tag}]: dataset != one-shot read "
              f"({got.num_rows} vs {want.num_rows} rows over "
              f"{cycles} kill cycles)")
        return False
    # kills after staging/finalize leave quarantined orphans — the
    # recovery evidence the crash windows MUST produce
    held = os.listdir(os.path.join(dataset, "quarantine")) \
        if os.path.isdir(os.path.join(dataset, "quarantine")) else []
    if not held:
        print(f"FAIL [{tag}]: post-stage kills left no quarantined "
              "orphans (recovery did not run?)")
        return False
    report = fsck_sink(dataset)
    if not report["clean"]:
        print(f"FAIL [{tag}]: fsck reports the recovered dataset "
              f"unclean: {report}")
        return False
    print(f"ok [{tag}]: {got.num_rows} rows byte-identical across "
          f"{cycles} kill/restart cycles ({len(fired)} kill windows, "
          f"{len(held)} quarantined orphan(s), fsck clean)")
    return True


def check_kill_fuzz(tag: str, payload: bytes, options: dict,
                    kills: int = 6, seed: int = 0) -> bool:
    """Randomized kill fuzz (the --sweep tier): each cycle kills at a
    random window via a fresh fault dir, until the feed drains."""
    import random

    from cobrix_tpu import read_cobol
    from cobrix_tpu.sink import read_dataset
    from cobrix_tpu.testing.faults import SINK_KILL_POINTS, LiveAppender

    rng = random.Random(seed)
    work = tempfile.mkdtemp(prefix=f"sinkcheck-fuzz-{tag}-")
    src = os.path.join(work, "feed.dat")
    ckpt = os.path.join(work, "ckpt")
    dataset = os.path.join(work, "dataset")
    open(src, "wb").write(payload[:len(payload) // 3])
    appender = LiveAppender(src, payload[len(payload) // 3:],
                            slice_sizes=(13, 5, 31),
                            pause_s=0.002).start()
    cycles = 0
    deadline = time.monotonic() + 300
    while True:
        fault_dir = os.path.join(work, f"faults-{cycles}")
        os.makedirs(fault_dir, exist_ok=True)
        points = ([rng.choice(SINK_KILL_POINTS)]
                  if cycles < kills else [])
        proc = _spawn_consumer(src, ckpt, dataset, fault_dir, points,
                               options)
        rc = proc.wait()
        cycles += 1
        if rc == 0 and appender.done:
            break
        if time.monotonic() > deadline:
            print(f"FAIL [{tag}]: fuzz loop did not drain (rc={rc})")
            return False
    got = read_dataset(dataset)
    want = read_cobol(src, **options).to_arrow() \
        .replace_schema_metadata(None)
    if not got.equals(want):
        print(f"FAIL [{tag}]: fuzz dataset != one-shot "
              f"({got.num_rows} vs {want.num_rows} rows)")
        return False
    print(f"ok [{tag}]: fuzz {got.num_rows} rows byte-identical over "
          f"{cycles} cycles")
    return True


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep", action="store_true",
                    help="+ VRL and random kill fuzz (slow)")
    ap.add_argument("--records", type=int, default=4000)
    args = ap.parse_args()
    fixed_opts = {"copybook_contents": COPYBOOK}
    ok = check_kill_matrix("fixed", make_records(args.records),
                           fixed_opts)
    if args.sweep:
        vrl_opts = {"copybook_contents": RDW_COPYBOOK,
                    "is_record_sequence": "true",
                    "generate_record_id": "true"}
        ok = check_kill_matrix(
            "vrl", make_rdw_records(args.records), vrl_opts) and ok
        ok = check_kill_fuzz(
            "fixed", make_records(args.records * 2), fixed_opts) and ok
    print("SINKCHECK", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
