"""Arrow columnar output: vectorized kernel->Arrow path vs the Python-object
oracle (rows_to_table builds the same declared types from materialized rows,
so the two tables must be identical)."""
import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

from cobrix_tpu import read_cobol
from cobrix_tpu.reader.arrow_out import rows_to_table

from util import REFERENCE_DATA, needs_reference_data

# every case in this module reads the reference golden datasets
pytestmark = needs_reference_data


def ref(*parts):
    return os.path.join(REFERENCE_DATA, *parts)


def assert_fast_matches_oracle(data):
    fast = data.to_arrow()
    oracle = rows_to_table(data.to_rows(), data.schema)
    assert fast.schema == oracle.schema
    for name in fast.schema.names:
        assert fast.column(name).combine_chunks().equals(
            oracle.column(name).combine_chunks()), f"column {name}"
    assert fast.num_rows == len(data)


CASES = [
    # fixed-length type variety (strings + COMP-3 + binary + floats)
    dict(path=ref("test1_data"), copybook=ref("test1_copybook.cob"),
         schema_retention_policy="collapse_root"),
    # IEEE floats
    dict(path=ref("test6_data"), copybook=ref("test6_copybook.cob"),
         schema_retention_policy="collapse_root",
         floating_point_format="IEEE754"),
    # variable-length multisegment with Seg_Id generation + record ids
    dict(path=ref("test4_data"), copybook=ref("test4_copybook.cob"),
         encoding="ascii", is_record_sequence="true",
         segment_field="SEGMENT_ID", segment_id_level0="C",
         segment_id_level1="P", generate_record_id="true",
         schema_retention_policy="collapse_root", segment_id_prefix="A"),
    # multisegment with segment redefines (per-segment column planes)
    dict(path=ref("test5_data"), copybook=ref("test5_copybook.cob"),
         is_record_sequence="true", segment_field="SEGMENT_ID",
         schema_retention_policy="collapse_root",
         generate_record_id="true",
         **{"redefine-segment-id-map:1": "STATIC-DETAILS => C,D",
            "redefine_segment_id_map:2": "CONTACTS => P"}),
    # OCCURS DEPENDING ON -> ListArrays with real offsets
    dict(path=ref("test21_data"), copybook=ref("test21_copybook.cob"),
         variable_size_occurs="true"),
    # keep_original -> struct column per root
    dict(path=ref("test1_data"), copybook=ref("test1_copybook.cob")),
    # DISPLAY numerics golden (explicit decimals)
    dict(path=ref("test19_display_num"),
         copybook=ref("test19_display_num.cob"),
         schema_retention_policy="collapse_root"),
]


@pytest.mark.parametrize("case", range(len(CASES)))
def test_fast_arrow_matches_object_oracle(case):
    data = read_cobol(**CASES[case])
    assert_fast_matches_oracle(data)


def test_arrow_matches_host_backend_rows():
    """Fast Arrow table pylist == host-oracle rows (independent decode)."""
    kwargs = CASES[0]
    fast = read_cobol(**kwargs).to_arrow()
    host = read_cobol(backend="host", **kwargs)
    oracle = rows_to_table(host.to_rows(), host.schema)
    assert fast.equals(oracle)


def test_to_pandas_via_arrow():
    df = read_cobol(**CASES[0]).to_pandas()
    assert len(df) == 10


def test_trimming_policies_match():
    for policy in ("none", "left", "right", "both"):
        data = read_cobol(path=ref("test3_data"),
                          copybook=ref("test3_copybook.cob"),
                          schema_retention_policy="collapse_root",
                          string_trimming_policy=policy)
        assert_fast_matches_oracle(data)


def test_multisegment_interleave_order():
    """Rows of a multisegment table come back in record order."""
    data = read_cobol(path=ref("test5_data"),
                      copybook=ref("test5_copybook.cob"),
                      is_record_sequence="true", segment_field="SEGMENT_ID",
                      generate_record_id="true",
                      schema_retention_policy="collapse_root",
                      **{"redefine-segment-id-map:1": "STATIC-DETAILS => C,D",
                         "redefine_segment_id_map:2": "CONTACTS => P"})
    table = data.to_arrow()
    rids = table.column("Record_Id").to_pylist()
    assert rids == sorted(rids)
    assert rids == [row[1] for row in data.to_rows()]


def test_empty_read_produces_typed_empty_table():
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        p = os.path.join(tmp, "empty.bin")
        open(p, "wb").close()
        data = read_cobol(p, copybook=ref("test1_copybook.cob"))
        table = data.to_arrow()
        assert table.num_rows == 0
        assert table.schema.names == data.schema.field_names()


def test_input_file_col_with_seg_ids_matches_row_layout():
    """Reference parity: rows place the input file name AFTER Seg_Id levels
    when record ids are off (RecordExtractors.applyRecordPostProcessing)
    while the schema declares it BEFORE them (CobolSchema.scala:99-103);
    Spark binds Rows positionally, so the columnar table must reproduce the
    positional (misaligned-by-name) layout, not bind by name."""
    kwargs = dict(path=ref("test4_data"), copybook=ref("test4_copybook.cob"),
                  encoding="ascii", is_record_sequence="true",
                  segment_field="SEGMENT_ID", segment_id_level0="C",
                  segment_id_level1="P", segment_id_prefix="A",
                  with_input_file_name_col="F_NAME",
                  schema_retention_policy="collapse_root")
    data = read_cobol(**kwargs)
    fast = data.to_arrow()
    oracle = rows_to_table(data.to_rows(), data.schema)
    assert fast.equals(oracle)
    # positional parity: the F_NAME-named column actually carries Seg_Id0
    assert fast.column("F_NAME").to_pylist()[0].startswith("A_0_")


def test_to_rows_then_to_arrow_keeps_fast_path():
    """Row materialization must not reroute to_arrow onto the row fallback."""
    data = read_cobol(**CASES[0])
    data.to_rows()
    assert all(r.is_columnar for r in data._results)
    assert_fast_matches_oracle(data)
