"""Columnar Arrow assembly for hierarchical (IMS-style) reads.

The reference assembles hierarchical rows one root at a time — buffer a
root record plus its children, then walk the AST per record
(VarLenHierarchicalIterator.scala:43-162, extractHierarchicalRecord,
RecordExtractors.scala:211). The row path here mirrors that walk; THIS
module is its vectorized twin for Arrow output: the parent/child nesting
is a pure function of the per-record segment types, so child-to-parent
assignment, list offsets, and every leaf column come from array ops over
the one decode-once batch — no Python rows at any point.

Child-attachment rule (matches extract_children's forward scan): a child
record attaches to the nearest PRECEDING occurrence of any segment type
in its ancestor chain, and is kept only when that occurrence is of its
direct parent's type (the oracle's scan from the parent breaks when any
ancestor id reappears). That type-level formulation equals the oracle's
sid-level one except when a NON-ROOT parent type is reachable from
multiple segment ids (the oracle then scans PAST sibling occurrences with
a different id, double-attaching their children) — such shapes bail to
the row path. Record_Id parity: each assembled root row is stamped with
the id of the record that TRIGGERS its flush — the next root, or one past
the last record at end of stream.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from ..copybook.ast import Group, Primitive
from ..copybook.datatypes import SchemaRetentionPolicy
from .arrow_out import _pa


# columnar-vs-row-path assembly counters (observability: the bail rate is
# a BENCH metric — a silent fall-back to the Python row path would read
# as "columnar" while costing 5-10x)
ASSEMBLY_STATS = {"columnar": 0, "bail_multi_sid_parent": 0,
                  "bail_odo_cross_segment": 0, "bail_schema_shape": 0}
_STATS_LOCK = threading.Lock()  # bridge handler threads assemble concurrently


def _count(key: str) -> None:
    with _STATS_LOCK:
        ASSEMBLY_STATS[key] += 1


def assembly_stats(reset: bool = False) -> Dict[str, int]:
    """Snapshot (optionally reset) the columnar/bail counters."""
    with _STATS_LOCK:
        out = dict(ASSEMBLY_STATS)
        if reset:
            for k in ASSEMBLY_STATS:
                ASSEMBLY_STATS[k] = 0
    return out


def _depending_crosses_segment(copybook) -> bool:
    """True when an OCCURS DEPENDING ON array inside a segment redefine
    names a dependee that is not declared inside that SAME redefine.

    The row oracle (extract_hierarchical_record, mirroring reference
    RecordExtractors.scala:211-385) registers dependees while walking the
    ROOT record's full AST — prefix fields and every overlay are decoded
    from the root's bytes — and a child record's subtree walk only
    re-registers dependees declared inside the child's own group. So for
    an in-redefine array, a dependee outside that redefine resolves to the
    ROOT record's value, while the columnar build would re-read the
    current record's own bytes at the dependee's offset — bail. Arrays in
    the shared area only materialize at root positions, where both paths
    read the root record's own bytes — safe for any dependee placement.
    A dependee name declared in multiple regions is ambiguous — bail."""
    # keys upper-cased: the oracle binds DEPENDING ON case-insensitively
    # (mark_dependee_fields matches on .upper(), pipeline.py)
    regions: Dict[str, set] = {}

    def collect(g: Group, region: Optional[str]) -> None:
        for st in g.children:
            r = (st.name if isinstance(st, Group) and st.is_segment_redefine
                 else region)
            regions.setdefault(st.name.upper(), set()).add(r)
            if isinstance(st, Group):
                collect(st, r)

    for root in copybook.ast.children:
        if isinstance(root, Group):
            collect(root, None)

    def crosses(g: Group, region: Optional[str]) -> bool:
        for st in g.children:
            r = (st.name if isinstance(st, Group) and st.is_segment_redefine
                 else region)
            if st.is_array and st.depending_on is not None and r is not None:
                if regions.get(st.depending_on.upper()) != {r}:
                    return True
            if isinstance(st, Group) and crosses(st, r):
                return True
        return False

    return any(crosses(root, None) for root in copybook.ast.children
               if isinstance(root, Group))


def hierarchical_table(batch, segment_names,
                       copybook, output_schema,
                       sid_map: Dict[str, Group],
                       parent_child_map: Dict[str, list],
                       root_names: set,
                       file_id: int, start_record_id: int,
                       input_file_name: str = ""):
    """pyarrow Table for a hierarchical read, straight from a decode-once
    `DecodedBatch` over all framed records. `segment_names`: per-record
    redefine group names — either a plain sequence ("" / None for
    unmapped ids) or the dictionary-coded pair (uniq_names, codes
    ndarray) straight from SegmentIds. Returns None when the shape needs
    the row path."""
    from .arrow_out import ArrowBatchBuilder, arrow_schema

    pa = _pa()
    n = batch.n_records

    # non-root parent types fed by multiple segment ids diverge from the
    # oracle's sid-level break rule (see module docstring)
    sids_per_name: Dict[str, int] = {}
    for _sid, g in sid_map.items():
        sids_per_name[g.name] = sids_per_name.get(g.name, 0) + 1
    for name, count in sids_per_name.items():
        if count > 1 and name not in root_names and name in parent_child_map:
            _count("bail_multi_sid_parent")
            return None

    # DEPENDING ON arrays whose dependee lives in a different visibility
    # region (shared area vs a segment redefine overlay): bail to the row
    # path, which owns the oracle's cross-record dependee semantics
    if _depending_crosses_segment(copybook):
        _count("bail_odo_cross_segment")
        return None

    # integer-coded segment names: every membership test below runs on an
    # int32 code vector (object-dtype string compares/np.isin dominated
    # the assembly at scale). Callers pass the dictionary-coded form
    # (uniq_names, codes) straight from SegmentIds; a plain sequence is
    # coded here for direct/test use.
    if (isinstance(segment_names, tuple) and len(segment_names) == 2
            and isinstance(segment_names[1], np.ndarray)):
        uniq_names, codes = segment_names
        uniq_names = ["" if not s else s for s in uniq_names]
    else:
        uniq_names, seen = [], {}
        codes = np.empty(len(segment_names), dtype=np.int32)
        for i, s in enumerate(segment_names):
            s = s or ""
            j = seen.get(s)
            if j is None:
                j = seen[s] = len(uniq_names)
                uniq_names.append(s)
            codes[i] = j
    codes = np.asarray(codes, dtype=np.int32)
    name_codes: Dict[str, list] = {}
    for j, nm in enumerate(uniq_names):
        name_codes.setdefault(nm, []).append(j)

    def mask_of(names_iter) -> np.ndarray:
        ids = [j for nm in names_iter for j in name_codes.get(nm, ())]
        if not ids:
            return np.zeros(len(codes), dtype=bool)
        # id lists are tiny (distinct sids per name): OR of equality
        # compares beats np.isin's sort machinery
        mask = codes == ids[0]
        for j in ids[1:]:
            mask |= codes == j
        return mask

    parent_of = {}
    for parent, children in parent_child_map.items():
        for ch in children:
            parent_of[ch.name] = parent

    def ancestors(name: str) -> List[str]:
        out = []
        cur = parent_of.get(name)
        while cur is not None:
            out.append(cur)
            cur = parent_of.get(cur)
        return out

    positions_of = {name: np.nonzero(mask_of([name]))[0]
                    for name in {g.name for g in sid_map.values()}}
    root_pos_list = [positions_of.get(name, np.zeros(0, dtype=np.int64))
                     for name in root_names]
    roots = (np.sort(np.concatenate(root_pos_list)) if root_pos_list
             else np.zeros(0, dtype=np.int64))
    if roots.size == 0:
        return arrow_schema(output_schema.schema).empty_table()

    # per-redefine visibility masks: leaf columns of a segment build only
    # their own rows (hidden rows skip truncation fixups and string work;
    # their values are garbage by design and are never gathered)
    seg_masks = {g.name.upper(): mask_of([g.name])
                 for g in sid_map.values()}
    builder = ArrowBatchBuilder(batch, active=None,
                                redefine_masks=seg_masks)
    full_cache: Dict[int, object] = {}

    def full_array(st):
        """Full-length array for a non-redefine statement, cached (a child
        type under two parents shares one build)."""
        arr = full_cache.get(id(st))
        if arr is None:
            arr = builder._statement_array(st, ())
            full_cache[id(st)] = arr
        return arr

    # child segments in the SCHEMA's order: global segment-redefine
    # declaration order filtered by parent (reader/schema.py _parse_group)
    all_redefines = copybook.get_all_segment_redefines()

    def child_segments_of(group: Group) -> List[Group]:
        return [seg for seg in all_redefines
                if seg.parent_segment is not None
                and seg.parent_segment.name.upper() == group.name.upper()]

    def assign_children(child: Group, parent_positions: np.ndarray):
        """(kept child positions in order, int32 list offsets aligned to
        parent_positions)."""
        ch_pos = positions_of.get(child.name, np.zeros(0, dtype=np.int64))
        anc_names = set(ancestors(child.name))
        anc_pos = np.nonzero(mask_of(anc_names))[0]
        if ch_pos.size and anc_pos.size:
            idx = np.searchsorted(anc_pos, ch_pos, side="left") - 1
            has_anc = idx >= 0
            owner = np.where(has_anc, anc_pos[np.maximum(idx, 0)], -1)
            # keep only children whose nearest ancestor occurrence is an
            # occurrence of the DIRECT parent
            is_parent_row = np.zeros(len(codes) + 1, dtype=bool)
            is_parent_row[parent_positions] = True
            keep = has_anc & is_parent_row[owner]
            ch_kept = ch_pos[keep]
            owner = owner[keep]
        else:
            ch_kept = np.zeros(0, dtype=np.int64)
            owner = ch_kept
        # children arrive in position order, owners non-decreasing
        starts = np.searchsorted(owner, parent_positions, side="left")
        offsets = np.empty(len(parent_positions) + 1, dtype=np.int32)
        offsets[:-1] = starts
        offsets[-1] = len(owner)
        return ch_kept, offsets

    def expand_offsets(offsets_own: np.ndarray, owned: np.ndarray
                       ) -> np.ndarray:
        """Re-align list offsets computed over the owned subset to the
        full positions vector (non-owned rows become empty lists)."""
        m = len(owned)
        ranks = np.cumsum(owned) - 1  # index into owned rows
        offsets = np.empty(m + 1, dtype=np.int32)
        start_owned = offsets_own[np.clip(ranks, 0, None)]
        end_owned = offsets_own[np.clip(ranks + 1, 0, len(offsets_own) - 1)]
        offsets[:-1] = np.where(owned, start_owned,
                                np.where(ranks >= 0, end_owned, 0))
        offsets[-1] = offsets_own[-1]
        return offsets

    def segment_struct(group: Group, positions: np.ndarray,
                       null_mask: Optional[np.ndarray] = None):
        """StructArray of `group` at `positions` (child segments nested as
        list<struct> fields, schema order). `null_mask`: True where the
        struct itself is null (rows of positions owned by a sibling
        redefine — their decoded bytes are garbage by design)."""
        arrays, field_names = [], []
        owned = None if null_mask is None else ~null_mask
        idx = pa.array(positions.astype(np.int64))
        # all of this struct's string leaves in ONE subset kernel call
        built_at = builder.leaf_strings_at(
            [c for c in group.children
             if isinstance(c, Primitive) and not c.is_filler
             and not c.is_array], positions)
        for child in group.children:
            if child.is_filler:
                continue
            if isinstance(child, Group) and child.parent_segment is not None:
                continue  # nested below in schema order
            if isinstance(child, Group) and child.is_segment_redefine:
                # a segment redefine nested below this group (the root
                # case: the AST root holds the root redefines)
                child_owned = mask_of([child.name])[positions]
                sub_mask = (None if bool(child_owned.all())
                            else ~child_owned)
                arrays.append(segment_struct(child, positions, sub_mask))
                field_names.append(child.name)
                continue
            field_names.append(child.name)
            arr = None
            if isinstance(child, Primitive) and not child.is_array:
                # string/numeric leaves build straight at `positions`
                # (raw-image subset transcode / numpy gather) — no
                # full-length build, no take
                arr = built_at.get(id(child))
                if arr is None:
                    arr = builder.leaf_numeric_at(child, positions)
            arrays.append(arr if arr is not None
                          else full_array(child).take(idx))
        for seg in child_segments_of(group):
            par_pos = positions if owned is None else positions[owned]
            ch_pos, offs_own = assign_children(seg, par_pos)
            offsets = (offs_own if owned is None
                       else expand_offsets(offs_own, owned))
            field_names.append(seg.name)
            arrays.append(pa.ListArray.from_arrays(
                pa.array(offsets), segment_struct(seg, ch_pos)))
        if not arrays:
            return pa.nulls(len(positions), type=pa.struct([]))
        return pa.StructArray.from_arrays(
            arrays, names=field_names,
            mask=None if null_mask is None else pa.array(null_mask))

    cols: List[object] = []
    n_roots = len(roots)
    if output_schema.generate_record_id:
        cols.append(pa.array(np.full(n_roots, file_id, dtype=np.int32)))
        # flush-trigger ids: the next root's record index, or one past the
        # last record at end of stream
        triggers = np.empty(n_roots, dtype=np.int64)
        triggers[:-1] = start_record_id + roots[1:]
        triggers[-1] = start_record_id + n
        cols.append(pa.array(triggers))
        if output_schema.input_file_name_field:
            cols.append(pa.array([input_file_name] * n_roots,
                                 type=pa.string()))
    elif output_schema.input_file_name_field:
        cols.append(pa.array([input_file_name] * n_roots, type=pa.string()))

    for root in copybook.ast.children:
        if not isinstance(root, Group):
            continue
        struct = segment_struct(root, roots)
        if output_schema.policy is SchemaRetentionPolicy.COLLAPSE_ROOT:
            for f in struct.type:
                cols.append(struct.field(f.name))
        else:
            cols.append(struct)

    if getattr(output_schema, "corrupt_record_field", ""):
        # hierarchical assemblies carry no per-row corruption attribution;
        # the debug column is declared but all-null here (the ledger on
        # CobolData.diagnostics still records every incident)
        cols.append(pa.nulls(n_roots, pa.string()))

    target = arrow_schema(output_schema.schema)
    if len(cols) != len(target):
        _count("bail_schema_shape")
        return None  # shape mismatch: the row path owns it
    arrays = [c.cast(target.field(i).type)
              if c.type != target.field(i).type else c
              for i, c in enumerate(cols)]
    _count("columnar")
    return pa.Table.from_arrays(arrays, schema=target)
