"""Host-side work planning: files and index entries -> hosts.

The reference plans work on the Spark driver: `IndexBuilder.buildIndex`
runs one index task per file, collects `SparseIndexEntry` lists, queries
HDFS block locations, and `LocationBalancer.balance` re-assigns entries
from busy executors to idle ones (IndexBuilder.scala:49-116,
LocationBalancer.scala:42-66). Here the same planning is a pure function:
shards (whole files, or index entries within files) are assigned to hosts
by greedy longest-processing-time balancing on byte size. Each host then
feeds its shard list to its local device mesh; no record bytes ever move
between hosts (DCN carries only metrics), mirroring §2.5 of SURVEY.md.
"""
from __future__ import annotations

import heapq
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..reader.index import SparseIndexEntry


@dataclass(frozen=True)
class WorkShard:
    """A byte range of one file, assigned to one host."""
    file_path: str
    file_order: int
    offset_from: int
    offset_to: int          # -1 = to end of file
    record_index: int       # Record_Id seed for the shard (reference
                            # SparseIndexEntry.recordIndex semantics)

    @property
    def size(self) -> int:
        return -1 if self.offset_to < 0 else self.offset_to - self.offset_from


def shards_from_index(file_path: str, file_order: int,
                      entries: Sequence[SparseIndexEntry],
                      file_size: Optional[int] = None) -> List[WorkShard]:
    if file_size is None:
        file_size = os.path.getsize(file_path)
    out = []
    for e in entries:
        end = e.offset_to if e.offset_to >= 0 else file_size
        out.append(WorkShard(file_path, file_order, e.offset_from, end,
                             e.record_index))
    return out


def balance(shards: Sequence[WorkShard], n_hosts: int,
            reallocate_idle: bool = False) -> List[List[WorkShard]]:
    """Greedy LPT bin packing of shards onto hosts by byte size — the
    LocationBalancer analogue (no locality term: TPU hosts read from
    shared storage, so only load balance matters).

    `reallocate_idle` adds the second LocationBalancer pass
    (LocationBalancer.scala:42-66): queued entries move from the
    most-loaded host to hosts left idle by the primary assignment (the
    common trigger: remote whole-file shards of unknown size report -1,
    weigh 0 under LPT, and pile onto one host). Once no host is idle,
    only unknown-size shards keep equalizing by count — moving a
    KNOWN-size shard onto a byte-heavier host would worsen the makespan
    LPT already optimized."""
    if n_hosts <= 0:
        raise ValueError("n_hosts must be positive")
    assignments: List[List[WorkShard]] = [[] for _ in range(n_hosts)]
    # heap of (assigned_bytes, host_id)
    heap: List[Tuple[int, int]] = [(0, h) for h in range(n_hosts)]
    heapq.heapify(heap)
    for shard in sorted(shards, key=lambda s: -(s.size if s.size >= 0 else 0)):
        load, host = heapq.heappop(heap)
        assignments[host].append(shard)
        heapq.heappush(heap, (load + max(shard.size, 0), host))
    if reallocate_idle:
        # equalize by COUNT until no host holds 2+ more shards than
        # another. Moving the donor's last-queued entry mirrors the
        # reference's re-assignment of pending (not in-flight)
        # partitions; donors always keep >= 1.
        while True:
            busiest = max(range(n_hosts),
                          key=lambda h: (len(assignments[h]), -h))
            laziest = min(range(n_hosts),
                          key=lambda h: (len(assignments[h]), h))
            if len(assignments[busiest]) - len(assignments[laziest]) < 2:
                break
            donor = assignments[busiest]
            if assignments[laziest]:
                # receiver already works: only an unknown-size shard
                # (weight 0 to LPT) may keep equalizing — moving real
                # bytes onto a byte-heavier host worsens the makespan
                movable = next((i for i in range(len(donor) - 1, -1, -1)
                                if donor[i].size < 0), None)
                if movable is None:
                    break
                assignments[laziest].append(donor.pop(movable))
            else:
                assignments[laziest].append(donor.pop())
    # deterministic per-host order: by (file_order, offset)
    for a in assignments:
        a.sort(key=lambda s: (s.file_order, s.offset_from))
    return assignments


def plan_files(files: Sequence[str], n_hosts: int,
               reallocate_idle: bool = False) -> List[List[WorkShard]]:
    """Whole-file sharding (fixed-length / no-index path): one shard per
    file, balanced across hosts. Remote files size through their storage
    backend; an unsizable file enters at size -1 (unknown), which is
    exactly the case `reallocate_idle` redistributes."""
    from ..io.compress import is_compressed
    from ..reader.stream import path_scheme, source_size

    def size_of(f: str) -> int:
        # logical (decompressed) sizes throughout: shard bounds live in
        # the same byte space the streams serve
        try:
            return (os.path.getsize(f)
                    if path_scheme(f) in (None, "file")
                    and not is_compressed(f)
                    else source_size(f))
        except Exception:
            return -1

    shards = [
        WorkShard(f, order, 0, size_of(f), 0)
        for order, f in enumerate(files)]
    return balance(shards, n_hosts, reallocate_idle=reallocate_idle)
