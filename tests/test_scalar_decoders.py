"""Scalar decoder parity tests (the oracle layer).

Byte patterns follow the reference decoder unit suites
(BinaryDecoderSpec, StringDecodersSpec, BCD/FP specs — SURVEY.md §4 tier 1):
handcrafted bytes -> expected values, including malformed->None policy.
"""
import decimal
import math

import pytest

from cobrix_tpu.copybook.datatypes import TrimPolicy
from cobrix_tpu.encoding.codepages import get_code_page_table
from cobrix_tpu.ops import scalar_decoders as d

D = decimal.Decimal
COMMON = get_code_page_table("common")


class TestStrings:
    def test_ebcdic_string(self):
        data = bytes([0xC8, 0x85, 0x93, 0x93, 0x96])  # "Hello"
        assert d.decode_ebcdic_string(data, TrimPolicy.BOTH, COMMON) == "Hello"

    def test_ebcdic_trimming(self):
        data = bytes([0x40, 0xC1, 0x40])  # " A "
        assert d.decode_ebcdic_string(data, TrimPolicy.NONE, COMMON) == " A "
        assert d.decode_ebcdic_string(data, TrimPolicy.LEFT, COMMON) == "A "
        assert d.decode_ebcdic_string(data, TrimPolicy.RIGHT, COMMON) == " A"
        assert d.decode_ebcdic_string(data, TrimPolicy.BOTH, COMMON) == "A"

    def test_ascii_string_masks_control_and_high(self):
        assert d.decode_ascii_string(b"\x01A\xffB", TrimPolicy.NONE) == " A B"

    def test_hex(self):
        assert d.decode_hex(bytes([0x01, 0xAB, 0xFF])) == "01ABFF"

    def test_raw(self):
        assert d.decode_raw(b"\x00\x01") == b"\x00\x01"


class TestZonedNumbers:
    def test_unsigned_digits(self):
        assert d.decode_ebcdic_number(bytes([0xF1, 0xF2, 0xF3]), True) == "123"

    def test_overpunched_positive(self):
        # last digit C3 => +3
        assert d.decode_ebcdic_number(bytes([0xF1, 0xF2, 0xC3]), False) == "+123"

    def test_overpunched_negative(self):
        assert d.decode_ebcdic_number(bytes([0xF1, 0xF2, 0xD3]), False) == "-123"

    def test_negative_unsigned_is_null(self):
        assert d.decode_ebcdic_number(bytes([0xF1, 0xD2]), True) is None

    def test_explicit_minus(self):
        assert d.decode_ebcdic_number(bytes([0x60, 0xF1, 0xF2]), False) == "-12"

    def test_explicit_plus(self):
        assert d.decode_ebcdic_number(bytes([0x4E, 0xF5]), False) == "+5"

    def test_spaces_skipped(self):
        assert d.decode_ebcdic_number(bytes([0x40, 0xF1, 0x40]), True) == "1"

    def test_malformed_is_null(self):
        assert d.decode_ebcdic_number(bytes([0xF1, 0x81]), True) is None

    def test_decimal_point(self):
        assert d.decode_ebcdic_number(bytes([0xF1, 0x4B, 0xF5]), True) == "1.5"

    def test_comma_decimal_point(self):
        assert d.decode_ebcdic_number(bytes([0xF1, 0x6B, 0xF5]), True) == "1.5"

    def test_ascii_number(self):
        assert d.decode_ascii_number(b"123", True) == "123"
        assert d.decode_ascii_number(b"-123", False) == "-123"
        assert d.decode_ascii_number(b"-1", True) is None
        assert d.decode_ascii_number(b"12,5", True) == "12.5"


class TestAddDecimalPoint:
    @pytest.mark.parametrize("value,scale,sf,expected", [
        ("123456", 2, 0, "1234.56"),
        ("12", 4, 0, "0.0012"),
        ("-12", 4, 0, "-0.0012"),
        ("-123456", 2, 0, "-1234.56"),
        ("123", 0, 0, "123"),
        ("123", 0, 2, "12300"),
        ("123", 0, -2, "0.00123"),
        ("-123", 0, -2, "-0.00123"),
    ])
    def test_cases(self, value, scale, sf, expected):
        assert d.add_decimal_point(value, scale, sf) == expected


class TestBCD:
    def test_positive(self):
        assert d.decode_bcd_integral(bytes([0x12, 0x3C])) == 123

    def test_negative(self):
        assert d.decode_bcd_integral(bytes([0x12, 0x3D])) == -123

    def test_unsigned(self):
        assert d.decode_bcd_integral(bytes([0x12, 0x3F])) == 123

    def test_bad_sign_nibble(self):
        assert d.decode_bcd_integral(bytes([0x12, 0x3A])) is None

    def test_bad_digit_nibble(self):
        assert d.decode_bcd_integral(bytes([0x1B, 0x3C])) is None

    def test_empty(self):
        assert d.decode_bcd_integral(b"") is None

    def test_scaled_string(self):
        assert d.decode_bcd_string(bytes([0x12, 0x34, 0x5C]), 2, 0) == "123.45"

    def test_scale_bigger_than_digits(self):
        assert d.decode_bcd_string(bytes([0x1C]), 2, 0) == "0.01"

    def test_negative_scaled(self):
        assert d.decode_bcd_string(bytes([0x12, 0x34, 0x5D]), 2, 0) == "-123.45"

    def test_scale_factor_positive(self):
        assert d.decode_bcd_string(bytes([0x12, 0x3C]), 0, 2) == "12300"

    def test_scale_factor_negative(self):
        assert d.decode_bcd_string(bytes([0x12, 0x3C]), 0, -2) == "0.00123"

    def test_decimal_value(self):
        assert d.decode_bcd_decimal(bytes([0x12, 0x34, 0x5C]), 2, 0) == D("123.45")


class TestBinary:
    def test_signed_short_be(self):
        assert d.decode_binary_int(bytes([0xFF, 0xFE]), True, True, 2) == -2

    def test_signed_short_le(self):
        assert d.decode_binary_int(bytes([0xFE, 0xFF]), False, True, 2) == -2

    def test_unsigned_int_overflow_null(self):
        assert d.decode_binary_int(bytes([0x80, 0, 0, 0]), True, False, 4) is None

    def test_unsigned_long_overflow_null(self):
        assert d.decode_binary_int(bytes([0x80] + [0] * 7), True, False, 8) is None

    def test_signed_long(self):
        assert d.decode_binary_int(bytes([0xFF] * 8), True, True, 8) == -1

    def test_short_data_null(self):
        assert d.decode_binary_int(b"\x01", True, True, 2) is None

    def test_arbitrary_precision(self):
        data = bytes([0x01] * 10)
        v = d.decode_binary_arbitrary(data, True, False)
        assert v == D(int.from_bytes(data, "big"))

    def test_binary_number_string_scale(self):
        assert d.decode_binary_number_string(bytes([0x30, 0x39]), True, True, 2) == "123.45"


class TestFloats:
    def test_ieee_single(self):
        import struct
        assert d.decode_ieee754_single(struct.pack(">f", 1.5)) == 1.5

    def test_ieee_double_le(self):
        import struct
        assert d.decode_ieee754_double(struct.pack("<d", -2.25), False) == -2.25

    def test_ibm_double_100(self):
        # IBM hex double: 100.0 = 0x42 64000000000000 (exp 66, fract 0x64/16^2)
        data = bytes([0x42, 0x64, 0, 0, 0, 0, 0, 0])
        assert d.decode_ibm_double(data) == 100.0

    def test_ibm_double_zero(self):
        assert d.decode_ibm_double(bytes(8)) == 0.0

    def test_ibm_single_zero_fraction(self):
        assert d.decode_ibm_single(bytes([0x42, 0, 0, 0])) == 0.0

    def test_short_returns_null(self):
        assert d.decode_ieee754_single(b"\x01") is None
        assert d.decode_ibm_double(b"\x01") is None
