"""TPU-evidence watcher: probe the device tunnel periodically and run the
device benchmark the moment it responds.

The tunneled single-chip setup this framework is benchmarked on can wedge
for hours (a killed mid-flight transfer takes the relay down), and a
one-shot probe at bench time then forfeits the round's only TPU numbers.
This watcher closes that gap operationally: it loops a cheap subprocess
probe (a wedged tunnel can only hang — never the watcher itself) and, on
the first healthy response, runs `bench.py` and writes the JSON to
--out, then exits.

Usage:  python tools/tpu_watch.py [--interval 600] [--out TPU_EVIDENCE.json]
"""
import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import _probe_jax  # noqa: E402  (shared dead-relay fast path)


def probe():
    platform, _err = _probe_jax(timeouts=(45,))
    return platform


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=600)
    ap.add_argument("--out", default=os.path.join(REPO, "TPU_EVIDENCE.json"))
    ap.add_argument("--bench-mb", default="48")
    args = ap.parse_args()

    while True:
        platform = probe()
        stamp = time.strftime("%H:%M:%S")
        if platform and platform != "cpu":
            print(f"[{stamp}] tunnel up ({platform}); running bench",
                  flush=True)
            env = dict(os.environ, BENCH_MB=args.bench_mb)
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.join(REPO, "bench.py")],
                    capture_output=True, text=True, env=env, timeout=3600)
            except subprocess.TimeoutExpired:
                # the tunnel wedged mid-bench — the exact scenario the
                # watcher exists to survive; keep polling
                print(f"[{stamp}] bench timed out (tunnel wedged?); "
                      "continuing", flush=True)
                time.sleep(args.interval)
                continue
            line = (proc.stdout.strip().splitlines() or [""])[-1]
            try:
                parsed = json.loads(line)
            except ValueError:
                parsed = None
            # only JSON with ACTUAL device evidence ends the watch — the
            # tunnel may answer the 45s probe yet wedge before bench's own
            # probe, yielding an honest but deviceless CPU-only line
            has_device = (parsed is not None
                          and parsed.get("device") not in (None,
                                                           "unavailable")
                          and isinstance(parsed.get("device_query"), dict)
                          and "error" not in parsed["device_query"])
            if parsed is None and os.path.exists(args.out):
                # never clobber earlier honest evidence with a failed run
                print(f"[{stamp}] bench produced no JSON; keeping "
                      f"existing {args.out}", flush=True)
                time.sleep(args.interval)
                continue
            with open(args.out, "w") as f:
                json.dump({"captured_at": time.strftime("%F %T"),
                           "platform": platform, "rc": proc.returncode,
                           "bench": parsed,
                           "stderr_tail": proc.stderr[-3000:]}, f, indent=1)
            print(f"[{stamp}] wrote {args.out} (rc={proc.returncode}, "
                  f"device={has_device})", flush=True)
            if has_device:
                return
        else:
            print(f"[{stamp}] tunnel down", flush=True)
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
