"""Scalar field encoders — the exact inverses of `ops.scalar_decoders`.

Every encoder here is derived from the corresponding decoder's semantics
(the parity oracle pinned by the reference goldens), so that for any value
`v` a field type can represent, `decode_field(dtype, encode_field(dtype, v))
== v`, and re-encoding the decoded value reproduces the same bytes
(encode is deterministic — decode→encode→decode is a byte-stable fixed
point after one round).

Known inversion gaps (named in ROADMAP item 3):

* COMP-1 under `FloatingPointFormat.IBM`: the reference decoder masks the
  exponent with the *sign* mask (FloatingPointDecoders.scala:82, replicated
  verbatim in `decode_ibm_single`), so no nonzero standard-encoded IBM
  single decodes to its own value. `encode_field` writes TRUE IBM bits
  (correct for real mainframes); round-trip identity for COMP-1 holds only
  under the IEEE754/IEEE754_LE formats (or for 0.0).
* Values a type cannot represent (None in a binary/float field, digits
  beyond the PIC precision, characters outside the code page) raise
  `EncodeError` rather than guessing.

Closed former gaps, now invariants the fuzzer covers unpinned: blank
fill is the spelling of None for EVERY display numeric — integrals,
explicit-dot decimals, and implied-point V-decimals alike — and the
decoders return null (never 0.00) for digit-less content, so
encode(None)→decode round-trips. Duplicate-glyph code pages invert
deterministically lowest-byte-wins (space always canonicalizes to
0x40), pinned end to end by rtcheck's P3 alias matrix.
"""
from __future__ import annotations

import decimal as _decimal
import math
import struct
from typing import Optional

from ..copybook.datatypes import (
    AlphaNumeric,
    Decimal,
    EBCDIC_DOT,
    EBCDIC_MINUS,
    EBCDIC_PLUS,
    EBCDIC_SPACE,
    Encoding,
    FloatingPointFormat,
    Integral,
    SignPosition,
    Usage,
    binary_size_bytes,
)
from ..encoding.codepages import get_code_page_encode_table

PyDecimal = _decimal.Decimal


class EncodeError(ValueError):
    """A value the target COBOL type cannot represent byte-for-byte."""


# ---------------------------------------------------------------------------
# mantissa extraction: value -> (integer mantissa, digit count available)
# ---------------------------------------------------------------------------

def _as_decimal(value) -> PyDecimal:
    if isinstance(value, PyDecimal):
        return value
    if isinstance(value, int):
        return PyDecimal(value)
    if isinstance(value, float):
        # repr round-trip: the decoded value came from a decimal string
        return PyDecimal(repr(value))
    if isinstance(value, str):
        return PyDecimal(value)
    raise EncodeError(f"Cannot encode {type(value).__name__} as a number")


def _exact_int(d: PyDecimal, what: str) -> int:
    if d != d.to_integral_value():
        raise EncodeError(f"{what}: value {d} is not representable "
                          f"(non-integral mantissa)")
    return int(d)


def scaled_mantissa(dtype, value, ndigits: int) -> int:
    """Integer mantissa whose `ndigits`-digit rendering decodes back to
    `value` under (scale, scale_factor) — the inverse of
    `add_decimal_point`/`decode_bcd_string` scaling."""
    d = _as_decimal(value)
    if isinstance(dtype, Integral):
        return _exact_int(d, dtype.pic)
    scale, sf = dtype.scale, dtype.scale_factor
    if sf == 0:
        return _exact_int(d.scaleb(scale), dtype.pic)
    if sf > 0:
        return _exact_int(d.scaleb(-sf), dtype.pic)
    # scale factor < 0: decoded value is 0.<|sf| zeros><digits>
    return _exact_int(d.scaleb(-sf + ndigits), dtype.pic)


def _binary_mantissa(dtype, value) -> int:
    """Binary fields render the mantissa with no leading zeros, so a
    negative scale factor needs the digit count solved for."""
    d = _as_decimal(value)
    if isinstance(dtype, Integral):
        return _exact_int(d, dtype.pic)
    scale, sf = dtype.scale, dtype.scale_factor
    if sf == 0:
        return _exact_int(d.scaleb(scale), dtype.pic)
    if sf > 0:
        return _exact_int(d.scaleb(-sf), dtype.pic)
    if d == 0:
        return 0
    for nd in range(1, 40):
        m = d.scaleb(-sf + nd)
        if m == m.to_integral_value() and len(str(abs(int(m)))) == nd:
            return int(m)
    raise EncodeError(f"{dtype.pic}: {d} has no scale_factor={sf} "
                      f"binary mantissa")


# ---------------------------------------------------------------------------
# zoned (DISPLAY) numerics
# ---------------------------------------------------------------------------

def _overpunch_side(dtype) -> str:
    """'left'/'right' overpunch digit, or 'separate' — from the PIC the
    sign clause was folded into (pic.apply_sign prepends/appends the sign
    char; a plain S picture overpunches the TRAILING digit, the COBOL
    default)."""
    if dtype.is_sign_separate:
        return "separate"
    pic = dtype.pic or ""
    if pic[:1] in "+-":
        return "left"
    return "right"


def encode_display_number(dtype, value, ascii_mode: bool = False) -> bytes:
    """Inverse of decode_ebcdic_number/decode_ascii_number (+ the
    add_decimal_point scaling applied by decode_field)."""
    size = binary_size_bytes(dtype)
    if value is None:
        return (b" " if ascii_mode else bytes([EBCDIC_SPACE])) * size
    precision = dtype.precision
    explicit_dot = isinstance(dtype, Decimal) and dtype.explicit_decimal
    m = scaled_mantissa(dtype, value, precision)
    if not dtype.is_signed and m < 0:
        raise EncodeError(f"{dtype.pic}: negative value in unsigned field")
    digits = str(abs(m))
    if len(digits) > precision:
        raise EncodeError(f"{dtype.pic}: {value} needs {len(digits)} digits, "
                          f"PIC has {precision}")
    digits = digits.zfill(precision)
    if explicit_dot:
        scale = dtype.scale
        digits = digits[:precision - scale] + "." + digits[precision - scale:]

    if ascii_mode:
        return _ascii_display(dtype, m, digits, size)
    return _ebcdic_display(dtype, m, digits, size)


def _ebcdic_display(dtype, m: int, digits: str, size: int) -> bytes:
    body = bytearray()
    for ch in digits:
        body.append(EBCDIC_DOT if ch == "." else 0xF0 + ord(ch) - 0x30)
    if not dtype.is_signed:
        out = bytes(body)
    else:
        side = _overpunch_side(dtype)
        if side == "separate":
            sign_byte = EBCDIC_MINUS if m < 0 else EBCDIC_PLUS
            if dtype.sign_position is SignPosition.LEFT:
                out = bytes([sign_byte]) + bytes(body)
            else:
                out = bytes(body) + bytes([sign_byte])
        else:
            zone = 0xD0 if m < 0 else 0xC0
            idx = 0 if side == "left" else len(body) - 1
            # overpunch lands on a digit byte, never the explicit dot
            if body[idx] == EBCDIC_DOT:
                raise EncodeError(f"{dtype.pic}: sign overpunch on the "
                                  f"decimal point")
            body[idx] = zone + (body[idx] - 0xF0)
            out = bytes(body)
    if len(out) != size:
        raise EncodeError(f"{dtype.pic}: encoded {len(out)} bytes, "
                          f"field is {size}")
    return out


def _ascii_display(dtype, m: int, digits: str, size: int) -> bytes:
    if not dtype.is_signed:
        out = digits.encode("ascii")
    elif dtype.is_sign_separate:
        sign = "-" if m < 0 else "+"
        if dtype.sign_position is SignPosition.LEFT:
            out = (sign + digits).encode("ascii")
        else:
            out = (digits + sign).encode("ascii")
    elif m < 0:
        # no ASCII overpunch exists: the sign char must displace the
        # leading (zero-filled) digit to keep the field width
        if digits[0] != "0":
            raise EncodeError(f"{dtype.pic}: negative ASCII DISPLAY needs "
                              f"a spare leading digit for the '-'")
        out = ("-" + digits[1:]).encode("ascii")
    else:
        out = digits.encode("ascii")
    if len(out) != size:
        raise EncodeError(f"{dtype.pic}: encoded {len(out)} bytes, "
                          f"field is {size}")
    return out


# ---------------------------------------------------------------------------
# packed BCD (COMP-3)
# ---------------------------------------------------------------------------

def encode_bcd(dtype, value) -> bytes:
    """Inverse of decode_bcd_integral / decode_bcd_string."""
    size = binary_size_bytes(dtype)
    if value is None:
        # 0x40 fill: every decoder rejects the 0x0 terminal sign nibble
        return bytes([EBCDIC_SPACE]) * size
    nslots = size * 2 - 1
    m = scaled_mantissa(dtype, value, nslots)
    if not dtype.is_signed and m < 0:
        raise EncodeError(f"{dtype.pic}: negative value in unsigned field")
    digits = str(abs(m))
    if len(digits) > nslots:
        raise EncodeError(f"{dtype.pic}: {value} needs {len(digits)} BCD "
                          f"digits, field holds {nslots}")
    digits = digits.zfill(nslots)
    sign_nibble = 0x0D if m < 0 else (0x0C if dtype.is_signed else 0x0F)
    nibbles = [ord(c) - 0x30 for c in digits] + [sign_nibble]
    return bytes((nibbles[i] << 4) | nibbles[i + 1]
                 for i in range(0, len(nibbles), 2))


# ---------------------------------------------------------------------------
# binary (COMP/COMP-4/COMP-5/COMP-9)
# ---------------------------------------------------------------------------

def encode_binary(dtype, value) -> bytes:
    size = binary_size_bytes(dtype)
    if value is None:
        raise EncodeError(f"{dtype.pic}: a binary field cannot encode None")
    big_endian = dtype.usage is not Usage.COMP9
    m = _binary_mantissa(dtype, value)
    signed = dtype.is_signed
    if not signed and m < 0:
        raise EncodeError(f"{dtype.pic}: negative value in unsigned field")
    try:
        out = m.to_bytes(size, "big" if big_endian else "little",
                         signed=signed)
    except OverflowError:
        raise EncodeError(f"{dtype.pic}: {value} overflows {size}-byte "
                          f"binary") from None
    if not signed and size in (4, 8) and m > (1 << (size * 8 - 1)) - 1:
        # the reference decoder returns None for these (unsigned
        # negative-overflow guard) — refuse to write undecodable bytes
        raise EncodeError(f"{dtype.pic}: {value} is in the unsigned "
                          f"overflow range the decoder rejects")
    return out


# ---------------------------------------------------------------------------
# floating point
# ---------------------------------------------------------------------------

def encode_ieee754_single(value: float, big_endian: bool = True) -> bytes:
    return struct.pack(">f" if big_endian else "<f", value)


def encode_ieee754_double(value: float, big_endian: bool = True) -> bytes:
    return struct.pack(">d" if big_endian else "<d", value)


def _encode_ibm_hex(value: float, frac_bits: int, width: int) -> bytes:
    """True IBM hexadecimal float: sign bit, excess-64 base-16 exponent,
    `frac_bits`-bit fraction in [1/16, 1)."""
    if value == 0.0:
        return b"\x00" * width
    sign = 0x80 if value < 0 else 0x00
    mant, e2 = math.frexp(abs(value))        # abs(value) = mant * 2**e2
    e16 = math.ceil(e2 / 4)
    frac = mant * 2.0 ** (e2 - 4 * e16)      # in [1/16, 1)
    f_int = int(round(frac * (1 << frac_bits)))
    if f_int >= (1 << frac_bits):            # rounding carried a hex digit
        f_int >>= 4
        e16 += 1
    exponent = 64 + e16
    if not 0 <= exponent <= 127:
        raise EncodeError(f"{value} overflows the IBM hexfloat exponent")
    return bytes([sign | exponent]) + f_int.to_bytes(width - 1, "big")


def encode_ibm_single(value: float) -> bytes:
    return _encode_ibm_hex(value, 24, 4)


def encode_ibm_double(value: float) -> bytes:
    return _encode_ibm_hex(value, 56, 8)


def _encode_float(dtype, value, fmt: FloatingPointFormat) -> bytes:
    if value is None:
        raise EncodeError(f"{dtype.pic}: a float field cannot encode None")
    v = float(value)
    single = dtype.usage is Usage.COMP1
    if fmt is FloatingPointFormat.IBM:
        return encode_ibm_single(v) if single else encode_ibm_double(v)
    if fmt is FloatingPointFormat.IBM_LE:
        raw = encode_ibm_single(v) if single else encode_ibm_double(v)
        return raw[::-1]
    big = fmt is FloatingPointFormat.IEEE754
    return (encode_ieee754_single(v, big) if single
            else encode_ieee754_double(v, big))


# ---------------------------------------------------------------------------
# strings
# ---------------------------------------------------------------------------

def encode_string(dtype: AlphaNumeric, value, *,
                  ebcdic_code_page: str = "common",
                  ascii_charset: str = "us-ascii",
                  is_utf16_big_endian: bool = True) -> bytes:
    enc = dtype.enc or Encoding.EBCDIC
    length = dtype.length
    if enc is Encoding.RAW:
        data = bytes(value or b"")
        pad = b"\x00"
    elif enc is Encoding.HEX:
        data = bytes.fromhex(value or "")
        pad = b"\x00"
    elif enc is Encoding.EBCDIC:
        table = get_code_page_encode_table(ebcdic_code_page)
        out = bytearray()
        for ch in (value or ""):
            b = table.get(ch)
            if b is None:
                raise EncodeError(
                    f"char {ch!r} has no EBCDIC byte in code page "
                    f"'{ebcdic_code_page}'")
            out.append(b)
        data, pad = bytes(out), bytes([EBCDIC_SPACE])
    elif enc is Encoding.ASCII:
        charset = ("ascii" if ascii_charset.lower().replace("_", "-")
                   in ("us-ascii", "ascii") else ascii_charset)
        try:
            data = (value or "").encode(charset)
        except (UnicodeEncodeError, LookupError) as e:
            raise EncodeError(str(e)) from e
        pad = b" "
    elif enc is Encoding.UTF16:
        codec = "utf-16-be" if is_utf16_big_endian else "utf-16-le"
        data = (value or "").encode(codec)
        pad = " ".encode(codec)
    else:
        raise EncodeError(f"Unknown encoding {enc}")
    if len(data) > length:
        raise EncodeError(f"{value!r} is {len(data)} bytes, PIC holds "
                          f"{length}")
    npad, rem = divmod(length - len(data), len(pad))
    return data + pad * npad + pad[:rem]


# ---------------------------------------------------------------------------
# dispatcher (inverse of decode_field)
# ---------------------------------------------------------------------------

def encode_field(dtype, value, *,
                 ebcdic_code_page: str = "common",
                 ascii_charset: str = "us-ascii",
                 is_utf16_big_endian: bool = True,
                 floating_point_format: FloatingPointFormat =
                 FloatingPointFormat.IBM) -> bytes:
    """Encode one field value to exactly `binary_size_bytes(dtype)` bytes
    such that `decode_field` recovers the value (see module docstring for
    the named gaps)."""
    if isinstance(dtype, AlphaNumeric):
        return encode_string(dtype, value,
                             ebcdic_code_page=ebcdic_code_page,
                             ascii_charset=ascii_charset,
                             is_utf16_big_endian=is_utf16_big_endian)
    if not isinstance(dtype, (Integral, Decimal)):
        raise TypeError(f"Unknown COBOL type {dtype!r}")
    usage = dtype.usage
    if usage is None:
        ascii_mode = (dtype.enc or Encoding.EBCDIC) is not Encoding.EBCDIC
        return encode_display_number(dtype, value, ascii_mode=ascii_mode)
    if usage in (Usage.COMP1, Usage.COMP2):
        return _encode_float(dtype, value, floating_point_format)
    if usage is Usage.COMP3:
        return encode_bcd(dtype, value)
    if usage in (Usage.COMP4, Usage.COMP5, Usage.COMP9):
        return encode_binary(dtype, value)
    raise EncodeError(f"Unknown usage {usage}")
