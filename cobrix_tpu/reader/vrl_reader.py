"""Variable-record-length framing: iterate (segment_id, record_bytes).

Mirrors the reference VRLRecordReader (reader/iterator/VRLRecordReader.scala:39):
records come from a raw extractor, RDW-style headers, or a record-length
field decoded mid-stream; tracks byte and record indices for deterministic
Record_Id generation.

This is the host-side framing pass of the TPU design: it yields record
boundaries; the columnar reader packs the framed records into padded
`[batch, max_len]` device blocks (reader/var_len_reader.py).
"""
from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from ..copybook.ast import Primitive
from ..copybook.copybook import Copybook
from .header_parsers import RecordHeaderParser
from .parameters import ReaderParameters
from .raw_extractors import RawRecordExtractor
from .stream import SimpleStream


def resolve_length_field(length_field_name: Optional[str],
                         copybook: Copybook) -> Optional[Primitive]:
    """reference ReaderParametersValidator.getLengthField."""
    if not length_field_name:
        return None
    field = copybook.get_field_by_name(length_field_name)
    if not isinstance(field, Primitive):
        raise ValueError(
            f"The record length field '{length_field_name}' must be a primitive.")
    from ..copybook.datatypes import Integral
    if not isinstance(field.dtype, Integral) and not field.depending_on_handlers:
        raise ValueError(
            f"The record length field '{length_field_name}' must be an integral type.")
    return field


class SegmentIds:
    """Per-record segment-id strings in dictionary-coded form: `codes`
    (int32 per record) indexing `uniq` (decoded strings, one per distinct
    byte pattern). Reads like a sequence of strings; the hot paths
    (segment masks, redefine routing, level mapping) work on the integer
    codes and never materialize per-record Python strings."""

    __slots__ = ("codes", "uniq")

    def __init__(self, codes, uniq):
        self.codes = codes
        self.uniq = list(uniq)

    def __len__(self) -> int:
        return len(self.codes)

    def __getitem__(self, i) -> str:
        return self.uniq[self.codes[i]]

    def __iter__(self):
        uniq = self.uniq
        for c in self.codes:
            yield uniq[c]

    def __eq__(self, other) -> bool:
        return list(self) == list(other)

    def tolist(self) -> list:
        if not self.uniq:
            return []
        return list(np.asarray(self.uniq, dtype=object)[self.codes])

    def map_uniq(self, mapping: dict, default: str = "") -> list:
        """Mapped value per DISTINCT id, aligned to `uniq` (one dict lookup
        per distinct id; broadcast over records via `codes`)."""
        return [mapping.get(u, default) for u in self.uniq]

    def _hits_mask(self, hits) -> "np.ndarray":
        """OR of code equalities — hit lists are tiny (distinct ids), so
        this beats np.isin's sort machinery on the hot per-record axis."""
        if not hits:
            return np.zeros(len(self.codes), dtype=bool)
        mask = self.codes == hits[0]
        for k in hits[1:]:
            mask |= self.codes == k
        return mask

    def mask_of(self, values) -> "np.ndarray":
        """Boolean per-record mask of ids contained in `values`."""
        return self._hits_mask(
            [k for k, u in enumerate(self.uniq) if u in values])

    def mask_of_mapped(self, mapping: dict, value: str,
                       default: str = "") -> "np.ndarray":
        """Boolean per-record mask of ids whose `mapping` image equals
        `value` (segment id -> active redefine routing)."""
        return self._hits_mask(
            [k for k, u in enumerate(self.uniq)
             if mapping.get(u, default) == value])

    def replace_at(self, i: int, value: str) -> None:
        """Point fixup (truncated trailing records decode individually)."""
        try:
            k = self.uniq.index(value)
        except ValueError:
            self.uniq.append(value)
            k = len(self.uniq) - 1
        self.codes[i] = k


def decode_segment_id_bytes(field_bytes, seg_field: Primitive,
                            options) -> SegmentIds:
    """Per-record segment ids from a [n, field_width] byte matrix as a
    dictionary-coded `SegmentIds`, decoding each unique byte pattern once
    (shared by the fixed-length and variable-length readers). Fields up to
    2 bytes code via one O(n) bincount; up to 8 bytes via an integer-key
    sort — both far cheaper than a row-wise lexicographic unique at exp2's
    600k narrow records."""
    fb = np.ascontiguousarray(field_bytes)
    n, w = fb.shape
    if n == 0:
        return SegmentIds(np.zeros(0, dtype=np.int32), [])
    if w <= 8:
        if w == 1:
            keys = fb[:, 0]
        elif w == 2:
            keys = fb.view("<u2").ravel()
        else:
            padded = np.zeros((n, 8), dtype=np.uint8)
            padded[:, :w] = fb
            keys = padded.view("<u8").ravel()
        if w <= 2:
            counts = np.bincount(keys, minlength=(1 << (8 * w)))
            uniq_keys = np.nonzero(counts)[0]
            code_of = np.zeros(counts.shape[0], dtype=np.int32)
            code_of[uniq_keys] = np.arange(len(uniq_keys), dtype=np.int32)
            codes = code_of[keys]
        else:
            uniq_keys, codes = np.unique(keys, return_inverse=True)
            codes = codes.astype(np.int32, copy=False)
        key_dt = {1: "<u1", 2: "<u2"}.get(w, "<u8")
        uniq_bytes = [uniq_keys.astype(key_dt)[k:k + 1].tobytes()[:w]
                      for k in range(len(uniq_keys))]
    else:
        flat = fb.view(np.dtype((np.void, w))).ravel()
        uniq_rows, codes = np.unique(flat, return_inverse=True)
        codes = codes.astype(np.int32, copy=False)
        uniq_bytes = [bytes(row) for row in uniq_rows]
    uniq = []
    for chunk in uniq_bytes:
        value = options.decode(seg_field.dtype, chunk)
        uniq.append("" if value is None else str(value).strip())
    return SegmentIds(codes, uniq)


def resolve_segment_id_field(params: ReaderParameters,
                             copybook: Copybook) -> Optional[Primitive]:
    """reference ReaderParametersValidator.getSegmentIdField."""
    if params.multisegment is None or not params.multisegment.segment_id_field:
        return None
    field = copybook.get_field_by_name(params.multisegment.segment_id_field)
    if not isinstance(field, Primitive):
        raise ValueError(
            f"The segment id field '{params.multisegment.segment_id_field}' "
            "must be a primitive.")
    return field


class VRLRecordReader:
    """Iterator of (segment_id, record_bytes)."""

    def __init__(self,
                 copybook: Copybook,
                 data_stream: SimpleStream,
                 params: ReaderParameters,
                 record_header_parser: RecordHeaderParser,
                 record_extractor: Optional[RawRecordExtractor] = None,
                 start_record_id: int = 0,
                 starting_file_offset: int = 0):
        self.copybook = copybook
        self.stream = data_stream
        self.params = params
        self.header_parser = record_header_parser
        self.record_extractor = record_extractor
        self._byte_index = starting_file_offset
        self._record_index = start_record_id - 1
        self.length_field = resolve_length_field(params.length_field_name, copybook)
        self.segment_id_field = resolve_segment_id_field(params, copybook)
        self._cached: Optional[Tuple[str, bytes]] = None
        self._fetch()

    def __iter__(self) -> Iterator[Tuple[str, bytes]]:
        return self

    def has_next(self) -> bool:
        return self._cached is not None

    @property
    def record_index(self) -> int:
        return self._record_index

    @property
    def byte_index(self) -> int:
        return self._byte_index

    def __next__(self) -> Tuple[str, bytes]:
        if self._cached is None:
            raise StopIteration
        value = self._cached
        self._fetch()
        self._record_index += 1
        return value

    def _fetch(self) -> None:
        if self.record_extractor is not None:
            data = (next(self.record_extractor)
                    if self.record_extractor.has_next() else None)
        elif self.params.is_record_sequence or self.length_field is None:
            data = self._fetch_using_headers()
        else:
            data = self._fetch_using_length_field()
        if data is None:
            self._cached = None
            return
        segment_id = ""
        if self.segment_id_field is not None:
            value = self.copybook.extract_primitive_field(
                self.segment_id_field, data, self.params.start_offset)
            segment_id = "" if value is None else str(value).strip()
        self._cached = (segment_id, data)

    def _fetch_using_length_field(self) -> Optional[bytes]:
        lf = self.length_field
        length_field_block = (lf.binary_properties.offset
                              + lf.binary_properties.actual_size)
        head_len = self.params.start_offset + length_field_block
        start = self.stream.next(head_len)
        self._byte_index += head_len
        if len(start) < head_len:
            return None
        value = self.copybook.extract_primitive_field(
            lf, start, self.params.start_offset)
        if value is None or isinstance(value, (bytes, float)):
            raise ValueError(
                f"Record length value of the field {lf.name} must be an "
                "integral type.")
        record_length = int(value) + self.params.rdw_adjustment
        rest = record_length - length_field_block + self.params.end_offset
        self._byte_index += rest
        if rest > 0:
            return start + self.stream.next(rest)
        return start

    def _fetch_using_headers(self) -> Optional[bytes]:
        header_block = self.header_parser.header_length
        is_valid = False
        end_of_file = False
        header = b""
        record = b""
        while not is_valid and not end_of_file:
            header = self.stream.next(header_block)
            meta = self.header_parser.get_record_metadata(
                header, self.stream.offset, self.stream.true_size,
                self._record_index)
            self._byte_index += len(header)
            if meta.record_length > 0:
                record = self.stream.next(meta.record_length)
                self._byte_index += len(record)
            else:
                end_of_file = True
            is_valid = meta.is_valid
        if end_of_file:
            return None
        if self.header_parser.is_header_defined_in_copybook:
            return header + record
        return record
