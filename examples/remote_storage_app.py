"""Remote-storage read through the fsspec backend (cobrix_tpu.io): a
VRL multisegment scan from an in-memory object store (`memory://`),
with the persistent block + sparse-index cache and read-ahead on. The
same options work for `s3://`/`gs://`/`hdfs://` URLs — only the URL
(and the protocol package, e.g. s3fs) changes."""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cobrix_tpu import read_cobol
from cobrix_tpu.testing.generators import EXP2_COPYBOOK, generate_exp2


def main():
    try:
        import fsspec
    except ImportError:
        # the io subsystem is optional: read_cobol on a remote URL
        # raises this same actionable message
        print("fsspec is not installed (pip install fsspec) — "
              "remote storage demo skipped")
        return

    # stand-in for an object store: fsspec's in-memory filesystem
    fs = fsspec.filesystem("memory")
    with fs.open("/landing/COMPANY.DETAILS.dat", "wb") as f:
        f.write(generate_exp2(2000, seed=100))

    with tempfile.TemporaryDirectory() as cache_dir:
        kw = dict(
            copybook_contents=EXP2_COPYBOOK,
            is_record_sequence="true",
            segment_field="SEGMENT-ID",
            redefine_segment_id_map="STATIC-DETAILS => C",
            **{"redefine_segment_id_map:1": "CONTACTS => P"},
            input_split_records=500,  # sparse index -> parallel shards
            cache_dir=cache_dir,   # persistent block + sparse-index cache
            prefetch_blocks=2,     # read-ahead: fetch 2 blocks ahead
            io_block_mb=0.02)      # small blocks for this small demo

        cold = read_cobol("memory://landing/COMPANY.DETAILS.dat", **kw)
        warm = read_cobol("memory://landing/COMPANY.DETAILS.dat", **kw)

    table = warm.to_arrow()
    print(f"{table.num_rows} rows from memory:// "
          f"(columns: {table.column_names[:4]}...)")
    for label, result in (("cold", cold), ("warm", warm)):
        io = result.metrics.as_dict()["io"]
        print(f"{label}: fetched {io['bytes_fetched']} B from storage, "
              f"{io['bytes_from_cache']} B from cache, "
              f"index {io['index_hits']} hit / {io['index_misses']} miss, "
              f"prefetch utilization {io['prefetch_utilization']:.2f}")
    assert warm.to_arrow().equals(cold.to_arrow())


if __name__ == "__main__":
    main()
