// Native record-framing and batch-packing runtime.
//
// The reference frames variable-length records on the JVM, one record per
// iteration (VRLRecordReader.scala:151-186 RDW path, :114-149
// record-length-field path; TextRecordExtractor.scala:27-103 for text),
// and the sequential index pass walks the same loop (IndexGenerator.
// scala:33). Here the host-side hot loops are C++: a single pass emits
// every record's (offset, length) into flat arrays, and a second routine
// packs selected records into the padded [batch, extent] uint8 matrix the
// TPU decode kernels consume. Python keeps the slow/flexible paths
// (custom extractors, copybook-driven length fields with exotic types).
//
// Exposed via a plain C ABI for ctypes binding (no pybind11 in the image).

#include <cstdint>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// Error codes (mirrors the hard-error semantics of
// RecordHeaderParserRDW.scala: zero/oversized RDW kills the read).
enum FramingStatus : int64_t {
  FRAMING_OK = 0,
  FRAMING_ZERO_LENGTH = -1,
  FRAMING_TOO_BIG = -2,
};

static const int64_t kMaxRdwRecordSize = 100L * 1024 * 1024;  // 100 MB cap

// Scan RDW (record descriptor word) headers.
//   data/size:        whole file image
//   big_endian:       1 = length in bytes [0..1], 0 = bytes [3..2]
//   rdw_adjustment:   added to each header length
//   file_header_bytes/file_footer_bytes: leading/trailing regions emitted
//                     as *invalid* records (skipped here, but their bytes
//                     are consumed) — reference RecordHeaderParserRDW
//                     file-header handling
//   offsets/lengths:  out arrays (caller-allocated, capacity max_records)
//   error_pos:        byte position of a fatal header on error
// Returns number of records, or a FramingStatus < 0.
int64_t rdw_scan(const uint8_t* data, int64_t size, int32_t big_endian,
                 int32_t rdw_adjustment, int64_t file_header_bytes,
                 int64_t file_footer_bytes, int64_t* offsets,
                 int64_t* lengths, int64_t max_records, int64_t* error_pos) {
  int64_t pos = 0;
  int64_t n = 0;
  int64_t body_end = size;
  if (file_footer_bytes > 0 && file_footer_bytes < size) {
    body_end = size - file_footer_bytes;
  }
  while (pos + 4 <= body_end && n < max_records) {
    // leading file-header region: consumed as an invalid record
    if (file_header_bytes > 4 && pos == 0) {
      pos = file_header_bytes;
      continue;
    }
    int64_t len;
    if (big_endian) {
      len = (int64_t)data[pos + 1] + 256 * (int64_t)data[pos];
    } else {
      len = (int64_t)data[pos + 2] + 256 * (int64_t)data[pos + 3];
    }
    len += rdw_adjustment;
    if (len <= 0) {
      *error_pos = pos;
      return FRAMING_ZERO_LENGTH;
    }
    if (len > kMaxRdwRecordSize) {
      *error_pos = pos;
      return FRAMING_TOO_BIG;
    }
    offsets[n] = pos + 4;
    int64_t avail = body_end - (pos + 4);
    lengths[n] = len < avail ? len : avail;
    ++n;
    pos += 4 + len;
  }
  return n;
}

// Scan records whose length comes from a field inside each record.
//   field_offset/field_width: where the length field sits
//   kind: 0 = unsigned binary big-endian, 1 = unsigned binary
//         little-endian, 2 = zoned DISPLAY digits (EBCDIC F0-F9),
//         3 = zoned DISPLAY digits (ASCII '0'-'9')
//   length_adjust: added to the decoded value (e.g. +header size when the
//                  field holds the payload length)
// Stops cleanly at a record whose length field is unreadable (returns
// records so far; *error_pos = position) — Python re-checks the tail.
int64_t length_field_scan(const uint8_t* data, int64_t size,
                          int64_t field_offset, int64_t field_width,
                          int32_t kind, int64_t length_adjust,
                          int64_t* offsets, int64_t* lengths,
                          int64_t max_records, int64_t* error_pos) {
  int64_t pos = 0;
  int64_t n = 0;
  while (pos < size && n < max_records) {
    if (pos + field_offset + field_width > size) break;
    const uint8_t* f = data + pos + field_offset;
    int64_t value = 0;
    if (kind == 0) {
      for (int64_t i = 0; i < field_width; ++i) value = (value << 8) | f[i];
    } else if (kind == 1) {
      for (int64_t i = field_width - 1; i >= 0; --i)
        value = (value << 8) | f[i];
    } else {
      for (int64_t i = 0; i < field_width; ++i) {
        uint8_t d = f[i];
        uint8_t digit;
        if (kind == 2) {  // EBCDIC zoned
          if (d == 0x40) continue;  // space
          if (d < 0xF0 || d > 0xF9) { *error_pos = pos; return n; }
          digit = d - 0xF0;
        } else {  // ASCII
          if (d == ' ') continue;
          if (d < '0' || d > '9') { *error_pos = pos; return n; }
          digit = d - '0';
        }
        value = value * 10 + digit;
      }
    }
    value += length_adjust;
    if (value <= 0) { *error_pos = pos; return n; }
    offsets[n] = pos;
    int64_t avail = size - pos;
    lengths[n] = value < avail ? value : avail;
    ++n;
    pos += value;
  }
  return n;
}

// Scan text records delimited by LF / CRLF (reference TextRecordExtractor:
// boundaries at EOL; CR stripped when followed by LF).
int64_t text_scan(const uint8_t* data, int64_t size, int64_t* offsets,
                  int64_t* lengths, int64_t max_records) {
  int64_t pos = 0;
  int64_t n = 0;
  while (pos < size && n < max_records) {
    int64_t eol = pos;
    while (eol < size && data[eol] != '\n') ++eol;
    int64_t end = eol;
    if (end > pos && end <= size && end > 0 && data[end - 1] == '\r') --end;
    offsets[n] = pos;
    lengths[n] = end - pos;
    ++n;
    pos = eol < size ? eol + 1 : size;
  }
  return n;
}

// Pack selected records into a zero-padded [n, extent] row-major matrix.
// start_offset skips leading bytes of each record (reference
// record_start_offset semantics); bytes past a record's length are zero.
void pack_records(const uint8_t* data, int64_t data_size,
                  const int64_t* offsets, const int64_t* lengths, int64_t n,
                  int64_t extent, int64_t start_offset, uint8_t* out) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; ++i) {
    uint8_t* row = out + i * extent;
    int64_t off = offsets[i] + start_offset;
    int64_t len = lengths[i] - start_offset;
    if (len > extent) len = extent;
    if (off < 0 || len <= 0 || off >= data_size) {
      std::memset(row, 0, extent);
      continue;
    }
    if (off + len > data_size) len = data_size - off;
    std::memcpy(row, data + off, len);
    if (len < extent) std::memset(row + len, 0, extent - len);
  }
}

}  // extern "C"
