"""Reproducible build of the native kernel library.

`_libframing.so` is compiled from framing.cpp + columnar.cpp (sharing
decode_cells.h) with one fixed flag set, by exactly one code path: the
lazy first-use build in `native.__init__` and this CLI both call
`build()` here, so "rebuilt by hand" and "rebuilt implicitly" cannot
drift apart.

    python -m cobrix_tpu.native.build            # rebuild if stale
    python -m cobrix_tpu.native.build --force    # rebuild regardless
    python -m cobrix_tpu.native.build --check    # exit 1 if stale/absent

The library is cached next to the sources and considered stale whenever
ANY source or header is newer than it (a header-only edit must trigger a
rebuild — both translation units inline its cell math).
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Tuple

HERE = os.path.dirname(os.path.abspath(__file__))
SOURCES = ["framing.cpp", "columnar.cpp"]
HEADERS = ["decode_cells.h"]
LIB_NAME = "_libframing.so"
FLAGS = ["-O3", "-shared", "-fPIC", "-fopenmp", "-std=c++17"]
BUILD_TIMEOUT_S = 240


def lib_path() -> str:
    return os.path.join(HERE, LIB_NAME)


def source_paths() -> List[str]:
    return [os.path.join(HERE, s) for s in SOURCES]


def command() -> List[str]:
    cxx = os.environ.get("COBRIX_CXX", "g++")
    return [cxx, *FLAGS, *source_paths(), "-o", lib_path()]


def needs_build() -> bool:
    lib = lib_path()
    if not os.path.exists(lib):
        return True
    lib_mtime = os.path.getmtime(lib)
    for name in SOURCES + HEADERS:
        p = os.path.join(HERE, name)
        if os.path.exists(p) and os.path.getmtime(p) > lib_mtime:
            return True
    return False


def build() -> Tuple[bool, str]:
    """(ok, message). Compiles to a temp path and renames so a crashed
    build can never leave a torn .so for the next import to dlopen."""
    cmd = command()
    tmp = lib_path() + ".tmp"
    cmd = cmd[:-1] + [tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True,
                              timeout=BUILD_TIMEOUT_S)
    except (OSError, subprocess.TimeoutExpired) as exc:
        return False, f"native build failed to run ({exc})"
    if proc.returncode != 0:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False, ("native build failed:\n"
                       + proc.stderr.decode(errors="replace"))
    os.replace(tmp, lib_path())
    return True, " ".join(command())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--force", action="store_true",
                    help="rebuild even when the library looks fresh")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when the library is stale or absent, "
                         "without building")
    args = ap.parse_args(argv)
    stale = needs_build()
    if args.check:
        print(f"{lib_path()}: {'STALE/ABSENT' if stale else 'fresh'}")
        return 1 if stale else 0
    if not stale and not args.force:
        print(f"{lib_path()}: fresh (use --force to rebuild)")
        return 0
    ok, message = build()
    print(message, file=sys.stdout if ok else sys.stderr)
    if ok:
        print(f"built {lib_path()}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
