"""Benchmark: exp3 multisegment-wide decode throughput (MB/s).

Reproduces the reference's north-star workload (BASELINE.md exp3:
RDW variable-length multisegment file; wide 'C' segments with
STRATEGY-DETAIL OCCURS 2000 of COMP + COMP-3, 16,068-byte records,
interleaved with 64-byte 'P' contact segments). Reference single-core
throughput is ~8.0 MB/s (performance/exp3_multiseg_wide.csv); the
vs_baseline field is measured MB/s / 8.0.

Pipeline timed end-to-end: RDW record framing (host) -> per-segment batch
packing (host) -> columnar kernel decode (device) -> typed column arrays
on host. Data generation and jit warmup are excluded; row/JSON
materialization is excluded (columnar output is the product, as Parquet
columns are for the reference).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_MBPS = 8.0  # exp3, 1 executor (BASELINE.md)


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _probe_jax(timeout: int = 60) -> bool:
    """Check device init in a subprocess first — a wedged TPU tunnel would
    hang this process forever."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run(backend: str, mb_target: float) -> dict:
    from cobrix_tpu.reader.parameters import (
        MultisegmentParameters,
        ReaderParameters,
    )
    from cobrix_tpu.reader.var_len_reader import VarLenReader
    from cobrix_tpu.testing.generators import EXP3_COPYBOOK, generate_exp3

    # same reader configuration as the reference exp3 run (SparkCobolApp
    # with redefine-segment-id-map): the copybook is parsed with
    # STATIC-DETAILS / CONTACTS marked as segment redefines
    params = ReaderParameters(
        is_record_sequence=True,
        multisegment=MultisegmentParameters(
            segment_id_field="SEGMENT-ID",
            segment_id_redefine_map={"C": "STATIC_DETAILS", "P": "CONTACTS"}))
    reader = VarLenReader(EXP3_COPYBOOK, params)

    # ~1/3 of records are 16 KB 'C' segments, the rest 64-byte contacts
    est_per_record = 16072 * 0.33 + 68 * 0.67
    n_records = max(64, int(mb_target * 1024 * 1024 / est_per_record))
    t0 = time.perf_counter()
    raw = generate_exp3(n_records, seed=100)
    _log(f"generated {len(raw) / 1e6:.1f} MB, {n_records} records "
         f"in {time.perf_counter() - t0:.1f}s")

    from cobrix_tpu import native

    total_mb = len(raw) / (1024 * 1024)
    _log(f"native framing: {native.available()}")

    def decode_all():
        # native RDW scan (VRLRecordReader loop in C++) + in-place decode
        # of numeric groups from the file image (decode_raw skips the
        # wide-record pack copy; only the narrow string prefix is packed)
        offsets, lengths = native.rdw_scan(raw, big_endian=False)
        out = []
        for seg_len in np.unique(lengths):
            # segment discrimination by record length (C records carry the
            # 2000-element strategy block; P contacts are 60 bytes)
            pos = np.nonzero(lengths == seg_len)[0]
            active = "CONTACTS" if seg_len < 1000 else "STATIC_DETAILS"
            dec = reader._decoder_for_segment(active, backend)
            out.append(dec.decode_raw(raw, offsets[pos], lengths[pos]))
        return out

    # warmup (jit compile; excluded from timing)
    t0 = time.perf_counter()
    decode_all()
    _log(f"warmup (incl. compile): {time.perf_counter() - t0:.1f}s")

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        decoded = decode_all()
        times.append(time.perf_counter() - t0)
    best = min(times)
    n_rows = sum(d.n_records for d in decoded)
    mbps = total_mb / best
    _log(f"runs: {[f'{t:.2f}s' for t in times]}; {n_rows} records; "
         f"{mbps:.1f} MB/s; {n_rows / best:.0f} rec/s")
    return {
        "metric": f"exp3_multiseg_wide_decode_{backend}",
        "value": round(mbps, 2),
        "unit": "MB/s",
        "vs_baseline": round(mbps / BASELINE_MBPS, 2),
    }


def run_exp2_side_metric(mb_target: float) -> None:
    """exp2 narrow-record profile (64-68 B/rec) as a stderr side metric:
    framing/segment-id bound rather than decode bound. Reference exp2
    single-core baseline: ~9.4 MB/s (BASELINE.md)."""
    import numpy as np

    from cobrix_tpu import native
    from cobrix_tpu.reader.parameters import (
        MultisegmentParameters,
        ReaderParameters,
    )
    from cobrix_tpu.reader.var_len_reader import VarLenReader
    from cobrix_tpu.reader.vrl_reader import resolve_segment_id_field
    from cobrix_tpu.testing.generators import EXP2_COPYBOOK, generate_exp2

    params = ReaderParameters(
        is_record_sequence=True,
        multisegment=MultisegmentParameters(
            segment_id_field="SEGMENT-ID",
            segment_id_redefine_map={"C": "STATIC_DETAILS",
                                     "P": "CONTACTS"}))
    reader = VarLenReader(EXP2_COPYBOOK, params)
    n_records = max(1000, int(mb_target * 1024 * 1024 / 66))
    raw = generate_exp2(n_records, seed=100)
    mb = len(raw) / (1024 * 1024)
    seg_field = resolve_segment_id_field(params, reader.copybook)

    def decode_all():
        offsets, lengths = native.rdw_scan(raw, big_endian=False)
        sids = np.asarray(reader._segment_ids_vectorized(
            raw, offsets, lengths, seg_field), dtype=object)
        for active, sid in (("STATIC_DETAILS", "C"), ("CONTACTS", "P")):
            pos = np.nonzero(sids == sid)[0]
            reader._decoder_for_segment(active, "numpy").decode_raw(
                raw, offsets[pos], lengths[pos])
        return len(offsets)

    n = decode_all()  # warmup
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        decode_all()
        times.append(time.perf_counter() - t0)
    best = min(times)
    _log(f"side metric exp2_multiseg_narrow: {mb / best:.1f} MB/s, "
         f"{n / best / 1e6:.2f} M rec/s (baseline 9.4 MB/s -> "
         f"{mb / best / 9.4:.1f}x)")


def main():
    mb_target = float(os.environ.get("BENCH_MB", "64"))
    backend = os.environ.get("BENCH_BACKEND", "")
    if not backend:
        # calibrate: time both backends on a small slice and run the full
        # benchmark on the faster one. On hosts with a locally-attached TPU
        # the jax path wins; over a remote/tunneled device the transfer
        # link caps it and the native host kernels win.
        candidates = ["numpy"]
        if _probe_jax():
            candidates.append("jax")
        else:
            _log("WARNING: jax device init timed out; numpy only")
        if len(candidates) == 1:
            backend = candidates[0]
        else:
            cal_mb = min(mb_target, 16.0)
            scores, results = {}, {}
            for cand in candidates:
                try:
                    results[cand] = run(cand, cal_mb)
                    scores[cand] = results[cand]["value"]
                except Exception as exc:  # pragma: no cover
                    _log(f"calibration {cand} failed: {exc}")
                    scores[cand] = 0.0
            backend = max(scores, key=scores.get)
            _log(f"calibration: {scores}; running full bench on {backend}")
            if cal_mb == mb_target and backend in results:
                _exp2_side_metric(mb_target)
                print(json.dumps(results[backend]), flush=True)
                return
    _exp2_side_metric(mb_target)
    result = run(backend, mb_target)
    print(json.dumps(result), flush=True)


def _exp2_side_metric(mb_target: float) -> None:
    try:
        run_exp2_side_metric(min(mb_target, 40.0))
    except Exception as exc:  # side metric must never break the bench
        _log(f"exp2 side metric failed: {exc}")


if __name__ == "__main__":
    main()
