"""Unified scan telemetry (cobrix_tpu.obs): span parent/child integrity
across threads and forked multihost workers, Chrome-trace JSON schema
validity, Prometheus exposition format, progress-callback monotonicity,
and the tracing-off zero-overhead fast path."""
import json
import re
import threading
import time

import pytest

from cobrix_tpu import prometheus_text, read_cobol
from cobrix_tpu.obs import (
    MetricsRegistry,
    ObsContext,
    ProgressTracker,
    Tracer,
    activate,
    current,
    maybe_span,
)
from cobrix_tpu.profiling import ReadMetrics, StageTimes
from cobrix_tpu.testing.generators import (
    EXP1_COPYBOOK,
    EXP2_COPYBOOK,
    generate_exp1,
    generate_exp2,
)
from tests.util import hard_timeout

EXP2_KW = dict(copybook_contents=EXP2_COPYBOOK, is_record_sequence="true",
               segment_field="SEGMENT-ID",
               redefine_segment_id_map="STATIC-DETAILS => C",
               redefine_segment_id_map_1="CONTACTS => P",
               segment_id_prefix="OBS")


def _spans_by_id(events):
    spans = [e for e in events if e.get("ph") == "X"]
    return spans, {e["args"]["span_id"]: e for e in spans}


# -- trace spans: threads --------------------------------------------------

def test_pipelined_trace_span_parentage(tmp_path):
    """Chunk spans parent to the scan root; stage spans recorded on the
    pipeline's worker/assembler THREADS parent to their chunk span — the
    parent relationship survives crossing the thread pool."""
    p = tmp_path / "exp1.dat"
    p.write_bytes(generate_exp1(400, seed=21).tobytes())
    tf = str(tmp_path / "scan.trace.json")
    out = read_cobol(str(p), copybook_contents=EXP1_COPYBOOK,
                     pipeline_workers="2", chunk_size_mb="0.05",
                     trace_file=tf)
    assert len(out) == 400
    events = json.load(open(tf))["traceEvents"]
    spans, by_id = _spans_by_id(events)
    roots = [e for e in spans if e["cat"] == "scan"]
    assert len(roots) == 1
    root_id = roots[0]["args"]["span_id"]
    chunks = [e for e in spans if e["cat"] == "chunk"]
    assert len(chunks) >= 2  # the tiny chunk size forced a multi-chunk plan
    assert all(c["args"]["parent_id"] == root_id for c in chunks)
    chunk_ids = {c["args"]["span_id"] for c in chunks}
    stages = [e for e in spans if e["cat"] == "stage"]
    assert stages and all(s["args"]["parent_id"] in chunk_ids
                          for s in stages)
    # stages ran on more than one thread, yet parentage held
    assert len({(e["pid"], e["tid"]) for e in stages}) >= 2
    # the read's metrics carry the span list too
    assert out.metrics.spans is not None
    assert out.metrics.as_dict()["span_count"] == len(out.metrics.spans)


def test_sequential_var_len_trace_has_shard_spans(tmp_path):
    p = tmp_path / "exp2.dat"
    p.write_bytes(generate_exp2(3000, seed=22))
    tf = str(tmp_path / "scan.trace.json")
    out = read_cobol(str(p), input_split_records="800", trace_file=tf,
                     **EXP2_KW)
    assert len(out) == 3000
    events = json.load(open(tf))["traceEvents"]
    spans, _ = _spans_by_id(events)
    root_id = [e for e in spans if e["cat"] == "scan"][0]["args"]["span_id"]
    shards = [e for e in spans if e["cat"] == "shard"]
    assert len(shards) >= 3
    assert all(s["args"]["parent_id"] == root_id for s in shards)


# -- trace spans: forked multihost workers ---------------------------------

def test_multihost_trace_merges_worker_spans(tmp_path):
    """One multihost scan -> ONE Chrome trace containing spans from >= 2
    forked worker processes, shard spans parented to the parent's scan
    root and stage spans to their shard (the acceptance criterion)."""
    with hard_timeout(240, "multihost trace"):
        p = tmp_path / "exp2.dat"
        p.write_bytes(generate_exp2(4000, seed=23))
        tf = str(tmp_path / "scan.trace.json")
        out = read_cobol(str(p), hosts="2", input_split_records="800",
                         trace_file=tf, **EXP2_KW)
        assert len(out) == 4000
        events = json.load(open(tf))["traceEvents"]
        spans, _ = _spans_by_id(events)
        root = [e for e in spans if e["cat"] == "scan"][0]
        shard_spans = [e for e in spans if e["cat"] == "shard"]
        worker_pids = {e["pid"] for e in shard_spans}
        assert len(worker_pids) >= 2, "spans from fewer than 2 workers"
        assert root["pid"] not in worker_pids  # workers are forks
        root_id = root["args"]["span_id"]
        assert all(s["args"]["parent_id"] == root_id for s in shard_spans)
        shard_ids = {s["args"]["span_id"] for s in shard_spans}
        stages = [e for e in spans if e["cat"] == "stage"]
        assert stages and all(s["args"]["parent_id"] in shard_ids
                              for s in stages)
        # clock-offset corrected: worker spans sit inside the scan window
        t0, t1 = root["ts"], root["ts"] + root["dur"]
        slack = 0.05e6  # 50ms of cross-process clock-pair jitter
        for s in shard_spans:
            assert t0 - slack <= s["ts"] <= t1 + slack
        # supervisor events landed as instants
        assert any(e["ph"] == "i" and e["name"] == "dispatch"
                   for e in events)


def test_clock_offset_correction_unit():
    """merge() maps a worker's perf timeline onto the host's using the
    shared wall clock: a worker whose perf_counter base differs by X
    lands exactly X later/earlier after correction."""
    host = Tracer()
    t = time.perf_counter()
    spans = [(123, host.root_id, "shard", "shard", "X", t, t + 1.0,
              9999, 1, None)]
    # fabricate a worker whose perf clock reads 100s BEHIND the host's
    skew = 100.0
    worker_clock = (time.time(), time.perf_counter() - skew)
    host.merge(spans, worker_clock)
    merged = [s for s in host.spans if s[0] == 123][0]
    assert abs(merged[5] - (t + skew)) < 0.05


def test_span_ids_unique_across_tracers_in_one_process():
    """Multiple Tracers in one process (one per shard in a multihost
    worker) share the process-wide id counter — ids never collide."""
    a, b = Tracer(), Tracer()
    ids = {a.root_id, b.root_id}
    for _ in range(50):
        ids.add(a.new_id())
        ids.add(b.new_id())
    assert len(ids) == 102


def test_multihost_worker_metrics_ship_home(tmp_path):
    """Worker-side record-length observations and compile-cache events
    reach the parent's registry and the read's plan_cache — hosts>1
    reads are not blind spots in the fleet metrics."""
    from cobrix_tpu.obs import scan_metrics

    with hard_timeout(240, "multihost metrics"):
        before = scan_metrics()["record_length"].snapshot()["count"]
        p = tmp_path / "exp2.dat"
        p.write_bytes(generate_exp2(3000, seed=33))
        out = read_cobol(str(p), hosts="2", input_split_records="800",
                         **EXP2_KW)
        after = scan_metrics()["record_length"].snapshot()["count"]
        assert after - before >= 3000  # every framed record counted
        stats = out.metrics.as_dict()["plan_cache"]
        # the workers' per-shard decoder lookups came home
        assert stats["decoder_hits"] + stats["decoder_misses"] >= 1


# -- Chrome-trace schema ---------------------------------------------------

def test_chrome_trace_schema_validity(tmp_path):
    p = tmp_path / "exp1.dat"
    p.write_bytes(generate_exp1(64, seed=24).tobytes())
    tf = str(tmp_path / "scan.trace.json")
    read_cobol(str(p), copybook_contents=EXP1_COPYBOOK, trace_file=tf)
    doc = json.load(open(tf))
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for e in doc["traceEvents"]:
        assert e["ph"] in ("X", "i", "M")
        assert isinstance(e["pid"], int)
        if e["ph"] == "M":
            assert e["name"] in ("process_name", "thread_name")
            continue
        assert isinstance(e["name"], str) and e["name"]
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        if e["ph"] == "X":
            assert e["dur"] >= 0
        args = e["args"]
        assert "span_id" in args and "parent_id" in args


# -- Prometheus exposition -------------------------------------------------

def test_prometheus_exposition_format(tmp_path):
    p = tmp_path / "exp1.dat"
    p.write_bytes(generate_exp1(32, seed=25).tobytes())
    read_cobol(str(p), copybook_contents=EXP1_COPYBOOK)
    text = prometheus_text()
    assert re.search(r"^# TYPE cobrix_scans_total counter$", text, re.M)
    assert re.search(r"^cobrix_scans_total \d+$", text, re.M)
    assert re.search(r"^# TYPE cobrix_record_length_bytes histogram$",
                     text, re.M)
    assert re.search(
        r'^cobrix_record_length_bytes_bucket\{le="\+Inf"\} \d+$',
        text, re.M)
    assert re.search(r"^cobrix_record_length_bytes_count \d+$", text, re.M)
    # labeled counter sample syntax
    assert re.search(r'^cobrix_plan_cache_events_total\{cache="parse",'
                     r'result="(hit|miss)(es)?"\} \d+$', text, re.M) \
        or "cobrix_plan_cache_events_total{" in text
    # every non-comment line is `name[{labels}] value`
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        assert re.match(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? '
                        r'-?\d+(\.\d+)?([eE][+-]?\d+)?$', line), line


def test_histogram_bucket_cumulativity():
    reg = MetricsRegistry()
    h = reg.histogram("h_test", "t", buckets=(1, 2, 4))
    for v in (0.5, 1.5, 3, 8, 0.1):
        h.observe(v)
    lines = reg.exposition().splitlines()
    buckets = [int(ln.rsplit(" ", 1)[1]) for ln in lines
               if ln.startswith("h_test_bucket")]
    assert buckets == sorted(buckets)       # cumulative, nondecreasing
    assert buckets[-1] == 5                 # +Inf sees every observation
    assert h.quantile(0.5) is not None


# -- live progress ---------------------------------------------------------

def test_progress_callback_monotonic_pipelined(tmp_path):
    p = tmp_path / "exp1.dat"
    p.write_bytes(generate_exp1(400, seed=26).tobytes())
    snaps = []
    out = read_cobol(str(p), copybook_contents=EXP1_COPYBOOK,
                     pipeline_workers="2", chunk_size_mb="0.05",
                     progress_callback=snaps.append,
                     progress_interval_s="0")
    assert len(snaps) >= 2
    _assert_monotonic(snaps)
    final = snaps[-1]
    assert final.done and final.records_done == len(out)
    assert final.bytes_done == final.bytes_total > 0
    assert final.chunks_done == final.chunks_total >= 2
    assert final.chunks_inflight == 0
    assert final.stage_busy_s.get("decode", 0) > 0


def test_progress_callback_multihost(tmp_path):
    with hard_timeout(240, "multihost progress"):
        p = tmp_path / "exp2.dat"
        p.write_bytes(generate_exp2(3000, seed=27))
        snaps = []
        out = read_cobol(str(p), hosts="2", input_split_records="800",
                         progress_callback=snaps.append,
                         progress_interval_s="0", **EXP2_KW)
        assert snaps and snaps[-1].done
        _assert_monotonic(snaps)
        assert snaps[-1].records_done == len(out) == 3000
        assert snaps[-1].chunks_done >= 3


def test_progress_callback_exception_never_breaks_scan(tmp_path):
    p = tmp_path / "exp1.dat"
    p.write_bytes(generate_exp1(16, seed=28).tobytes())

    def boom(progress):
        raise RuntimeError("broken progress bar")

    out = read_cobol(str(p), copybook_contents=EXP1_COPYBOOK,
                     progress_callback=boom, progress_interval_s="0")
    assert len(out) == 16


def test_progress_bytes_reach_total_on_var_len_tail_shard(tmp_path):
    """The last index shard of a var-len file is an open range
    (offset_to=-1): its bytes must still count, so bytes_done converges
    to bytes_total instead of plateauing below it."""
    p = tmp_path / "exp2.dat"
    raw = generate_exp2(3000, seed=34)
    p.write_bytes(raw)
    snaps = []
    read_cobol(str(p), input_split_records="800",
               progress_callback=snaps.append, progress_interval_s="0",
               **EXP2_KW)
    final = snaps[-1]
    assert final.bytes_total == len(raw)
    assert final.bytes_done == final.bytes_total


def test_trace_file_unwritable_fails_before_scan(tmp_path):
    p = tmp_path / "exp1.dat"
    p.write_bytes(generate_exp1(16, seed=35).tobytes())
    with pytest.raises(ValueError, match="trace_file"):
        read_cobol(str(p), copybook_contents=EXP1_COPYBOOK,
                   trace_file=str(tmp_path / "no" / "such" / "t.json"))


def test_failed_scan_still_writes_partial_trace_and_final_progress(
        tmp_path):
    """A scan that raises under fail_fast still flushes telemetry: the
    done=True progress snapshot fires and the partial trace (the thing
    that diagnoses the failure) lands on disk."""
    p = tmp_path / "bad.dat"
    p.write_bytes(generate_exp1(4, seed=36).tobytes() + b"\x00\x01\x02")
    tf = str(tmp_path / "fail.trace.json")
    snaps = []
    with pytest.raises(ValueError):
        read_cobol(str(p), copybook_contents=EXP1_COPYBOOK,
                   trace_file=tf, progress_callback=snaps.append,
                   progress_interval_s="0")
    assert snaps and snaps[-1].done
    doc = json.load(open(tf))
    assert doc["traceEvents"]


def test_progress_callback_must_be_callable(tmp_path):
    p = tmp_path / "exp1.dat"
    p.write_bytes(generate_exp1(16, seed=29).tobytes())
    with pytest.raises(ValueError, match="progress_callback"):
        read_cobol(str(p), copybook_contents=EXP1_COPYBOOK,
                   progress_callback="not-a-function")


def _assert_monotonic(snaps):
    for a, b in zip(snaps, snaps[1:]):
        assert b.bytes_done >= a.bytes_done
        assert b.records_done >= a.records_done
        assert b.chunks_done >= a.chunks_done
        assert b.chunks_failed >= a.chunks_failed
        assert b.elapsed_s >= a.elapsed_s
        assert b.chunks_inflight >= 0


def test_progress_tracker_thread_safety():
    tracker = ProgressTracker(lambda p: None, bytes_total=8000,
                              chunks_total=80, min_interval_s=0.0)

    def work():
        for _ in range(20):
            tracker.chunk_started()
            tracker.chunk_done(bytes_done=100, records=10)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tracker.finish()
    snap = tracker.snapshot(done=True)
    assert snap.chunks_done == 80
    assert snap.bytes_done == 8000
    assert snap.records_done == 800


def test_progress_retried_chunk_counts_once_without_tracer():
    """A chunk that fails once and succeeds on re-dispatch is ONE chunk
    to the progress tracker even with tracing off (regression: the
    first-dispatch sentinel used to be the trace span id, so without a
    tracer every retry re-fired chunk_started and inflight drifted)."""
    from cobrix_tpu.engine.pipeline import PipelineExecutor

    snaps = []
    tracker = ProgressTracker(snaps.append, min_interval_s=0.0)
    failed_once = []

    def flaky(payload):
        if not failed_once:
            failed_once.append(1)
            raise RuntimeError("transient")
        return payload

    ctx = ObsContext(progress=tracker)
    with activate(ctx):
        ex = PipelineExecutor(2, chunk_retries=1)
    results = ex.run([((lambda: 1), flaky), ((lambda: 2), (lambda p: p))])
    assert results == [1, 2]
    snap = tracker.snapshot()
    assert snap.chunks_done == 2          # not 2 + a phantom retry
    assert snap.chunks_inflight == 0      # no drift from the retry
    _assert_monotonic(snaps)


# -- tracing-off fast path -------------------------------------------------

def test_tracing_off_zero_allocation_fast_path(tmp_path):
    """With tracing off, maybe_span returns ONE shared null context (no
    allocation per call) and a read records no spans at all."""
    assert maybe_span(None, "a") is maybe_span(None, "b")
    st = StageTimes()             # no tracer attached
    with st.timed("read"):
        pass
    assert st.tracer is None
    p = tmp_path / "exp1.dat"
    p.write_bytes(generate_exp1(16, seed=30).tobytes())
    out = read_cobol(str(p), copybook_contents=EXP1_COPYBOOK)
    assert out.metrics.spans is None
    assert out.metrics.tracer is None
    assert "span_count" not in out.metrics.as_dict()


# -- satellite regression: racy accumulations ------------------------------

def test_read_metrics_timings_accumulation_is_locked():
    """profiling._Stage routes through ReadMetrics.add_timing under a
    lock: concurrent accumulation from many threads loses nothing."""
    m = ReadMetrics()
    n_threads, n_iter = 8, 2000

    def work():
        for _ in range(n_iter):
            m.add_timing("scan", 0.001)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert m.timings_s["scan"] == pytest.approx(
        n_threads * n_iter * 0.001, rel=1e-6)


def test_cache_scope_isolated_between_concurrent_reads(tmp_path):
    """Two concurrent reads each see their OWN cache events (the old
    process-global delta attributed both reads' lookups to whichever
    finished last)."""
    p1 = tmp_path / "a.dat"
    p2 = tmp_path / "b.dat"
    p1.write_bytes(generate_exp1(32, seed=31).tobytes())
    p2.write_bytes(generate_exp1(32, seed=32).tobytes())
    read_cobol(str(p1), copybook_contents=EXP1_COPYBOOK)  # warm caches
    outs = [None, None]

    def read(i, path):
        outs[i] = read_cobol(path, copybook_contents=EXP1_COPYBOOK)

    threads = [threading.Thread(target=read, args=(0, str(p1))),
               threading.Thread(target=read, args=(1, str(p2)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for out in outs:
        stats = out.metrics.as_dict()["plan_cache"]
        # each read did exactly one parse lookup (a hit) — were the
        # counters still process-global deltas, one read would see both
        assert stats["parse_hits"] == 1
        assert stats["parse_misses"] == 0


def test_obs_context_thread_locality():
    ctx = ObsContext()
    seen = []
    with activate(ctx):
        assert current() is ctx
        t = threading.Thread(target=lambda: seen.append(current()))
        t.start()
        t.join()
    assert seen == [None]         # other threads are not contaminated
    assert current() is None      # deactivated on exit


# -- traceview smoke (the multihost sweep stays behind `slow`) -------------

def test_traceview_smoke_quick():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "tools/traceview.py", "--smoke"],
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


@pytest.mark.slow
def test_traceview_smoke_sweep():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "tools/traceview.py", "--smoke", "--sweep"],
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
