"""Profiling hooks: JAX profiler traces + structured read metrics.

The reference's observability is SLF4J logging around the scan
(CobolScanners.scala:51, IndexBuilder.scala:216 — per-partition offsets
and index counts). The TPU-native equivalents here:

- `profile_trace(dir)`: a context manager wrapping any read/decode in a
  `jax.profiler.trace` session — the artifact opens in TensorBoard/XProf
  and shows the fused kernel, transfers, and collectives on the device
  timeline. The bench writes one such artifact per run.
- `annotate(name)`: named TraceAnnotation spans used inside the decode
  paths (visible on the profiler timeline; ~free when no trace is on).
- `ReadMetrics`: per-read structured counters (files, shards, records,
  bytes, per-stage timings) attached to every CobolData as `.metrics`.
- `StageTimes`: thread-safe per-stage BUSY time accumulation for the
  pipelined execution engine (cobrix_tpu.engine) — wall time alone cannot
  attribute a pipeline win, because overlapped stages each burn close to
  the full wall on a busy pool; busy/wall is the overlap factor.

The host-side scan timeline (trace spans, Chrome-trace export, metrics
registry, live progress) lives in `cobrix_tpu.obs`; ReadMetrics carries
its per-read artifacts (`spans`, `plan_cache` via a per-read cache
scope) and publishes read totals into the default registry.
"""
from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional


@contextlib.contextmanager
def profile_trace(output_dir: str):
    """Capture a JAX profiler trace of everything inside the block into
    `output_dir` (TensorBoard-loadable). Falls back to a no-op if the
    profiler is unavailable (e.g. numpy-only environments)."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        yield
        return
    with jax.profiler.trace(output_dir):
        yield


_TRACE_ANNOTATION = None


def annotate(name: str):
    """Named span on the profiler timeline; no-op outside a trace. The
    TraceAnnotation class resolves once — this sits on per-block decode
    hot paths."""
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is None:
        try:
            import jax

            _TRACE_ANNOTATION = jax.profiler.TraceAnnotation
        except Exception:  # pragma: no cover
            _TRACE_ANNOTATION = False
    if _TRACE_ANNOTATION is False:  # pragma: no cover
        return contextlib.nullcontext()
    return _TRACE_ANNOTATION(name)


class StageTimes:
    """Per-stage busy-time accumulator shared by pipeline worker threads.

    `busy_s[stage]` is the SUM of time any thread spent inside that stage
    (read / frame / decode / assemble), so with N-way overlap the busy
    total exceeds the pipeline wall time — the ratio is the overlap
    factor reported in ReadMetrics. A plain dict read-modify-write races
    across threads; the lock makes each accumulation atomic."""

    __slots__ = ("_lock", "busy_s", "tracer")

    def __init__(self, tracer=None):
        self._lock = threading.Lock()
        self.busy_s: Dict[str, float] = {}
        # optional obs.Tracer: when set, every timed stage also lands on
        # the scan timeline as a span (parent = the thread's current
        # chunk/shard span). None costs one attribute check per stage.
        self.tracer = tracer

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            self.busy_s[name] = self.busy_s.get(name, 0.0) + seconds

    @contextlib.contextmanager
    def timed(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            self.add(name, t1 - t0)
            if self.tracer is not None:
                self.tracer.record_span(name, "stage", t0, t1)

    def as_dict(self) -> Dict[str, float]:
        with self._lock:
            return {k: round(v, 6) for k, v in self.busy_s.items()}


def timed_stage(stage_times: Optional[StageTimes], name: str):
    """`stage_times.timed(name)` or a no-op when no accumulator is wired
    (sequential reads pass None through the reader hot paths)."""
    if stage_times is None:
        return contextlib.nullcontext()
    return stage_times.timed(name)


class PassCounters:
    """Thread-safe named counters for native-pass accounting.

    Each increment records that one fused native kernel launch actually
    engaged (`fused_frame`, `fused_assembly`, `string_transcode`,
    `take_elided`, ...). asmcheck's quick mode asserts on these so a
    silent fallback to the multi-pass shape fails loudly instead of
    reading as a slowdown. Shared by reference: read-time threads reach
    it through the ObsContext, and post-read Arrow assembly through the
    reference each DecodedBatch captured at decode time (the same
    capture pattern field-cost attribution uses — sequential reads
    assemble Arrow after read_cobol returned and the context died)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


@dataclass
class ReadMetrics:
    """Structured per-read metrics (the IndexBuilder/CobolScanners log
    lines as data instead of log text)."""

    files: int = 0
    shards: int = 0
    records: int = 0
    bytes_read: int = 0
    backend: str = ""
    hosts: int = 1
    timings_s: Dict[str, float] = field(default_factory=dict)
    # pipelined execution: per-stage busy times (thread-summed) and the
    # executor's shape/overlap report ({workers, chunks, max_inflight,
    # peak_inflight, wall_s, busy_s, overlap}); None on sequential reads
    stage_busy: Optional[StageTimes] = None
    pipeline: Optional[dict] = None
    # distributed supervision events (multihost scheduler / pipeline
    # watchdog): re-dispatches, speculation won/wasted, timeouts, worker
    # deaths; None when the read ran unsupervised
    supervision: Optional[dict] = None
    # compile-cache activity DURING this read (copybook parse / field-plan
    # / code-page LUT hits and misses). Counted through a per-read
    # CacheStatsScope that every thread working for the read activates
    # (obs.context), so concurrent read_cobol calls attribute their own
    # lookups exactly — never each other's
    plan_cache: Optional[dict] = None
    # remote-storage io counters (block/index cache hits, prefetch
    # utilization, bytes fetched — cobrix_tpu.io); None when the read
    # never touched the io layer
    io: Optional[dict] = None
    # finished obs.Tracer span records when the read traced (trace_file
    # or an explicitly attached tracer); None otherwise
    spans: Optional[list] = None
    # query-pushdown pruning counters (records_scanned/records_pruned
    # by depth, bytes_skipped, selectivity — query/pushdown.
    # PushdownStats.as_dict); None when the read carried no filter.
    # In-process executions only: multihost workers prune in their own
    # processes and their counters stay there
    pushdown: Optional[dict] = None

    def __post_init__(self):
        from .io.stats import IoStats
        from .plan.cache import CacheStatsScope

        self._timings_lock = threading.Lock()
        self.cache_scope = CacheStatsScope()
        # per-read remote-IO counter bag, activated alongside the cache
        # scope on every thread working for this read (obs.context)
        self.io_stats = IoStats()
        # optional obs.Tracer for the read (set by read_cobol when
        # tracing is on); stage() timers double as scan-level spans
        self.tracer = None
        # per-field/kernel-group cost attribution
        # (obs.fieldcost.FieldCostAccumulator) — set by read_cobol when
        # the `field_costs` option (or explain=True) enables it; None
        # keeps every attribution timer site a no-op. Snapshots are
        # taken LIVE (field_costs/as_dict), not frozen at finalize:
        # sequential reads assemble Arrow after the read returns, and
        # the snapshot must include that work like the pipelined path's
        self.field_costs_acc = None
        # fused-native-pass engagement counters (always on — one locked
        # dict increment per kernel launch, nowhere near hot-loop cost)
        self.pass_counts = PassCounters()
        # root-span args dict + trace destination, kept so lazy
        # post-read assembly can fold its costs back into an already
        # written trace artifact (refresh_trace_field_costs)
        self._trace_root_args = None
        self._trace_file = ""

    def add_timing(self, name: str, seconds: float) -> None:
        """Accumulate wall time for one named stage. Locked: pipelined
        reads hit the same metrics object from multiple stage threads."""
        with self._timings_lock:
            self.timings_s[name] = (self.timings_s.get(name, 0.0)
                                    + seconds)

    def finalize(self, data, shards: int) -> None:
        """Attach this metrics object to a finished CobolData."""
        self.shards = max(self.shards, shards)
        self.records = len(data)
        self.plan_cache = dict(self.cache_scope.stats)
        if not self.io_stats.is_zero:
            self.io = self.io_stats.as_dict()
            self.io["prefetch_utilization"] = round(
                self.io_stats.prefetch_utilization, 3)
        if self.tracer is not None:
            root_args = {
                "files": self.files, "shards": self.shards,
                "records": self.records, "bytes": self.bytes_read,
                "backend": self.backend, "hosts": self.hosts}
            fc = self.field_costs
            if fc:
                # the trace artifact carries the cost table too, so
                # `tools/traceview.py --fields` works on a trace file
                # alone, no separate metrics dump needed
                root_args["field_costs"] = fc
            self.tracer.finish_root(args=root_args)
            self._trace_root_args = root_args
            self.spans = list(self.tracer.spans)
        self._publish_registry()
        data.metrics = self

    def refresh_trace_field_costs(self) -> None:
        """Fold the now-complete cost table back into the trace artifact.

        Sequential reads assemble Arrow (and transcode lazy strings)
        AFTER finalize wrote the trace, so a string-heavy traced read
        would otherwise ship a trace whose field_costs is missing or
        missing its assemble plane. Called from `to_arrow` when both
        attribution and `trace_file` were on: the root-span args dict is
        shared by reference with the recorded span, so updating it and
        rewriting (atomic) brings the artifact up to date. No-op for
        untraced / unattributed reads and safely repeatable."""
        if (self.tracer is None or not self._trace_file
                or self._trace_root_args is None):
            return
        fc = self.field_costs
        if not fc:
            return
        self._trace_root_args["field_costs"] = fc
        self.spans = list(self.tracer.spans)
        try:
            self.tracer.write_chrome_trace(self._trace_file)
        except OSError:
            import logging

            logging.getLogger(__name__).warning(
                "failed to refresh trace_file %r with field costs",
                self._trace_file, exc_info=True)

    @property
    def field_costs(self) -> Optional[dict]:
        """Live per-field cost table ({field -> kernel/decode_s/
        assemble_s/bytes/values}); None when attribution is off or
        nothing was attributed yet."""
        acc = self.field_costs_acc
        if acc is None or acc.is_zero:
            return None
        return acc.as_dict()

    def roofline(self) -> Optional[dict]:
        """Achieved scan bytes/s anchored to the calibrated host memory
        bandwidth (obs.roofline); None until both a calibration and a
        finished 'scan' timing exist. Never triggers a calibration."""
        from .obs.roofline import roofline_summary

        scan_s = self.timings_s.get("scan", 0.0)
        return roofline_summary(self.bytes_read, scan_s)

    def _publish_registry(self) -> None:
        """Fold this read into the process-global metrics registry
        (obs.metrics.default_registry): scan/bytes/records totals plus
        the read's cache events, so a Prometheus scrape sees the fleet
        aggregate without touching per-read objects."""
        from .obs.metrics import scan_metrics

        m = scan_metrics()
        m["scans"].inc()
        m["bytes"].inc(self.bytes_read)
        m["records"].inc(self.records)
        for key, count in (self.plan_cache or {}).items():
            if count:
                cache, _, result = key.rpartition("_")
                m["cache"].labels(cache=cache, result=result).inc(count)
        io = self.io or {}
        for plane in ("block", "index", "compress"):
            for result, label in (("hits", "hit"), ("misses", "miss")):
                count = io.get(f"{plane}_{result}", 0)
                if count:
                    m["io_cache"].labels(
                        plane=plane, result=label).inc(count)
            corrupt = io.get(f"{plane}_corrupt", 0)
            if corrupt:
                # detections ride IoStats during a read (multihost
                # workers merge theirs home) and reach Prometheus here,
                # exactly once per detection
                m["cache_corruption"].labels(plane=plane).inc(corrupt)
        for result, label in (("issued", "issued"), ("hits", "hit"),
                              ("waits", "wait"), ("unused", "unused")):
            count = io.get(f"prefetch_{result}", 0)
            if count:
                m["prefetch"].labels(result=label).inc(count)
        if io.get("bytes_fetched"):
            m["remote_bytes"].labels(source="backend").inc(
                io["bytes_fetched"])
        if io.get("bytes_from_cache"):
            m["remote_bytes"].labels(source="cache").inc(
                io["bytes_from_cache"])
        if io.get("compressed_bytes_in"):
            m["inflate_bytes"].labels(direction="in").inc(
                io["compressed_bytes_in"])
        if io.get("decompressed_bytes_out"):
            m["inflate_bytes"].labels(direction="out").inc(
                io["decompressed_bytes_out"])
        if io.get("inflate_s"):
            m["inflate_seconds"].inc(io["inflate_s"])
        if io.get("inflate_skipped"):
            m["inflate_skipped"].inc(io["inflate_skipped"])
        if io.get("bytes_from_peer"):
            # peer-tier EVENTS are counted live by PeerCacheTier; here
            # only the byte volume joins the backend/cache split
            m["remote_bytes"].labels(source="peer").inc(
                io["bytes_from_peer"])
        pd = self.pushdown or {}
        for depth in ("segment", "filter", "residual"):
            count = pd.get(f"records_pruned_{depth}", 0)
            if count:
                m["records_pruned"].labels(depth=depth).inc(count)
        if pd.get("bytes_skipped"):
            m["bytes_skipped"].inc(pd["bytes_skipped"])
        if pd.get("chunks_skipped"):
            m["chunks_skipped"].inc(pd["chunks_skipped"])
        roof = self.roofline()
        if roof is not None:
            m["roofline"].set(roof["fraction"])

    def as_dict(self) -> dict:
        out = {
            "files": self.files,
            "shards": self.shards,
            "records": self.records,
            "bytes_read": self.bytes_read,
            "backend": self.backend,
            "hosts": self.hosts,
            "timings_s": {k: round(v, 6) for k, v in self.timings_s.items()},
        }
        if self.stage_busy is not None:
            out["stage_busy_s"] = self.stage_busy.as_dict()
        if self.pipeline is not None:
            out["pipeline"] = self.pipeline
        if self.supervision is not None:
            out["supervision"] = self.supervision
        if self.plan_cache is not None:
            out["plan_cache"] = self.plan_cache
        if self.io is not None:
            out["io"] = self.io
        if self.pushdown is not None:
            out["pushdown"] = self.pushdown
        fc = self.field_costs
        if fc is not None:
            out["field_costs"] = fc
        passes = self.pass_counts.as_dict()
        if passes:
            out["native_passes"] = passes
        roof = self.roofline()
        if roof is not None:
            out["roofline"] = roof
        if self.spans is not None:
            out["span_count"] = len(self.spans)
        return out


class _Stage:
    def __init__(self, metrics: ReadMetrics, name: str):
        self.metrics = metrics
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        # locked accumulation: the pipelined executor runs stages of the
        # same read on multiple threads, and a bare dict read-modify-write
        # here loses increments under that interleaving
        self.metrics.add_timing(self.name, t1 - self._t0)
        tracer = self.metrics.tracer
        if tracer is not None:
            tracer.record_span(self.name, "phase", self._t0, t1)


def stage(metrics: Optional[ReadMetrics], name: str):
    """Accumulating wall-clock timer for one pipeline stage."""
    if metrics is None:
        return contextlib.nullcontext()
    return _Stage(metrics, name)
