"""Aggregates answered from statistics alone — when provably exact.

``dataset().aggregate()`` and ``count_rows()`` call in here first
(``use_stats=true``, no filter): if EVERY input file has a warm
profile under the read's exact configuration, ``count`` is the sum of
profiled record counts, ``min``/``max`` fold the per-chunk zone maps,
and ``sum`` folds the per-chunk exact sums (int/decimal kinds only —
float sums are order-dependent, never answered from stats). Anything
short of proof — a missing profile, a NaN-tainted chunk, an unknown
field, an inexact kind — returns None and the caller decodes, so a
stats answer is always byte-identical to the decoded one.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .profile import FileProfile

_UNPROVABLE = object()


def parse_specs(aggs: Sequence[str]) -> List[Tuple[str, Optional[str]]]:
    """``["count", "min:FIELD", ...]`` -> ``[(fn, field|None), ...]``
    (validated; the one spelling both the stats and decode paths
    share)."""
    out: List[Tuple[str, Optional[str]]] = []
    for spec in aggs:
        fn, sep, field = str(spec).partition(":")
        fn = fn.strip().lower()
        field = field.strip()
        if fn == "count" and not field:
            out.append(("count", None))
            continue
        if fn in ("min", "max", "sum") and sep and field:
            out.append((fn, field))
            continue
        raise ValueError(
            f"unsupported aggregate spec {spec!r} (use 'count', "
            f"'min:FIELD', 'max:FIELD', or 'sum:FIELD')")
    if not out:
        raise ValueError("aggregate() needs at least one spec")
    return out


def resolve_leaf(copybook, name: str) -> Optional[str]:
    """The profile key for an aggregate field reference (the same
    copybook resolution the filter binder uses), or None."""
    from ..copybook.ast import Group

    try:
        st = copybook.get_field_by_name(name)
    except (KeyError, ValueError):
        return None
    return None if isinstance(st, Group) else st.name


def load_all_profiles(files, copybook_contents,
                      params) -> Optional[List[FileProfile]]:
    """One profile per input file under this exact configuration — or
    None when ANY file lacks one (partial coverage cannot answer a
    whole-read aggregate)."""
    from ..plan.cache import parse_fingerprint
    from ..reader.stream import normalize_local
    from .collect import bump_overhead, profiling_eligibility
    from .store import StatsStore, local_fingerprint, \
        stats_config_fingerprint

    bump_overhead()
    if profiling_eligibility(files, params, "numpy") is not None:
        return None
    try:
        store = StatsStore(params.cache_dir)
    except OSError:
        return None
    config_fp = stats_config_fingerprint(
        parse_fingerprint(copybook_contents, params), params)
    profiles: List[FileProfile] = []
    for path in files:
        local = normalize_local(path)
        fingerprint = local_fingerprint(local)
        if fingerprint is None:
            return None
        profile = store.load(local, fingerprint, config_fp)
        if profile is None:
            return None
        profiles.append(profile)
    return profiles


def _fold_min_max(profiles: List[FileProfile], leaf: str, fn: str):
    best = None
    non_null = 0
    for profile in profiles:
        if leaf not in profile.field_kinds:
            return _UNPROVABLE
        for chunk in profile.chunks:
            fs = chunk.fields.get(leaf)
            if fs is None:
                return _UNPROVABLE
            present = chunk.records - fs.null_count
            if present <= 0:
                continue
            if fs.min is None:
                return _UNPROVABLE  # NaN taint / unknown zone map
            non_null += present
            value = fs.min if fn == "min" else fs.max
            if best is None:
                best = value
            else:
                best = min(best, value) if fn == "min" \
                    else max(best, value)
    return best if non_null else None  # SQL NULL over no values


def _fold_sum(profiles: List[FileProfile], leaf: str):
    total = None
    non_null = 0
    for profile in profiles:
        kind = profile.field_kinds.get(leaf)
        if kind not in ("int", "decimal"):
            return _UNPROVABLE  # float sums are not exactly foldable
        for chunk in profile.chunks:
            fs = chunk.fields.get(leaf)
            if fs is None or fs.sum is None:
                return _UNPROVABLE
            non_null += chunk.records - fs.null_count
            total = fs.sum if total is None else total + fs.sum
    return total if non_null else None


def aggregates_from_profiles(profiles: List[FileProfile], copybook,
                             specs: Sequence[Tuple[str, Optional[str]]]
                             ) -> Optional[Dict[str, object]]:
    """Every requested aggregate from statistics alone, keyed by its
    original spec spelling — or None when any one is unprovable (all
    or nothing: mixing stats and decode answers in one call would make
    the provenance unauditable)."""
    out: Dict[str, object] = {}
    for fn, field in specs:
        if fn == "count":
            out["count"] = sum(p.total_records for p in profiles)
            continue
        leaf = resolve_leaf(copybook, field)
        if leaf is None:
            return None
        value = (_fold_sum(profiles, leaf) if fn == "sum"
                 else _fold_min_max(profiles, leaf, fn))
        if value is _UNPROVABLE:
            return None
        out[f"{fn}:{field}"] = value
    return out
