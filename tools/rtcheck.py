"""Round-trip property check: copybook-driven encode vs the readers.

The encoder (cobrix_tpu.encode) claims byte-compatibility with the
decode path. This check enforces two properties end to end through
`encode_file` -> `read_cobol` -> `CobolData.to_ebcdic`:

  P1 (value round-trip)  decode(encode(body)) == body for every body
     drawn from the canonical value domain (testing/genspec.py);
  P2 (byte stability)    re-encoding the decoded rows reproduces the
     original file byte for byte;
  P3 (alias canonicalization)  RAW bytes — including duplicate-glyph
     alias bytes the encoder can never emit — reach a canonical fixed
     point after ONE decode→encode round: the re-encoded file decodes
     to the same rows and re-encodes to the same bytes (deterministic
     lowest-byte-wins inversion on every builtin code page).

Quick mode runs a deterministic seed matrix over both framings (fixed
and RDW) in a few seconds — tier-1 runs it via tests/test_roundtrip.py.
`--sweep N` fuzzes N random copybooks (default 120) with fresh random
bodies each; any failure is SHRUNK to a minimal (copybook, record)
reproduction before printing, so a red run ends with a paste-able repro.

    python tools/rtcheck.py                # quick deterministic matrix
    python tools/rtcheck.py --sweep 150    # fuzz 150 random copybooks
    python tools/rtcheck.py --seed 42      # reproduce one sweep case

Exit code 0 = both properties hold everywhere; 1 = any failure.
"""
from __future__ import annotations

import argparse
import os
import random
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _read_back(spec, data: bytes, framing: str):
    from cobrix_tpu import read_cobol

    with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
        f.write(data)
        path = f.name
    try:
        out = read_cobol(path, **spec.read_options(framing))
        rows = out.to_rows()
        rebytes = out.to_ebcdic(
            framing=framing,
            variable_size_occurs=spec.has_depending)
        return rows, rebytes
    finally:
        os.unlink(path)


def roundtrip_failure(spec, bodies, framing: str):
    """None if both properties hold, else a short failure tag."""
    from cobrix_tpu.encode import encode_file

    data = encode_file(spec.copybook_text, bodies,
                       **spec.encode_options(framing))
    rows, rebytes = _read_back(spec, data, framing)
    if [list(b) for b in rows] != [list(b) for b in bodies]:
        for i, (got, want) in enumerate(zip(rows, bodies)):
            if list(got) != list(want):
                return (f"P1 value mismatch at record {i}: "
                        f"decoded {got!r} != encoded {want!r}")
        return (f"P1 record count mismatch: decoded {len(rows)} "
                f"!= encoded {len(bodies)}")
    if rebytes != data:
        n = min(len(rebytes), len(data))
        at = next((i for i in range(n) if rebytes[i] != data[i]), n)
        return (f"P2 byte instability at offset {at}: re-encode gives "
                f"{len(rebytes)} bytes vs {len(data)} original")
    return None


ALIAS_CODE_PAGES = ("common", "common_extended", "cp037",
                    "cp037_extended", "cp500", "cp500_extended",
                    "cp875", "cp1047", "cp1047_extended")

# the P1/P2 fuzz rotation: every Latin-1 page takes seeds (cp875's
# Greek alphabet needs genspec's safe-alphabet filtering, exercised by
# the alias matrix instead)
FUZZ_CODE_PAGES = ("common", "cp037", "cp500", "cp1047")


def alias_roundtrip_failure(code_page: str, raw: bytes,
                            width: int = 16):
    """P3 for one raw byte image on one code page: decode the raw
    bytes through a PIC X(width) reader, re-encode, and demand the
    canonical fixed point — decode(canon) == decode-after-one-round
    and re-encoding reproduces `canon` byte for byte. None if P3
    holds, else a short failure tag."""
    from cobrix_tpu import read_cobol

    copybook = f"""
       01  R.
           05  S  PIC X({width}).
"""
    if len(raw) % width:
        raw = raw + b"\x40" * (width - len(raw) % width)

    def decode_reencode(data: bytes):
        with tempfile.NamedTemporaryFile(suffix=".dat",
                                         delete=False) as f:
            f.write(data)
            path = f.name
        try:
            out = read_cobol(path, copybook_contents=copybook,
                             ebcdic_code_page=code_page)
            return out.to_rows(), out.to_ebcdic(framing="fixed")
        finally:
            os.unlink(path)

    rows1, canon = decode_reencode(raw)
    rows2, stable = decode_reencode(canon)
    if rows2 != rows1:
        return (f"P3 value instability on {code_page}: canonical "
                f"bytes decode to different rows than the raw image")
    if stable != canon:
        n = min(len(stable), len(canon))
        at = next((i for i in range(n) if stable[i] != canon[i]), n)
        return (f"P3 alias bytes on {code_page} do not reach a fixed "
                f"point after one round (first divergence at byte "
                f"{at})")
    return None


def run_alias_matrix(seeds=(0, 1, 2)) -> int:
    """P3 over every builtin code page: the full byte space (all 256
    values, so every duplicate-glyph alias byte is exercised) plus a
    few random images per page."""
    failures = 0
    cases = 0
    every_byte = bytes(range(256))
    for code_page in ALIAS_CODE_PAGES:
        images = [every_byte]
        for seed in seeds:
            rng = random.Random(7000 + seed)
            images.append(bytes(rng.randrange(256)
                                for _ in range(16 * 24)))
        for raw in images:
            cases += 1
            failure = alias_roundtrip_failure(code_page, raw)
            if failure:
                failures += 1
                print(f"FAIL code_page={code_page}: {failure}")
    print(f"rtcheck alias: {cases} raw images over "
          f"{len(ALIAS_CODE_PAGES)} code pages, {failures} failure(s)")
    return failures


def _framing_for(spec, rng=None) -> str:
    if spec.has_depending:
        return "rdw"  # variable_size_occurs needs variable-length records
    if rng is None:
        return "fixed"
    return rng.choice(["fixed", "rdw"])


def _shrink_and_report(spec, bodies, framing: str, failure: str,
                       seed) -> None:
    from cobrix_tpu.testing import genspec

    print(f"FAIL seed={seed} framing={framing}: {failure}")

    # isolate the failing record first, then shrink the pair
    row = bodies[0]
    for body in bodies:
        if roundtrip_failure(spec, [body], framing):
            row = body
            break

    def spec_fails(candidate) -> bool:
        return roundtrip_failure(candidate, [candidate.trivial_body()],
                                 framing) is not None

    # shrink the copybook only if the failure reproduces on the
    # trivial body (a pure schema bug); otherwise keep the schema and
    # shrink the record
    if spec_fails(spec):
        spec = genspec.shrink_spec(spec, spec_fails)
        row = spec.trivial_body()
    row = genspec.shrink_body(
        spec, row,
        lambda r: roundtrip_failure(spec, [r], framing) is not None)
    final = roundtrip_failure(spec, [row], framing)
    print("---- minimal reproduction ----")
    print(spec.copybook_text)
    print(f"framing: {framing}")
    print(f"record body: {row!r}")
    print(f"failure: {final or failure}")
    print("------------------------------")


def run_quick() -> int:
    """Deterministic seed matrix: both framings, every fuzzable code
    page, every grammar feature reachable from the seeds."""
    from cobrix_tpu.testing.genspec import CopybookSpec

    failures = 0
    cases = 0
    for seed in range(12):
        rng = random.Random(1000 + seed)
        spec = CopybookSpec.random(
            rng, code_page=FUZZ_CODE_PAGES[seed % len(FUZZ_CODE_PAGES)])
        bodies = [spec.random_body(rng) for _ in range(3)]
        framing = _framing_for(spec, rng)
        cases += 1
        failure = roundtrip_failure(spec, bodies, framing)
        if failure:
            failures += 1
            _shrink_and_report(spec, bodies, framing, failure,
                               1000 + seed)
    print(f"rtcheck quick: {cases} copybooks, "
          f"{failures} failure(s)")
    return failures + run_alias_matrix()


def run_sweep(n: int, base_seed: int) -> int:
    from cobrix_tpu.testing.genspec import CopybookSpec

    failures = 0
    for i in range(n):
        seed = base_seed + i
        rng = random.Random(seed)
        spec = CopybookSpec.random(
            rng, max_fields=10,
            code_page=rng.choice(list(FUZZ_CODE_PAGES)))
        bodies = [spec.random_body(rng) for _ in range(4)]
        framing = _framing_for(spec, rng)
        try:
            failure = roundtrip_failure(spec, bodies, framing)
        except Exception as exc:
            failure = f"exception: {type(exc).__name__}: {exc}"
        if failure:
            failures += 1
            try:
                _shrink_and_report(spec, bodies, framing, failure, seed)
            except Exception as exc:
                print(f"FAIL seed={seed} (shrink aborted: {exc})")
        if (i + 1) % 25 == 0:
            print(f"  ... {i + 1}/{n} copybooks, {failures} failure(s)")
    print(f"rtcheck sweep: {n} copybooks, {failures} failure(s)")
    return failures


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sweep", type=int, nargs="?", const=120,
                    default=None, metavar="N",
                    help="fuzz N random copybooks (default 120)")
    ap.add_argument("--seed", type=int, default=2000,
                    help="base seed for --sweep (default 2000)")
    args = ap.parse_args()
    failures = (run_sweep(args.sweep, args.seed)
                if args.sweep is not None else run_quick())
    if failures:
        print("rtcheck: FAILURES — see minimal reproductions above")
        return 1
    print("rtcheck: encode/decode round-trip properties hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
