"""Variable-length reader: framing + decode for RDW/length-field/text files,
multisegment filtering, Seg_Id generation, hierarchical assembly, and the
batched columnar path.

Mirrors the reference core reader semantics
(reader/VarLenNestedReader.scala:46: record extractor choice :60-79, RDW
header parser config :267, generateIndex :125-180, iterator choice :89;
reader/iterator/VarLenNestedIterator.scala:43-148;
reader/iterator/VarLenHierarchicalIterator.scala:43-162;
reader/iterator/SegmentIdAccumulator.scala:19-86) — but the decode plane is
columnar: records framed on the host are packed per active-segment into
padded `[batch, max_len]` blocks and decoded by the TPU kernels
(reader/columnar.py), with the per-record host walk kept as the oracle path.
"""
from __future__ import annotations

import time
from dataclasses import replace
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..copybook.ast import Group, Primitive
from ..copybook.copybook import Copybook
from ..plan.cache import copybook_for_params, decoder_cache_for
from ..obs.context import count_pass, current as obs_current
from ..profiling import timed_stage
from .columnar import ColumnarDecoder, decoder_for_segment
from .extractors import (
    DecodeOptions,
    extract_hierarchical_record,
    extract_record,
)
from .header_parsers import (
    FixedLengthHeaderParser,
    RdwHeaderParser,
    RecordHeaderParser,
    create_record_header_parser,
)
from .index import SparseIndexEntry, sparse_index_generator
from .parameters import (
    DEFAULT_FILE_RECORD_ID_INCREMENT,
    DEFAULT_INDEX_ENTRY_SIZE_MB,
    MEGABYTE,
    ReaderParameters,
)
from .result import FileResult, SegmentBatch
from .raw_extractors import (
    RawRecordContext,
    TextRecordExtractor,
    VarOccursRecordExtractor,
    create_raw_record_extractor,
)
from .stream import SimpleStream
from .vrl_reader import (
    SegmentIds,
    VRLRecordReader,
    decode_segment_id_bytes,
    resolve_segment_id_field,
)


class SegmentIdAccumulator:
    """Generates Seg_Id0..N values: root = `prefix_fileId_recordIndex`,
    children `<root>_L<level>_<counter>` (reference SegmentIdAccumulator)."""

    def __init__(self, segment_ids: Sequence[str], segment_id_prefix: str,
                 file_id: int):
        self._ids = [s.split(",") for s in segment_ids]
        self._count = len(segment_ids)
        self._acc = [0] * (self._count + 1)
        self._current_level = -1
        self._current_root = ""
        self.prefix = segment_id_prefix
        self.file_id = file_id

    def acquired_segment_id(self, segment_id: str, record_index: int) -> None:
        if self._count == 0:
            return
        level = None
        for i, ids in enumerate(self._ids):
            if segment_id in ids:
                level = i
                break
        if level is None:
            return
        self._current_level = level
        if level == 0:
            self._current_root = f"{self.prefix}_{self.file_id}_{record_index}"
            self._acc = [0] * len(self._acc)
        else:
            self._acc[level] += 1

    def get_segment_level_id(self, level: int) -> Optional[str]:
        if 0 <= level <= self._current_level:
            if level == 0:
                return self._current_root
            return f"{self._current_root}_L{level}_{self._acc[level]}"
        return None


def default_segment_id_prefix() -> str:
    return time.strftime("%Y%m%d%H%M%S")


def _segment_level_ids_vectorized(segment_ids: Sequence[str],
                                  level_defs: Sequence[str], prefix: str,
                                  file_id: int, start_record_id: int):
    """Vectorized SegmentIdAccumulator over a framed shard: Seg_Id0..N as
    per-level columns with the exact state semantics of the per-record
    accumulator (forward-filled current level/root, per-level counters
    reset at roots, empty root prefix before the first root). Returns
    (SegLevelColumns, no_match_yet_mask)."""
    from .result import SegLevelColumns

    n = len(segment_ids)
    level_lists = [s.split(",") for s in level_defs]
    level_count = len(level_lists)
    sid_level = {}
    for i, ids in enumerate(level_lists):
        for sid in ids:
            sid_level.setdefault(sid, i)
    if isinstance(segment_ids, SegmentIds):
        # one level lookup per DISTINCT id, broadcast by the codes
        lvl_uniq = np.asarray([sid_level.get(u, -1)
                               for u in segment_ids.uniq], dtype=np.int32)
        lvl = (lvl_uniq[segment_ids.codes] if len(lvl_uniq)
               else np.full(n, -1, dtype=np.int32))
    else:
        get_level = sid_level.get
        lvl = np.fromiter((get_level(s, -1) for s in segment_ids),
                          dtype=np.int32, count=n)

    # int32 state: the plane is memory-bandwidth bound; only root_rid
    # widens to int64 at the end. Explicit bound instead of silent wrap
    if n >= 2 ** 31:
        raise ValueError(
            f"shard of {n} records exceeds the 2^31 seg-id plane bound; "
            "split the input (hosts/input_split options)")
    idx = np.arange(n, dtype=np.int32)
    # forward-filled current level (last matched record's level; -1 = none)
    last_match = np.where(lvl >= 0, idx, np.int32(-1))
    np.maximum.accumulate(last_match, out=last_match)
    cur_level = np.where(last_match >= 0, lvl[np.maximum(last_match, 0)], -1)
    no_match_yet = last_match < 0
    # forward-filled root position (-1 before the first root: the
    # accumulator's empty pre-root prefix)
    root_pos = np.where(lvl == 0, idx, np.int32(-1))
    np.maximum.accumulate(root_pos, out=root_pos)
    root_rid = np.where(root_pos >= 0,
                        start_record_id + root_pos.astype(np.int64),
                        np.int64(-1))

    # per-level child counters (cumulative count since the current root)
    counters: List[Optional[np.ndarray]] = [None]
    for k in range(1, level_count):
        c = np.cumsum(lvl == k, dtype=np.int32)
        at_root = np.where(root_pos >= 0, c[np.maximum(root_pos, 0)],
                           np.int32(0))
        counters.append(c - at_root)
    valids = [cur_level >= k for k in range(level_count)]
    coded = dict(root_rid=root_rid, counters=counters, valids=valids,
                 prefix=f"{prefix}_{file_id}_", level_count=level_count)
    return SegLevelColumns(coded=coded), no_match_yet


def _has_dynamic_occurs_layout(root: Group) -> bool:
    """True when a variable-size OCCURS makes later field offsets
    record-dependent: a DEPENDING ON array followed by any other field, or
    nested inside another array. A single *trailing* depending array keeps
    static element offsets and stays on the columnar path."""
    state = {"after_var_array": False, "dynamic": False}

    def walk(group: Group, in_array: bool) -> None:
        for st in group.children:
            if state["dynamic"]:
                return
            if state["after_var_array"]:
                state["dynamic"] = True
                return
            is_dep_array = st.is_array and st.depending_on is not None
            if is_dep_array and in_array:
                state["dynamic"] = True
                return
            if isinstance(st, Group):
                walk(st, in_array or st.is_array)
                if state["dynamic"]:
                    return
            if is_dep_array:
                state["after_var_array"] = True

    walk(root, False)
    return state["dynamic"]


class VarLenReader:
    """Core variable-length reader bound to one copybook + parameters."""

    def __init__(self, copybook_contents, params: ReaderParameters):
        seg = params.multisegment
        # fingerprint-keyed parse cache (plan/cache.py): repeated scans of
        # the same copybook/options share the Copybook object and its
        # compiled plans/decoders — per-chunk pipeline decodes never
        # re-derive them
        self.copybook = copybook_for_params(copybook_contents, params)
        # stable copybook identity for the persisted sparse-index key
        # (io.index_store): survives process restarts, unlike id()
        from ..plan.cache import parse_fingerprint

        self.copybook_fingerprint = parse_fingerprint(copybook_contents,
                                                      params)
        self.params = params
        self.segment_redefine_map = dict(
            seg.segment_id_redefine_map) if seg else {}
        self._decoders: Dict[str, ColumnarDecoder] = \
            decoder_cache_for(self.copybook)
        # predicate pushdown (query/pushdown.py): bound once per reader,
        # shared (with its counters) by every shard/chunk of the read
        from ..query.pushdown import BoundFilter

        self.pushdown = BoundFilter.build(params.filter, self.copybook,
                                          params)
        # variable-size OCCURS that shift later fields make the static
        # columnar plan inapplicable — those records decode on the host.
        # Walked over the whole record (all 01-level roots in one pass): a
        # variable array at the end of one root shifts every later root.
        self.dynamic_occurs_layout = (
            params.variable_size_occurs
            and _has_dynamic_occurs_layout(self.copybook.ast))

    # -- plumbing ----------------------------------------------------------

    def record_extractor(self, starting_record_number: int,
                         stream: SimpleStream):
        """reference VarLenNestedReader.recordExtractor (:60-79)."""
        ctx = RawRecordContext(starting_record_number, stream, self.copybook,
                               self.params.re_additional_info)
        if self.params.record_extractor:
            return create_raw_record_extractor(self.params.record_extractor, ctx)
        if self.params.is_text:
            return TextRecordExtractor(ctx)
        if self.params.variable_size_occurs \
                and not self.params.is_record_sequence \
                and not self.params.length_field_name:
            return VarOccursRecordExtractor(ctx)
        return None

    def record_header_parser(self) -> RecordHeaderParser:
        """reference VarLenNestedReader.getDefaultRecordHeaderParser (:267)."""
        if self.params.record_header_parser:
            parser = create_record_header_parser(
                self.params.record_header_parser,
                record_size=self.copybook.record_size,
                file_header_bytes=self.params.file_start_offset,
                file_footer_bytes=self.params.file_end_offset,
                rdw_adjustment=self.params.rdw_adjustment)
        elif self.params.is_record_sequence:
            adjustment = self.params.rdw_adjustment
            if self.params.is_rdw_part_of_record_length:
                adjustment -= 4
            parser = RdwHeaderParser(self.params.is_rdw_big_endian,
                                     self.params.file_start_offset,
                                     self.params.file_end_offset,
                                     adjustment)
        else:
            # record_length override wins over the copybook size (same
            # semantics as FixedLenReader.record_size: the override is the
            # full on-disk record, offsets not re-added)
            record_size = (self.params.record_length_override
                           or self.copybook.record_size
                           + self.params.start_offset
                           + self.params.end_offset)
            parser = FixedLengthHeaderParser(
                record_size,
                self.params.file_start_offset, self.params.file_end_offset)
        if self.params.rhp_additional_info is not None:
            parser.on_receive_additional_info(self.params.rhp_additional_info)
        return parser

    # -- index -------------------------------------------------------------

    def _index_split_config(self):
        """Validated (records_per_entry, size_mb) + root-boundary config
        (reference VarLenNestedReader.generateIndex :125-180: splits align
        to root-segment boundaries whenever Seg_Id generation or a
        parent-child segment map is requested, so per-shard Seg_Id
        accumulators restart exactly at a root)."""
        params = self.params
        if params.input_split_records is not None and not (
                1 <= params.input_split_records <= 1_000_000_000):
            raise ValueError(
                "Invalid input split size. The requested number of records "
                f"is {params.input_split_records}.")
        if params.input_split_size_mb is not None and not (
                1 <= params.input_split_size_mb <= 2000):
            raise ValueError(
                f"Invalid input split size of {params.input_split_size_mb} MB.")
        seg = params.multisegment
        is_hierarchical = bool(seg and (seg.segment_level_ids
                                        or seg.field_parent_map))
        root_segment_id = ""
        if seg:
            if seg.field_parent_map and self.segment_redefine_map:
                # every root id is a valid split boundary (multi-root files,
                # reference Test12MultiRootSparseIndex)
                root_segment_id = ",".join(self.copybook.get_root_segment_ids(
                    self.segment_redefine_map, seg.field_parent_map))
            elif seg.segment_level_ids:
                root_segment_id = seg.segment_level_ids[0]
        return is_hierarchical, root_segment_id

    def generate_index(self, stream: SimpleStream, file_id: int
                       ) -> List[SparseIndexEntry]:
        """reference VarLenNestedReader.generateIndex (:125-180)."""
        params = self.params
        seg_field = resolve_segment_id_field(params, self.copybook)
        is_hierarchical, root_segment_id = self._index_split_config()
        return sparse_index_generator(
            file_id,
            stream,
            record_header_parser=self.record_header_parser(),
            record_extractor=self.record_extractor(0, stream),
            records_per_index_entry=params.input_split_records,
            size_per_index_entry_mb=params.input_split_size_mb,
            copybook=self.copybook,
            segment_field=seg_field,
            is_hierarchical=is_hierarchical,
            root_segment_id=root_segment_id,
            record_error_policy=params.record_error_policy,
            resync_window_bytes=params.resync_window_bytes)

    def generate_index_fast(self, data, file_id: int
                            ) -> Optional[List[SparseIndexEntry]]:
        """Vectorized sparse index for plain RDW files: one native scan of
        the file image + split arithmetic over the offset arrays instead of
        the per-record Python pass. Returns None when the configuration
        needs the generic generator (custom extractors/parsers, text mode,
        length fields, variable OCCURS). Split semantics (including the
        invalid-record counting and size-drift quirks) mirror
        sparse_index_generator exactly — pinned by tests against it."""
        from .. import native

        if not self.supports_fast_framing:
            return None
        p = self.params
        adjustment = p.rdw_adjustment
        if p.is_rdw_part_of_record_length:
            adjustment -= 4
        if p.is_permissive:
            # same skip decisions as the shard scan so split offsets land
            # on records the shard framers will actually find; the ledger
            # here is a throwaway (the decode pass records the incidents)
            from .recovery import rdw_scan_permissive

            offsets, lengths, _ = rdw_scan_permissive(
                data, p.is_rdw_big_endian, adjustment,
                p.file_start_offset, p.file_end_offset,
                p.record_error_policy, p.resync_window_bytes,
                p.new_diagnostics())
        else:
            offsets, lengths = native.rdw_scan(
                data, p.is_rdw_big_endian, adjustment,
                p.file_start_offset, p.file_end_offset)
        n = len(offsets)
        starts = offsets - 4  # RDW header precedes the payload
        # the file-header region is consumed as one counted invalid record
        # (IndexGenerator.scala:117-120 counts unconditionally)
        base = 1 if p.file_start_offset > 0 else 0

        is_hierarchical, root_segment_id = self._index_split_config()
        seg_field = resolve_segment_id_field(p, self.copybook)
        root_indices: Optional[np.ndarray] = None
        if is_hierarchical and seg_field is not None:
            root_ids = set(root_segment_id.split(","))
            sids = self._segment_ids_vectorized(data, offsets, lengths,
                                                seg_field)
            root_indices = np.nonzero(sids.mask_of(root_ids))[0]

        def next_root(i: int) -> Optional[int]:
            if root_indices is None:
                return i
            k = np.searchsorted(root_indices, i, side="left")
            if k >= len(root_indices):
                return None
            return int(root_indices[k])

        entries = [SparseIndexEntry(0, -1, file_id, 0)]
        if p.input_split_records is not None:
            per = p.input_split_records
        else:
            per = None
            mb = ((p.input_split_size_mb or DEFAULT_INDEX_ENTRY_SIZE_MB)
                  * MEGABYTE)

        # processing the last record ends the stream before the split check
        # (IndexGenerator loop order) — unless a footer region follows it,
        # which is consumed as one more counted iteration
        last_candidate = n - 1 if p.file_end_offset > 0 else n - 2
        subtracted = 0
        chunk_start_counted = 0
        i = -1  # a first-chunk split at record 0 is possible (header counted)
        while True:
            if per is not None:
                cand = chunk_start_counted + per - base
            else:
                target = subtracted + mb
                cand = int(np.searchsorted(starts, target, side="left"))
            cand = max(cand, i + 1)
            split_at = next_root(cand)
            if split_at is None or split_at > last_candidate:
                break
            entries[-1] = replace(entries[-1],
                                  offset_to=int(starts[split_at]))
            entries.append(SparseIndexEntry(
                int(starts[split_at]), -1, file_id, split_at + base))
            if per is not None:
                chunk_start_counted = split_at + base
            else:
                subtracted += mb
            i = split_at
        return entries

    # -- framing -----------------------------------------------------------

    def make_record_reader(self, stream: SimpleStream,
                           start_record_id: int = 0,
                           starting_file_offset: int = 0,
                           ledger=None) -> VRLRecordReader:
        """The per-record framing iterator (policy-aware; `ledger` carries
        the error ledger across shards of one read)."""
        return VRLRecordReader(
            self.copybook, stream, self.params, self.record_header_parser(),
            self.record_extractor(start_record_id, stream),
            start_record_id, starting_file_offset, ledger=ledger)

    def frame_records(self, stream: SimpleStream, start_record_id: int = 0,
                      starting_file_offset: int = 0, ledger=None
                      ) -> Iterator[Tuple[int, str, bytes]]:
        """Yield (record_index, segment_id, record_bytes)."""
        reader = self.make_record_reader(stream, start_record_id,
                                         starting_file_offset, ledger)
        while reader.has_next():
            index = reader.record_index + 1
            segment_id, data = next(reader)
            yield index, segment_id, data

    # -- row iteration (host oracle path) -----------------------------------

    def iter_rows(self, stream: SimpleStream, file_id: int = 0,
                  start_record_id: int = 0, starting_file_offset: int = 0,
                  segment_id_prefix: Optional[str] = None,
                  ledger=None,
                  corrupt_reasons_out: Optional[dict] = None
                  ) -> Iterator[List[object]]:
        if self.copybook.is_hierarchical:
            # hierarchical assemblies carry no per-row corruption
            # attribution (the ledger still records every incident)
            yield from self._iter_rows_hierarchical(
                stream, file_id, start_record_id, starting_file_offset,
                ledger=ledger)
            return
        params = self.params
        seg = params.multisegment
        prefix = segment_id_prefix or default_segment_id_prefix()
        accumulator = (SegmentIdAccumulator(seg.segment_level_ids, prefix, file_id)
                       if seg else None)
        level_count = len(seg.segment_level_ids) if seg else 0
        segment_filter = set(seg.segment_id_filter) if seg and seg.segment_id_filter else None
        options = DecodeOptions.from_copybook(self.copybook)
        generate_input_file = bool(params.input_file_name_column)

        record_reader = self.make_record_reader(
            stream, start_record_id, starting_file_offset, ledger)
        row_position = 0
        while record_reader.has_next():
            record_index = record_reader.record_index + 1
            segment_id, data = next(record_reader)
            level_ids: List[Optional[str]] = []
            if level_count and accumulator is not None:
                accumulator.acquired_segment_id(segment_id, record_index)
                level_ids = [accumulator.get_segment_level_id(i)
                             for i in range(level_count)]
            if level_ids and level_ids[0] is None:
                continue  # before the first root segment
            if segment_filter is not None and segment_id not in segment_filter:
                continue
            if corrupt_reasons_out is not None:
                # the reader ledgers a kept-malformed record during its
                # prefetch, so the entry exists by the time it is emitted
                reason = record_reader.corrupt_reasons.get(record_index)
                if reason is not None:
                    corrupt_reasons_out[row_position] = reason
            row_position += 1
            active_redefine = self.segment_redefine_map.get(segment_id, "")
            yield extract_record(
                self.copybook.ast,
                data,
                offset_bytes=params.start_offset,
                policy=params.schema_policy,
                variable_length_occurs=params.variable_size_occurs,
                generate_record_id=params.generate_record_id,
                segment_level_ids=level_ids,
                file_id=file_id,
                record_id=record_index,
                active_segment_redefine=active_redefine,
                generate_input_file_field=generate_input_file,
                input_file_name=stream.input_file_name,
                options=options)

    def _hierarchy_maps(self):
        """(segment id -> redefine group, parent -> child groups, root
        group names) — shared by the scalar and columnar hierarchical
        paths so they cannot disagree on the hierarchy."""
        segment_redefines = {g.name: g
                             for g in self.copybook.get_all_segment_redefines()}
        sid_map = {sid: segment_redefines[name]
                   for sid, name in self.segment_redefine_map.items()
                   if name in segment_redefines}
        parent_child_map = self.copybook.get_parent_children_segment_map()
        root_names = {g.name for g in segment_redefines.values()
                      if g.parent_segment is None}
        return sid_map, parent_child_map, root_names

    def _iter_rows_hierarchical(self, stream: SimpleStream, file_id: int,
                                start_record_id: int,
                                starting_file_offset: int,
                                ledger=None) -> Iterator[List[object]]:
        """Buffer one root record plus its children, then assemble
        (reference VarLenHierarchicalIterator.fetchNext :99)."""
        params = self.params
        segment_id_redefine_map, parent_child_map, root_names = \
            self._hierarchy_maps()
        options = DecodeOptions.from_copybook(self.copybook)
        generate_input_file = bool(params.input_file_name_column)

        buffer: List[Tuple[str, bytes]] = []
        root_record_index = 0

        def flush():
            return extract_hierarchical_record(
                self.copybook.ast,
                buffer,
                segment_id_redefine_map,
                parent_child_map,
                offset_bytes=params.start_offset,
                policy=params.schema_policy,
                variable_length_occurs=params.variable_size_occurs,
                generate_record_id=params.generate_record_id,
                file_id=file_id,
                record_id=root_record_index,
                generate_input_file_field=generate_input_file,
                input_file_name=stream.input_file_name,
                options=options)

        # Record_Id parity quirk: the reference's hierarchical iterator
        # stamps each assembled row with the raw record index of the record
        # that TRIGGERS the flush — the next root (or the total record
        # count at end of stream), VarLenHierarchicalIterator.scala:99-135
        last_index = start_record_id - 1
        for record_index, segment_id, data in self.frame_records(
                stream, start_record_id, starting_file_offset,
                ledger=ledger):
            redefine = segment_id_redefine_map.get(segment_id)
            is_root = redefine is not None and redefine.name in root_names
            if is_root:
                if buffer:
                    root_record_index = record_index
                    yield flush()
                buffer = [(segment_id, data)]
            elif buffer:
                buffer.append((segment_id, data))
            last_index = record_index
        if buffer:
            root_record_index = last_index + 1
            yield flush()

    def _hierarchical_columnar_setup(self, stream: SimpleStream,
                                     backend: str,
                                     ledger=None,
                                     stage_times=None) -> Optional[dict]:
        """Frame + decode-once setup shared by the hierarchical row and
        Arrow paths. Returns None when the configuration needs the
        generic scalar path — every bail happens BEFORE framing consumes
        the stream, so the caller's fallback can still read it."""
        params = self.params
        if resolve_segment_id_field(params, self.copybook) is None:
            return None
        if params.select:
            # the scalar oracle ignores column projection; a projected
            # columnar decode would silently change hierarchical rows
            return None
        if params.start_offset:
            # the oracle reads CHILD records at the field's plain offset,
            # without the record start offset (extract_children /
            # reference extractChildren) — the uniform decode_raw shift
            # cannot reproduce that
            return None
        fast = self._frame_fast(stream, ledger=ledger,
                                stage_times=stage_times)
        if fast is None:
            return None
        data, _base, offsets, rec_lengths, segment_ids, _reasons = fast
        assert segment_ids is not None  # guaranteed by the seg-field guard
        n = len(offsets)

        sid_map, parent_child_map, root_names = self._hierarchy_maps()
        name_of_sid = {sid: g.name for sid, g in sid_map.items()}
        # per-redefine row masks: a redefine's columns are read only on
        # its own segment's records, so whole-column materialization (and
        # the truncation fixups of OTHER segments' shorter records) is
        # skipped outside the mask
        seg_masks = {name: segment_ids.mask_of_mapped(name_of_sid, name)
                     for name in {g.name for g in sid_map.values()}}
        # dictionary-coded segment names: one name per DISTINCT sid plus
        # the int32 code vector — the Arrow assembly's membership tests
        # run on the codes, never on per-row Python strings
        uniq_named = [name_of_sid.get(u) for u in segment_ids.uniq]
        segment_names = (uniq_named, segment_ids.codes)
        decoder = self._decoder_for_segment("", backend)
        # masked decode: each segment's numeric groups run only on its
        # own rows (hidden rows come back invalid, which the assembly and
        # the nesting walk treat exactly like the garbage they replace)
        with timed_stage(stage_times, "decode"):
            batch = (decoder.decode_raw(data, offsets, rec_lengths,
                                        segment_row_masks=seg_masks) if n
                     else None)
        root_uniq = np.asarray([nm in root_names for nm in uniq_named])
        n_roots = (int(root_uniq[segment_ids.codes].sum())
                   if len(uniq_named) else 0)
        return dict(batch=batch, segment_names=segment_names,
                    segment_ids=segment_ids, sid_map=sid_map,
                    parent_child_map=parent_child_map,
                    root_names=root_names, seg_masks=seg_masks,
                    decoder=decoder, n=n, n_roots=n_roots,
                    input_file_name=stream.input_file_name)

    def _read_rows_hierarchical_columnar(self, ctx: dict, file_id: int,
                                         start_record_id: int
                                         ) -> List[List[object]]:
        """Hierarchical rows with batched value decode: every record's
        fields come from ONE full-plan columnar batch (kernels, not the
        per-field scalar walk); only the parent/child nesting assembly
        runs per record, mirroring extract_hierarchical_record's scan
        semantics exactly (forward scan per child segment, stop when a
        parent id reappears, flush-trigger Record_Id)."""
        from .extractors import _apply_post_processing
        from .columnar import _resolve_occurs

        params = self.params
        n = ctx["n"]
        if n == 0:
            return []
        batch = ctx["batch"]
        segment_ids = ctx["segment_ids"].tolist()
        sid_map = ctx["sid_map"]
        parent_child_map = ctx["parent_child_map"]
        root_names = ctx["root_names"]
        seg_masks = ctx["seg_masks"]
        decoder = ctx["decoder"]
        stream_name = ctx["input_file_name"]
        slot_map = decoder.slot_map
        col_values: Dict[int, list] = {}

        def values_of(col):
            lst = col_values.get(col)
            if lst is None:
                spec = decoder.plan.columns[col]
                # dependee columns are READ at every row — the walk runs
                # non-emitted parts to register DEPENDING-ON counters from
                # whatever bytes overlay them (oracle parity) — so they
                # must never be masked
                is_dependee = (spec.statement is not None
                               and spec.statement.is_dependee)
                mask = (seg_masks.get(spec.segment)
                        if spec.segment is not None and not is_dependee
                        else None)
                lst = batch.column_values(col, relevant=mask)
                col_values[col] = lst
            return lst

        # the walk compiles once per (group, slot_path) into closures over
        # the column value lists — per-record work is list indexing, not
        # slot-map dict lookups per element (the hierarchical twin of
        # ColumnarDecoder._row_maker)
        maker_cache: Dict[tuple, object] = {}

        def build_group(group, slot_path):
            key = (id(group), slot_path)
            maker = maker_cache.get(key)
            if maker is not None:
                return maker
            parts = []  # (emit, fn) — fn(i, scan_i, span_end, pids, depend)
            for st in group.children:
                emit = not st.is_filler and not st.is_child_segment
                if st.is_array:
                    if isinstance(st, Group):
                        elems = [build_group(st, slot_path + (k,))
                                 for k in range(st.array_max_size)]
                        fn = (lambda i, s, e, pd, dep, st=st, el=elems:
                              [mk(i, s, e, pd, dep)
                               for mk in el[:_resolve_occurs(
                                   st, dep.get(st.depending_on))]])
                    else:
                        cols = [slot_map.get((id(st), slot_path + (k,)))
                                for k in range(st.array_max_size)]
                        lists = [None if c is None else values_of(c)
                                 for c in cols]
                        fn = (lambda i, s, e, pd, dep, st=st, ls=lists:
                              [None if l is None else l[i]
                               for l in ls[:_resolve_occurs(
                                   st, dep.get(st.depending_on))]])
                elif isinstance(st, Group):
                    fn = build_group(st, slot_path)
                else:
                    col = slot_map.get((id(st), slot_path))
                    if col is None:
                        fn = lambda i, s, e, pd, dep: None
                    elif st.is_dependee:
                        lst = values_of(col)
                        name = st.name
                        def fn(i, s, e, pd, dep, lst=lst, name=name):
                            value = lst[i]
                            if value is not None:
                                dep[name] = (value if isinstance(value, str)
                                             else int(value))
                            return value
                    else:
                        lst = values_of(col)
                        fn = lambda i, s, e, pd, dep, lst=lst: lst[i]
                parts.append((emit, fn))
            children_groups = (tuple(parent_child_map.get(group.name, ()))
                               if group.is_segment_redefine else ())

            def maker(i, scan_i, span_end, parent_ids, depend,
                      parts=tuple(parts), children_groups=children_groups):
                # declaration order throughout: dependees must register
                # before any later OCCURS resolves, emitted or not
                fields = []
                for emit, fn in parts:
                    value = fn(i, scan_i, span_end, parent_ids, depend)
                    if emit:
                        fields.append(value)
                for child in children_groups:
                    fields.append(extract_children(
                        child, scan_i + 1, span_end, parent_ids, depend))
                return tuple(fields)

            maker_cache[key] = maker
            return maker

        def extract_children(field, from_i, span_end, parent_ids, depend):
            child_maker = build_group(field, ())
            children = []
            j = from_i
            while j < span_end:
                sid_j = segment_ids[j]
                redefine = sid_map.get(sid_j)
                if redefine is not None and redefine.name == field.name:
                    children.append(child_maker(
                        j, j, span_end, [sid_j] + parent_ids, depend))
                elif sid_j in parent_ids:
                    break
                j += 1
            return children

        roots = [p for p in range(n)
                 if (g := sid_map.get(segment_ids[p])) is not None
                 and g.name in root_names]
        generate_input_file = bool(params.input_file_name_column)
        ast_roots = [r for r in self.copybook.ast.children
                     if isinstance(r, Group) and r.parent_segment is None]
        rows = []
        for ri, p in enumerate(roots):
            span_end = roots[ri + 1] if ri + 1 < len(roots) else n
            # Record_Id parity quirk: the id of the record that TRIGGERS
            # the flush — the next root, or one past the last record at
            # end of stream (VarLenHierarchicalIterator.scala:99-135)
            trigger_id = start_record_id + span_end
            depend: Dict[str, object] = {}
            records = [build_group(root, ())(p, p, span_end,
                                             [segment_ids[p]], depend)
                       for root in ast_roots]
            rows.append(_apply_post_processing(
                records, params.schema_policy, params.generate_record_id,
                [], file_id, trigger_id, generate_input_file,
                stream_name))
        return rows

    # -- columnar batch path -------------------------------------------------

    def _decoder_for_segment(self, active_segment: str,
                             backend: str) -> ColumnarDecoder:
        return decoder_for_segment(self._decoders, self.copybook,
                                   active_segment, backend,
                                   select=self.params.select)

    # -- vectorized fast framing (native scan) ------------------------------

    @property
    def supports_fast_framing(self) -> bool:
        """True when whole-shard vectorized RDW framing applies (no custom
        extractors/parsers, no text mode, no length fields, no variable
        OCCURS)."""
        return self.params.supports_fast_framing

    def _frame_fast(self, stream: SimpleStream, ledger=None,
                    stage_times=None):
        """Whole-shard RDW framing via the native scanner. Returns
        (data, base_offset, offsets, lengths, segment_ids, corrupt_reasons)
        or None when the configuration needs the generic per-record
        reader. `corrupt_reasons` maps kept malformed record positions to
        reasons (permissive policy only; empty otherwise). `stage_times`:
        optional StageTimes — the bulk byte materialization is attributed
        to "read", the header scan + segment-id decode to "frame"."""
        from .. import native

        if not self.supports_fast_framing:
            return None
        p = self.params
        base = stream.offset
        with timed_stage(stage_times, "read"):
            data = stream.next_view(stream.size() - base)
        adjustment = p.rdw_adjustment
        if p.is_rdw_part_of_record_length:
            adjustment -= 4
        # the file-header region rule only applies at the file start, the
        # footer rule only when this shard reaches the file's true end (an
        # indexed shard ending mid-file has a data tail, not a footer)
        file_header = p.file_start_offset if base == 0 else 0
        file_footer = (p.file_end_offset
                       if stream.size() >= stream.true_size else 0)
        corrupt_reasons: dict = {}
        with timed_stage(stage_times, "frame"):
            seg_field = resolve_segment_id_field(p, self.copybook)
            seg_bytes = None
            if p.is_permissive:
                from .recovery import rdw_scan_permissive

                offsets, lengths, corrupt_reasons = rdw_scan_permissive(
                    data, p.is_rdw_big_endian, adjustment, file_header,
                    file_footer, p.record_error_policy,
                    p.resync_window_bytes,
                    ledger if ledger is not None else p.new_diagnostics(),
                    file_name=stream.input_file_name, base_offset=base)
            else:
                fused = None
                if seg_field is not None:
                    # fused frame + segment-id gather: one native walk
                    # emits the record table AND each record's id-field
                    # bytes, replacing rdw_scan + a whole-file
                    # pack_records re-walk (None = no native library)
                    fused = native.rdw_scan_segids(
                        data, p.is_rdw_big_endian,
                        p.start_offset + seg_field.binary_properties.offset,
                        seg_field.binary_properties.actual_size,
                        adjustment, file_header, file_footer)
                if fused is not None:
                    offsets, lengths, seg_bytes = fused
                    count_pass("fused_frame")
                else:
                    offsets, lengths = native.rdw_scan(
                        data, p.is_rdw_big_endian, adjustment, file_header,
                        file_footer)
            segment_ids: Optional[List[str]] = None
            if seg_field is not None:
                segment_ids = self._segment_ids_vectorized(
                    data, offsets, lengths, seg_field,
                    field_bytes=seg_bytes)
        obs = obs_current()
        if obs is not None and obs.metrics is not None and len(lengths):
            # record-length distribution (one vectorized bucket count per
            # shard, never a per-record loop)
            obs.metrics["record_length"].observe_many(lengths)
        return data, base, offsets, lengths, segment_ids, corrupt_reasons

    def _segment_ids_vectorized(self, data, offsets, lengths,
                                seg_field: Primitive,
                                field_bytes=None) -> SegmentIds:
        """Per-record segment ids (dictionary-coded): gather just the id
        field's bytes, decode each *unique* byte pattern once (the scalar
        oracle) — the columnar analogue of getSegmentId per record.
        `field_bytes`: the [n, width] id-field byte matrix when the fused
        framing scan already gathered it (zero-padded past short records,
        pack_records parity); None gathers here."""
        from .. import native

        start = self.params.start_offset
        seg_off = seg_field.binary_properties.offset
        seg_w = seg_field.binary_properties.actual_size
        extent = start + seg_off + seg_w
        if field_bytes is None:
            packed = native.pack_records(data, offsets, lengths, extent)
            field_bytes = packed[:, start + seg_off:]
        short = lengths < extent  # id field truncated -> decode actual bytes
        options = DecodeOptions.from_copybook(self.copybook)
        out = decode_segment_id_bytes(field_bytes, seg_field, options)
        for i in np.nonzero(short)[0]:
            avail = max(0, int(lengths[i]) - (start + seg_off))
            value = options.decode(seg_field.dtype,
                                   bytes(field_bytes[i, :avail]))
            out.replace_at(int(i), "" if value is None else str(value).strip())
        return out

    def _read_result_fast(self, result: "FileResult", data, base: int,
                          offsets, lengths,
                          segment_ids: Optional[List[str]],
                          file_id: int, backend: str,
                          prefix: str,
                          start_record_id: int,
                          corrupt_reasons: Optional[dict] = None) -> None:
        if corrupt_reasons:
            result.corrupt_row_reasons = dict(corrupt_reasons)
        params = self.params
        seg = params.multisegment
        n = len(offsets)
        level_count = len(seg.segment_level_ids) if seg else 0
        segment_filter = (set(seg.segment_id_filter)
                          if seg and seg.segment_id_filter else None)

        keep = np.ones(n, dtype=bool)
        level_ids_per_record: Optional[List[List[Optional[str]]]] = None
        if level_count and segment_ids is not None:
            level_ids_per_record, no_root = _segment_level_ids_vectorized(
                segment_ids, seg.segment_level_ids, prefix, file_id,
                start_record_id)
            keep[no_root] = False  # before the first matched segment
        if segment_filter is not None and segment_ids is not None:
            keep &= segment_ids.mask_of(segment_filter)

        start = params.start_offset
        kept = np.nonzero(keep)[0]
        if self.pushdown is not None:
            # scanned = records the PUSHDOWN examined: level-gating and
            # the legacy segment_id_filter dropped theirs above, and
            # counting them as scanned-but-unpruned would overstate
            # selectivity in the audit/fleet rollups
            kept = self._pushdown_kept(
                self.pushdown, kept, data, offsets, lengths,
                segment_ids, start, backend, n_scanned=len(kept))
            keep = np.zeros(n, dtype=bool)
            keep[kept] = True
        result.n_rows = len(kept)

        # Decode ONCE over every kept record with the full (all-redefines)
        # plan: redefines share byte offsets, so inactive rows decode
        # garbage that a per-redefine struct-validity mask hides — and the
        # per-segment split + interleave gather disappears entirely.
        # Size-skewed profiles (e.g. exp3's 16KB 'C' vs 64B 'P' records)
        # come through here too: the segment row masks reach the decode
        # (masked groups subset-decode or defer into the fused native
        # assembly, which skips hidden rows in-kernel), so the wide
        # plan's columns never run over the narrow records' bytes.
        if segment_ids is not None and self.segment_redefine_map:
            full = self._decoder_for_segment("", backend)
            active_of_uniq = segment_ids.map_uniq(
                self.segment_redefine_map)
            distinct = sorted(set(active_of_uniq))
            a_idx = {a: j for j, a in enumerate(distinct)}
            per_uniq = np.asarray([a_idx[a] for a in active_of_uniq],
                                  dtype=np.int32)
            row_act = per_uniq[segment_ids.codes[kept]]
            masks = {a.upper(): row_act == j
                     for a, j in a_idx.items() if a}
            decoded = full.decode_raw(
                data, offsets[kept], lengths[kept], start_offset=start,
                segment_row_masks=masks, lazy_masked=True)
            kept64 = kept.astype(np.int64)
            result.segments.append(SegmentBatch(
                decoded, None, kept64, start_record_id + kept64,
                seg_level_ids=(
                    level_ids_per_record
                    if level_ids_per_record is not None
                    and len(kept) == n
                    else level_ids_per_record.take(kept)
                    if level_ids_per_record is not None else None),
                redefine_masks=masks,
                row_actives=SegmentIds(row_act, distinct)))
            return

        # per-active-segment split: map segment ids -> active redefines per
        # UNIQUE id; same-active ids merge into one integer-code mask
        by_segment: Dict[str, np.ndarray] = {}
        if segment_ids is None:
            by_segment[""] = kept
        else:
            for active in set(segment_ids.map_uniq(
                    self.segment_redefine_map)):
                mask = segment_ids.mask_of_mapped(
                    self.segment_redefine_map, active)
                positions = np.nonzero(keep & mask)[0]
                if positions.size:
                    by_segment[active] = positions

        for active, positions in by_segment.items():
            decoder = self._decoder_for_segment(active, backend)
            decoded = decoder.decode_raw(
                data, offsets[positions], lengths[positions],
                start_offset=start)
            result.segments.append(SegmentBatch(
                decoded, active or None,
                positions.astype(np.int64),
                start_record_id + positions.astype(np.int64),
                seg_level_ids=(
                    level_ids_per_record.take(positions)
                    if level_ids_per_record is not None else None)))

    def _pushdown_kept(self, pushdown, kept: np.ndarray, data,
                       offsets: np.ndarray, lengths: np.ndarray,
                       segment_ids, start: int, backend: str,
                       n_scanned: int) -> np.ndarray:
        """Pushdown over the kept records of a framed shard: segment-id
        conjuncts drop on the raw id bytes (depth 2, no decode at
        all), then the stage-1 decode of ONLY the filter columns
        evaluates the value predicate — per active segment, so a field
        owned by one redefine evaluates null (and therefore drops) on
        other segments' records, exactly like a post-hoc filter on the
        assembled nested table."""
        pruned_segment = 0
        bytes_skipped = 0
        if pushdown.segment_values is not None and segment_ids is not None \
                and len(kept):
            mask = segment_ids.mask_of(set(pushdown.segment_values))[kept]
            pruned_segment = len(kept) - int(mask.sum())
            if pruned_segment:
                bytes_skipped += int(lengths[kept][~mask].sum())
            kept = kept[mask]
        pruned_filter = 0
        if pushdown.value_expr is not None and len(kept):
            if segment_ids is None or not self.segment_redefine_map:
                mask = pushdown.mask_raw(
                    self, "", backend, data, offsets[kept],
                    lengths[kept], start_offset=start)
            else:
                mask = np.zeros(len(kept), dtype=bool)
                for active in set(segment_ids.map_uniq(
                        self.segment_redefine_map)):
                    amask = segment_ids.mask_of_mapped(
                        self.segment_redefine_map, active)[kept]
                    idx = np.nonzero(amask)[0]
                    if not len(idx):
                        continue
                    sub = kept[idx]
                    m = pushdown.mask_raw(
                        self, active, backend, data, offsets[sub],
                        lengths[sub], start_offset=start)
                    mask[idx[m]] = True
            pruned_filter = len(kept) - int(mask.sum())
            if pruned_filter:
                bytes_skipped += int(lengths[kept][~mask].sum())
            kept = kept[mask]
        pushdown.stats.note(scanned=n_scanned,
                            pruned_segment=pruned_segment,
                            pruned_filter=pruned_filter,
                            bytes_skipped=bytes_skipped)
        return kept

    def read_rows_columnar(self, stream: SimpleStream, file_id: int = 0,
                           backend: str = "numpy",
                           segment_id_prefix: Optional[str] = None,
                           start_record_id: int = 0,
                           starting_file_offset: int = 0) -> List[List[object]]:
        return self.read_result_columnar(
            stream, file_id=file_id, backend=backend,
            segment_id_prefix=segment_id_prefix,
            start_record_id=start_record_id,
            starting_file_offset=starting_file_offset).to_rows()

    def read_result_columnar(self, stream: SimpleStream, file_id: int = 0,
                             backend: str = "numpy",
                             segment_id_prefix: Optional[str] = None,
                             start_record_id: int = 0,
                             starting_file_offset: int = 0,
                             stage_times=None) -> FileResult:
        """Frame all records, pack per-active-segment padded batches, decode
        with the batched kernels; rows/Arrow are materialized lazily from
        the FileResult. `stage_times`: optional profiling.StageTimes —
        the pipeline engine passes it to attribute read/frame/decode busy
        time."""
        params = self.params
        ledger = params.new_diagnostics() if params.is_permissive else None
        result = FileResult(
            n_rows=0,
            file_id=file_id,
            input_file_name=stream.input_file_name,
            policy=params.schema_policy,
            generate_record_id=params.generate_record_id,
            generate_input_file_field=bool(params.input_file_name_column),
            corrupt_record_field=params.corrupt_record_column,
            diagnostics=ledger)
        if self.copybook.is_hierarchical or self.dynamic_occurs_layout:
            # hierarchical nesting / per-record offset shifts have no
            # static columnar plan (reference extractHierarchicalRecord,
            # RecordExtractors.scala:211; VarOccursRecordExtractor) — but
            # hierarchical VALUES still come from batched kernels: the
            # decode-once batch feeds a span-based Arrow assembly (no
            # Python rows) and a lazy nesting walk for the row path
            ctx = None
            if (self.copybook.is_hierarchical
                    and not self.dynamic_occurs_layout
                    and not params.variable_size_occurs):
                ctx = self._hierarchical_columnar_setup(
                    stream, backend, ledger=ledger,
                    stage_times=stage_times)
            if ctx is not None:
                from .hierarchical_arrow import hierarchical_table

                result.n_rows = ctx["n_roots"]
                result.rows_factory = (
                    lambda: self._read_rows_hierarchical_columnar(
                        ctx, file_id, start_record_id))
                result.arrow_factory = (
                    lambda output_schema: hierarchical_table(
                        ctx["batch"], ctx["segment_names"],
                        self.copybook, output_schema,
                        ctx["sid_map"],
                        ctx["parent_child_map"], ctx["root_names"],
                        file_id=file_id,
                        start_record_id=start_record_id,
                        input_file_name=ctx["input_file_name"])
                    if ctx["n"] else None)
                if self.pushdown is not None:
                    # no static columnar plan -> the whole filter runs
                    # post-decode on the assembled table (correct,
                    # unpruned; the explain report calls this depth out)
                    self.pushdown.filter_result_generic(
                        result, self._output_schema())
                return result
            rows = list(self.iter_rows(
                stream, file_id=file_id,
                start_record_id=start_record_id,
                starting_file_offset=starting_file_offset,
                segment_id_prefix=segment_id_prefix,
                ledger=ledger))
            result.rows = rows
            result.n_rows = len(rows)
            if self.pushdown is not None:
                self.pushdown.filter_result_generic(
                    result, self._output_schema())
            return result
        fast = self._frame_fast(stream, ledger=ledger,
                                stage_times=stage_times)
        if fast is not None:
            data, base, offsets, lengths, segment_ids, reasons = fast
            result.records_framed = len(offsets)
            with timed_stage(stage_times, "decode"):
                self._read_result_fast(
                    result, data, base, offsets, lengths, segment_ids,
                    file_id, backend,
                    segment_id_prefix or default_segment_id_prefix(),
                    start_record_id, corrupt_reasons=reasons)
            return result
        seg = params.multisegment
        prefix = segment_id_prefix or default_segment_id_prefix()
        accumulator = (SegmentIdAccumulator(seg.segment_level_ids, prefix, file_id)
                       if seg else None)
        level_count = len(seg.segment_level_ids) if seg else 0
        segment_filter = set(seg.segment_id_filter) if seg and seg.segment_id_filter else None
        pushdown = self.pushdown
        pd_segments = (set(pushdown.segment_values)
                       if pushdown is not None
                       and pushdown.segment_values is not None else None)
        pd_scanned = pd_pruned_segment = pd_pruned_filter = 0
        pd_bytes_skipped = 0

        framed = []   # (record_index, active_redefine, data, level_ids)
        record_reader = self.make_record_reader(
            stream, start_record_id, starting_file_offset, ledger)
        with timed_stage(stage_times, "frame"):
            while record_reader.has_next():
                record_index = record_reader.record_index + 1
                segment_id, data = next(record_reader)
                level_ids: List[Optional[str]] = []
                if level_count and accumulator is not None:
                    accumulator.acquired_segment_id(segment_id,
                                                    record_index)
                    level_ids = [accumulator.get_segment_level_id(i)
                                 for i in range(level_count)]
                if level_ids and level_ids[0] is None:
                    continue
                if segment_filter is not None \
                        and segment_id not in segment_filter:
                    continue
                if pushdown is not None:
                    pd_scanned += 1
                    if pd_segments is not None \
                            and segment_id not in pd_segments:
                        # depth-2 pushdown: the segment-id conjunct
                        # drops the record at framing time
                        pd_pruned_segment += 1
                        pd_bytes_skipped += len(data)
                        continue
                active = self.segment_redefine_map.get(segment_id, "")
                framed.append((record_index, active, data, level_ids))
        result.records_framed = (record_reader.record_index + 1
                                 - start_record_id)
        if record_reader.corrupt_reasons:
            # absolute record indices -> output positions of kept rows
            pos_of = {idx: pos for pos, (idx, _, _, _) in enumerate(framed)}
            result.corrupt_row_reasons = {
                pos_of[idx]: reason
                for idx, reason in record_reader.corrupt_reasons.items()
                if idx in pos_of}

        start = params.start_offset
        by_segment: Dict[str, List[int]] = {}
        for pos, (_, active, _, _) in enumerate(framed):
            by_segment.setdefault(active, []).append(pos)

        result.n_rows = len(framed)
        with timed_stage(stage_times, "decode"):
            for active, positions in by_segment.items():
                decoder = self._decoder_for_segment(active, backend)
                # pack to the plan's byte extent, not the full record
                # size — narrow segments of a wide copybook decode from
                # narrow matrices (wide enough for the stage-1 filter
                # columns too: the predicate may reach past the
                # projected plan)
                rs = decoder.plan.max_extent
                if pushdown is not None \
                        and pushdown.value_expr is not None:
                    rs = max(rs, pushdown._stage1_decoder(
                        self, active, backend).plan.max_extent)
                batch = np.zeros((len(positions), rs), dtype=np.uint8)
                lengths = np.zeros(len(positions), dtype=np.int64)
                for row_i, pos in enumerate(positions):
                    payload = framed[pos][2][start: start + rs]
                    batch[row_i, :len(payload)] = np.frombuffer(payload,
                                                                np.uint8)
                    lengths[row_i] = len(payload)
                if pushdown is not None \
                        and pushdown.value_expr is not None \
                        and len(positions):
                    keep = pushdown.mask_matrix(self, active, backend,
                                                batch, lengths)
                    if not keep.all():
                        dropped = int(len(keep) - keep.sum())
                        pd_pruned_filter += dropped
                        # FULL record bytes, not the stage-extent-
                        # clamped payload — bytes_skipped must agree
                        # with the fast path for the same file+filter
                        pd_bytes_skipped += sum(
                            len(framed[p][2])
                            for p, k in zip(positions, keep) if not k)
                        result.n_rows -= dropped
                        batch = batch[keep]
                        lengths = lengths[keep]
                        positions = [p for p, k in zip(positions, keep)
                                     if k]
                        if not positions:
                            continue
                decoded = decoder.decode(batch, lengths=lengths)
                has_levels = level_count > 0
                result.segments.append(SegmentBatch(
                    decoded, active or None,
                    np.asarray(positions, dtype=np.int64),
                    np.asarray([framed[p][0] for p in positions],
                               dtype=np.int64),
                    seg_level_ids=([framed[p][3] for p in positions]
                                   if has_levels else None)))
        if pushdown is not None:
            pushdown.stats.note(scanned=pd_scanned,
                                pruned_segment=pd_pruned_segment,
                                pruned_filter=pd_pruned_filter,
                                bytes_skipped=pd_bytes_skipped)
        return result


    def _output_schema(self):
        """The read's CobolOutputSchema, built reader-side for the
        generic (post-decode) filter paths through the SAME shared
        constructor the API layer uses, so the filtered table types
        identically (FileResult.to_arrow then serves it for the API's
        structurally-equal schema instance)."""
        from .schema import output_schema_for

        return output_schema_for(self.copybook, self.params,
                                 is_var_len=True)


def file_record_id_base(file_order: int) -> int:
    """Deterministic Record_Id base per file (reference Constants.scala:28)."""
    return file_order * DEFAULT_FILE_RECORD_ID_INCREMENT
