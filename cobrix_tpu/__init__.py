"""cobrix_tpu — a TPU-native COBOL copybook / EBCDIC mainframe data framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of Cobrix
(SudhirNikam/cobrix): parse COBOL copybooks, decode EBCDIC binary files
(fixed-length, variable-length RDW/BDW, multisegment, hierarchical) into
columnar data — with the per-record decode loop replaced by batched TPU
byte-transcoding kernels over `[batch, record_len]` uint8 arrays.
"""
from .api import CobolData, read_cobol
from .explain import ScanReport, explain
from .copybook.copybook import Copybook, merge_copybooks, parse_copybook
from .reader.diagnostics import (ReadDiagnostics, RecordErrorPolicy,
                                 ShardErrorPolicy, ShardFailureInfo)
from .reader.handlers import (DictHandler, JsonHandler, RecordHandler,
                              TupleHandler)
from .obs import ScanProgress, Tracer, prometheus_text
from .profiling import ReadMetrics, profile_trace
from .reader.stream import (ByteRangeSource, open_stream,
                            register_stream_backend, source_size)
from .io import IoConfig, register_fsspec_backend
from .streaming import ContinuousIngestor, SourceTruncated, tail_cobol
from .sink import (DatasetSink, SinkCorruption, SinkSchemaError,
                   read_dataset, sink_cobol)
from . import query
from .copybook.datatypes import (
    CommentPolicy,
    DebugFieldsPolicy,
    Encoding,
    FloatingPointFormat,
    SchemaRetentionPolicy,
    TrimPolicy,
    Usage,
)

__version__ = "0.1.0"

__all__ = [
    "CobolData",
    "read_cobol",
    "ScanReport",
    "explain",
    "Copybook",
    "parse_copybook",
    "merge_copybooks",
    "CommentPolicy",
    "DebugFieldsPolicy",
    "Encoding",
    "FloatingPointFormat",
    "SchemaRetentionPolicy",
    "TrimPolicy",
    "Usage",
    "RecordHandler",
    "TupleHandler",
    "DictHandler",
    "JsonHandler",
    "ByteRangeSource",
    "open_stream",
    "register_stream_backend",
    "source_size",
    "IoConfig",
    "register_fsspec_backend",
    "ContinuousIngestor",
    "tail_cobol",
    "SourceTruncated",
    "DatasetSink",
    "SinkCorruption",
    "SinkSchemaError",
    "read_dataset",
    "sink_cobol",
    "ReadMetrics",
    "profile_trace",
    "ScanProgress",
    "Tracer",
    "prometheus_text",
    "ReadDiagnostics",
    "RecordErrorPolicy",
    "ShardErrorPolicy",
    "ShardFailureInfo",
    "query",
]
