"""Sparse index generation — the sequential-to-parallel bridge.

One sequential pass over a variable-length stream produces split points every
N records / M MB so shards can be decoded in parallel; for hierarchical data
splits land only at root-segment boundaries, and size-based splitting carries
the drift so shard boundaries stay aligned with storage blocks. Mirrors the
reference IndexGenerator.sparseIndexGenerator (reader/index/IndexGenerator.scala:33-127)
and SparseIndexEntry (reader/index/entry/SparseIndexEntry.scala:19).

In the TPU design the index entries become the unit of host-side data
parallelism: each entry maps to one byte-range shard a host worker frames
and ships to the device as a `[batch, max_len]` block (SURVEY.md §2.5).
"""
from __future__ import annotations

import logging
import os
from dataclasses import dataclass, replace
from typing import List, Optional

from ..copybook.ast import Primitive
from ..copybook.copybook import Copybook
from .diagnostics import (
    DEFAULT_RESYNC_WINDOW,
    ReadDiagnostics,
    RecordErrorPolicy,
)
from .header_parsers import RdwHeaderParser, RecordHeaderParser
from .parameters import DEFAULT_INDEX_ENTRY_SIZE_MB, MEGABYTE
from .raw_extractors import RawRecordExtractor
from .recovery import (
    PendingReader,
    generic_blob_validator,
    rdw_blob_validator,
    resync_stream,
)
from .stream import SimpleStream


@dataclass(frozen=True)
class SparseIndexEntry:
    offset_from: int
    offset_to: int      # -1 = to end of file
    file_id: int
    record_index: int


def file_index_entries(reader, file_path: str, file_order: int, params,
                       retry=None, on_retry=None, io=None
                       ) -> Optional[List[SparseIndexEntry]]:
    """Sparse index for one file, or None when a single shard suffices —
    the chunk-planning primitive shared by the threaded indexed scan, the
    multi-host executor, and the chunked pipeline engine
    (cobrix_tpu.engine.chunks). The vectorized RDW index is used when the
    configuration allows it; otherwise the generic per-record generator
    (the reference's only mode, IndexGenerator.scala:33) runs.

    With `io.cache_dir` set, computed entries persist in the sparse-index
    store (cobrix_tpu.io.index_store) keyed by file fingerprint +
    framing-config fingerprint: the sequential indexing pass runs once
    per file version, and warm re-scans load the shard plan directly."""
    from .parameters import DEFAULT_INDEX_ENTRY_SIZE_MB, MEGABYTE
    from .stream import open_stream, path_scheme

    explicit = (params.input_split_records is not None
                or params.input_split_size_mb is not None)
    split_mb = params.input_split_size_mb or DEFAULT_INDEX_ENTRY_SIZE_MB

    def too_small(size: int) -> bool:
        if size == 0:
            return True  # nothing to index (and mmap rejects empty files)
        # the whole file is one shard anyway
        return not explicit and size <= split_mb * MEGABYTE

    store = config_fp = io_stats = None
    if io is not None and io.cache_enabled:
        from ..io.index_store import (SparseIndexStore,
                                      index_config_fingerprint)
        from ..io.stats import current_io_stats

        try:
            store = SparseIndexStore(io.cache_dir)
            config_fp = index_config_fingerprint(reader, params)
            io_stats = current_io_stats()
        except OSError:
            # unusable cache volume (read-only / full): index without
            # persistence — the cache must never fail the scan
            store = None

    def from_store(fingerprint: str):
        cached = store.load(file_path, fingerprint, config_fp, file_order)
        if io_stats is not None:
            io_stats.bump("index_hits" if cached is not None
                          else "index_misses")
        return cached

    def to_store(fingerprint: str, entries) -> None:
        if store is not None and entries is not None:
            store.save(file_path, fingerprint, config_fp, entries)
            if io_stats is not None:
                io_stats.bump("index_saves")

    from ..io.compress import active_codec, compressed_chunkable

    if not compressed_chunkable(file_path, io):
        # compressed input without a decompressed cache plane: byte-range
        # shards would each re-inflate the prefix, so one whole-file
        # shard (the streaming-discovery fallback) is strictly cheaper
        return None
    if path_scheme(file_path) in (None, "file") \
            and active_codec(file_path, io) is None:
        if too_small(os.path.getsize(file_path)):
            return None
        fingerprint = None
        if store is not None:
            st = os.stat(file_path)
            fingerprint = f"local:{st.st_size}:{st.st_mtime_ns}"
            cached = from_store(fingerprint)
            if cached is not None:
                return cached
        entries = None
        if reader.supports_fast_framing:
            # mmap, not read(): the scan touches the whole file once to
            # find split offsets; materializing it would spike RSS by the
            # file size on exactly the large files indexing targets
            import mmap

            with open(file_path, "rb") as f:
                mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                try:
                    entries = reader.generate_index_fast(mm, file_order)
                finally:
                    try:
                        mm.close()
                    except BufferError:
                        # a FramingError in flight still references the
                        # map through its traceback; closing here would
                        # MASK that actionable error with a BufferError —
                        # the map is released when the exception is
                        pass
        if entries is None:
            with open_stream(file_path) as stream:
                entries = reader.generate_index(stream, file_order)
        to_store(fingerprint, entries)
        return entries
    # registry-backed storage (and compressed local files, whose raw
    # bytes cannot be mmap-framed): one stream serves the size probe,
    # the fingerprint probe, and the index scan (a backend open is
    # typically a network round trip; a cold compressed open is the
    # discovery inflate)
    with open_stream(file_path, retry=retry, on_retry=on_retry,
                     io=io) as stream:
        if too_small(stream.size()):
            return None
        fingerprint = None
        if store is not None:
            source = getattr(stream, "source", None)
            if source is not None:
                fingerprint = source.fingerprint()
                cached = from_store(fingerprint)
                if cached is not None:
                    return cached
        entries = reader.generate_index(stream, file_order)
    if fingerprint is not None:
        to_store(fingerprint, entries)
    return entries


class IncrementalIndexer:
    """Sparse-index construction one record at a time — the streaming
    twin of `sparse_index_generator` for the continuous-ingest tailer.

    A growing file cannot be indexed by a one-shot sequential pass (the
    pass would never end), but the tailer already frames every record as
    it stabilizes; feeding those framings here keeps the sparse index
    CURRENT at every watermark, so when the generation finalizes
    (rotation, stream shutdown) the complete entries persist to the
    index store and the very first batch `read_cobol` of the rotated
    file goes straight to shard planning — no re-index pass.

    Split arithmetic mirrors `sparse_index_generator` exactly for the
    non-hierarchical case (records-per-entry, or size-per-entry with
    drift carry); `state_dict()`/`from_state` round-trip through the
    ingest checkpoint so a crashed tailer resumes indexing from its
    watermark instead of record zero. Hierarchical root-boundary
    alignment needs segment inspection the live tailer refuses anyway
    (see streaming.ingest), so it is unsupported here."""

    def __init__(self, records_per_entry: Optional[int] = None,
                 size_per_entry_mb: Optional[int] = None):
        self.records_per_entry = records_per_entry
        self.size_per_entry_mb = size_per_entry_mb
        self._bytes_per_entry = (size_per_entry_mb
                                 or DEFAULT_INDEX_ENTRY_SIZE_MB) * MEGABYTE
        self.byte_index = 0
        self.record_index = 0
        self.records_in_chunk = 0
        self.bytes_in_chunk = 0
        # (offset_from, record_index) split points; entry 0 is implicit
        self._splits: List[List[int]] = [[0, 0]]
        # one-record lookahead: the one-shot generator detects EOF
        # BEFORE its split branch, so the stream's LAST record can
        # never open a new entry — mirrored here by applying each
        # record only once a successor proves it was not last
        self._held: Optional[List] = None

    def _need_split(self) -> bool:
        if self.records_per_entry is not None:
            return self.records_in_chunk >= self.records_per_entry
        return self.bytes_in_chunk >= self._bytes_per_entry

    def add_record(self, record_size: int, is_valid: bool = True) -> None:
        """One framed record, in stream order (`record_size` includes
        its header bytes — the full stream distance it consumed)."""
        if self._held is not None:
            self._apply(*self._held)
        self._held = [int(record_size), bool(is_valid)]

    def _apply(self, record_size: int, is_valid: bool) -> None:
        if is_valid and self._need_split():
            self._splits.append([self.byte_index, self.record_index])
            self.records_in_chunk = 0
            if self.records_per_entry is None:
                # carry the size-split drift (sparse_index_generator's
                # block-alignment rule)
                self.bytes_in_chunk -= self._bytes_per_entry
            else:
                self.bytes_in_chunk = 0
        self.record_index += 1
        self.records_in_chunk += 1
        self.byte_index += record_size
        self.bytes_in_chunk += record_size

    def entries(self, file_id: int) -> List[SparseIndexEntry]:
        """The sparse index as of the records fed so far (the last entry
        is open-ended, matching the one-shot generator's output; the
        held lookahead record never contributes a split, exactly like
        the generator's last record)."""
        out: List[SparseIndexEntry] = []
        for i, (offset_from, record_index) in enumerate(self._splits):
            offset_to = (self._splits[i + 1][0]
                         if i + 1 < len(self._splits) else -1)
            out.append(SparseIndexEntry(offset_from, offset_to, file_id,
                                        record_index))
        return out

    def state_dict(self) -> dict:
        return {
            "records_per_entry": self.records_per_entry,
            "size_per_entry_mb": self.size_per_entry_mb,
            "byte_index": self.byte_index,
            "record_index": self.record_index,
            "records_in_chunk": self.records_in_chunk,
            "bytes_in_chunk": self.bytes_in_chunk,
            "splits": [list(s) for s in self._splits],
            "held": list(self._held) if self._held else None,
        }

    @classmethod
    def from_state(cls, state: dict) -> "IncrementalIndexer":
        indexer = cls(records_per_entry=state.get("records_per_entry"),
                      size_per_entry_mb=state.get("size_per_entry_mb"))
        indexer.byte_index = int(state.get("byte_index", 0))
        indexer.record_index = int(state.get("record_index", 0))
        indexer.records_in_chunk = int(state.get("records_in_chunk", 0))
        indexer.bytes_in_chunk = int(state.get("bytes_in_chunk", 0))
        splits = state.get("splits") or [[0, 0]]
        indexer._splits = [[int(a), int(b)] for a, b in splits]
        held = state.get("held")
        indexer._held = ([int(held[0]), bool(held[1])] if held
                         else None)
        return indexer


def sparse_index_generator(file_id: int,
                           data_stream: SimpleStream,
                           record_header_parser: Optional[RecordHeaderParser] = None,
                           record_extractor: Optional[RawRecordExtractor] = None,
                           records_per_index_entry: Optional[int] = None,
                           size_per_index_entry_mb: Optional[int] = None,
                           copybook: Optional[Copybook] = None,
                           segment_field: Optional[Primitive] = None,
                           is_hierarchical: bool = False,
                           root_segment_id: str = "",
                           record_error_policy: RecordErrorPolicy =
                           RecordErrorPolicy.FAIL_FAST,
                           resync_window_bytes: int = DEFAULT_RESYNC_WINDOW,
                           ledger: Optional[ReadDiagnostics] = None
                           ) -> List[SparseIndexEntry]:
    root_segment_ids = root_segment_id.split(",")
    byte_index = 0
    index: List[SparseIndexEntry] = [SparseIndexEntry(0, -1, file_id, 0)]
    root_record_id = ""
    records_in_chunk = 0
    bytes_in_chunk = 0
    record_index = 0
    is_really_hierarchical = (copybook is not None and segment_field is not None
                              and is_hierarchical)
    is_split_by_size = records_per_index_entry is None
    if records_per_index_entry is not None:
        def need_split(records: int, size: int) -> bool:
            return records >= records_per_index_entry
    else:
        bytes_per_entry = (size_per_index_entry_mb
                           or DEFAULT_INDEX_ENTRY_SIZE_MB) * MEGABYTE

        def need_split(records: int, size: int) -> bool:
            return size >= bytes_per_entry

    def get_segment_id(record: bytes) -> str:
        value = copybook.extract_primitive_field(segment_field, record)
        return "" if value is None else str(value).strip()

    permissive = record_error_policy is not RecordErrorPolicy.FAIL_FAST
    if permissive and ledger is None:
        ledger = ReadDiagnostics()
    reader = PendingReader(data_stream)

    def header_validator():
        if type(record_header_parser) is RdwHeaderParser:
            return rdw_blob_validator(record_header_parser)
        return generic_blob_validator(record_header_parser,
                                      data_stream.size(), reader.offset)

    end_of_file = False
    while not end_of_file:
        record = None
        if record_extractor is not None:
            offset0 = record_extractor.offset
            if record_extractor.has_next():
                record = next(record_extractor)
                is_valid = True
            else:
                is_valid = False
            record_size = record_extractor.offset - offset0
            has_more = record_extractor.has_next()
        else:
            header = reader.read(record_header_parser.header_length)
            while True:
                try:
                    meta = record_header_parser.get_record_metadata(
                        header, reader.offset, data_stream.size(),
                        record_index)
                    break
                except ValueError as exc:
                    # corruption tolerance mirrors VRLRecordReader so the
                    # index pass and the shard framers skip identically
                    if not permissive:
                        raise
                    header = resync_stream(
                        reader, header, header_validator(),
                        record_header_parser.header_length,
                        resync_window_bytes, ledger,
                        data_stream.input_file_name,
                        getattr(exc, "reason", str(exc)))
                    if header is None:
                        meta = None
                        break
            if meta is None:
                record_size = reader.offset - byte_index
                has_more = False
                is_valid = False
            else:
                if meta.record_length > 0:
                    record = reader.read(meta.record_length)
                record_size = reader.offset - byte_index
                has_more = record_size > 0
                is_valid = meta.is_valid

        if (record_extractor is None and reader.at_end) \
                or (record_extractor is not None
                    and data_stream.is_end_of_stream) or not has_more:
            end_of_file = True
        elif is_valid:
            if is_really_hierarchical and not root_record_id:
                cur = get_segment_id(record)
                if (cur and not root_segment_ids) or cur in root_segment_ids:
                    root_record_id = cur
            if need_split(records_in_chunk, bytes_in_chunk):
                if (not is_really_hierarchical
                        or get_segment_id(record) in root_segment_ids):
                    entry = SparseIndexEntry(byte_index, -1, file_id, record_index)
                    index[-1] = replace(index[-1], offset_to=entry.offset_from)
                    index.append(entry)
                    records_in_chunk = 0
                    if is_split_by_size:
                        # carry the size-split drift so shard boundaries stay
                        # aligned with storage blocks
                        bytes_in_chunk -= (size_per_index_entry_mb
                                           or DEFAULT_INDEX_ENTRY_SIZE_MB) * MEGABYTE
                    else:
                        bytes_in_chunk = 0
        # NOTE: invalid records (file headers/footers) ARE counted, mirroring
        # the reference exactly (IndexGenerator.scala:117-120 increments
        # unconditionally) — even though VRLRecordReader skips invalid
        # records without numbering them. The resulting Record_Id shift
        # after a file header on indexed reads is reference behavior.
        record_index += 1
        records_in_chunk += 1
        byte_index += record_size
        bytes_in_chunk += record_size
    if is_really_hierarchical and root_segment_id and not root_record_id:
        logging.getLogger(__name__).error(
            "Root segment %s=='%s' not found in the data file.",
            segment_field.name, root_segment_id)
    elif is_really_hierarchical and not root_record_id:
        logging.getLogger(__name__).error(
            "Root segment %s is empty for every record in the data file.",
            segment_field.name)
    return index
