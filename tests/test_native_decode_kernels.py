"""Native C++ columnar decode kernels vs the numpy blueprint.

The C++ kernels (native/framing.cpp decode_*_cols) must match
ops/batch_np exactly on arbitrary bytes — batch_np is itself pinned to
the reference's scalar semantics by tests/test_scalar_decoders.py, so
agreement here transitively pins the native path to the reference's
malformed->null policy (DecoderSelector.scala:283-291).
"""
import numpy as np
import pytest

from cobrix_tpu import native
from cobrix_tpu.ops import batch_np

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable")


def _random_batch(rng, n, extent):
    return rng.integers(0, 256, size=(n, extent), dtype=np.uint8)


def _adversarial_bytes(rng, n, extent):
    """Bytes biased toward the interesting classes: digits, signs,
    spaces, sign nibbles, zeros."""
    pool = np.array(
        [0x00, 0x0C, 0x0D, 0x0F, 0x1C, 0x1D, 0x20, 0x2B, 0x2D, 0x2E,
         0x2C, 0x30, 0x39, 0x40, 0x4B, 0x4E, 0x60, 0x6B, 0x80, 0x99,
         0xC0, 0xC9, 0xD0, 0xD9, 0xF0, 0xF9, 0xFF], dtype=np.uint8)
    return pool[rng.integers(0, len(pool), size=(n, extent))]


@pytest.mark.parametrize("width", [1, 2, 4, 8])
@pytest.mark.parametrize("signed", [False, True])
@pytest.mark.parametrize("big_endian", [False, True])
def test_binary_parity(width, signed, big_endian):
    rng = np.random.default_rng(width * 100 + signed * 10 + big_endian)
    batch = _random_batch(rng, 64, 64)
    offsets = np.arange(0, 48, width, dtype=np.int64)
    res = native.decode_binary_cols(batch, offsets, width, signed, big_endian)
    slab = batch[:, offsets[:, None] + np.arange(width)[None, :]]
    exp_v, exp_ok = batch_np.decode_binary(slab, signed, big_endian)
    np.testing.assert_array_equal(res[0], exp_v)
    np.testing.assert_array_equal(res[1], exp_ok)


@pytest.mark.parametrize("width", [1, 2, 3, 5, 10])
@pytest.mark.parametrize("gen", ["random", "adversarial"])
def test_bcd_parity(width, gen):
    rng = np.random.default_rng(width)
    make = _random_batch if gen == "random" else _adversarial_bytes
    batch = make(rng, 128, 64)
    offsets = np.arange(0, 50, width, dtype=np.int64)
    res = native.decode_bcd_cols(batch, offsets, width)
    slab = batch[:, offsets[:, None] + np.arange(width)[None, :]]
    exp_v, exp_ok = batch_np.decode_bcd(slab)
    np.testing.assert_array_equal(res[0], exp_v)
    np.testing.assert_array_equal(res[1], exp_ok)


@pytest.mark.parametrize("kind,blueprint", [
    (native.DISPLAY_EBCDIC, batch_np.decode_display_ebcdic),
    (native.DISPLAY_ASCII, batch_np.decode_display_ascii),
])
@pytest.mark.parametrize("signed", [False, True])
@pytest.mark.parametrize("allow_dot", [False, True])
@pytest.mark.parametrize("require_digits", [False, True])
@pytest.mark.parametrize("gen", ["random", "adversarial"])
def test_display_parity(kind, blueprint, signed, allow_dot, require_digits,
                        gen):
    rng = np.random.default_rng(
        kind * 31 + signed * 7 + allow_dot * 3 + require_digits)
    make = _random_batch if gen == "random" else _adversarial_bytes
    batch = make(rng, 128, 72)
    width = 6
    offsets = np.arange(0, 72 - width, width, dtype=np.int64)
    res = native.decode_display_cols(
        batch, offsets, width, kind, signed, allow_dot, require_digits)
    slab = batch[:, offsets[:, None] + np.arange(width)[None, :]]
    exp_v, exp_ok, exp_dots = blueprint(slab, signed, allow_dot,
                                        require_digits)
    np.testing.assert_array_equal(res[0], exp_v)
    np.testing.assert_array_equal(res[1], exp_ok)
    np.testing.assert_array_equal(res[2], exp_dots)


def test_int64_wraparound_parity():
    """>18-digit BCD mantissas wrap identically in C++ (uint64 internally)
    and numpy int64 (JVM Long multiply-add semantics)."""
    # 12 bytes = 23 digits, all 9s, positive sign -> wraps
    rec = bytes([0x99] * 11 + [0x9C])
    batch = np.frombuffer(rec, np.uint8)[None, :].copy()
    offsets = np.array([0], dtype=np.int64)
    res = native.decode_bcd_cols(batch, offsets, 12)
    exp_v, exp_ok = batch_np.decode_bcd(batch[:, None, :])
    np.testing.assert_array_equal(res[0], exp_v)
    np.testing.assert_array_equal(res[1], exp_ok)
