"""Fused Pallas decode kernel parity vs the numpy blueprint kernels.

Runs in Pallas interpret mode on CPU (conftest pins JAX to the virtual CPU
mesh); the same code path compiles with Mosaic on a real TPU.
"""
import numpy as np
import pytest

from cobrix_tpu import parse_copybook
from cobrix_tpu.ops import pallas_tpu
from cobrix_tpu.reader.columnar import ColumnarDecoder, _pallas_group_spec
from cobrix_tpu.testing.generators import EXP3_COPYBOOK, generate_exp3

from conftest import jax_usable

pytestmark = pytest.mark.skipif(not jax_usable(), reason="jax backend unusable")


def test_offsets_progression():
    assert pallas_tpu.offsets_progression([10]) == (10, 0)
    assert pallas_tpu.offsets_progression([4, 12, 20]) == (4, 8)
    assert pallas_tpu.offsets_progression([4, 12, 21]) is None
    assert pallas_tpu.offsets_progression([12, 4]) is None
    assert pallas_tpu.offsets_progression([]) is None


def test_binary_group_parity_all_variants():
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=(64, 200), dtype=np.uint8)
    for signed in (False, True):
        for big_endian in (False, True):
            for width in (1, 2, 3, 4):
                g = pallas_tpu.StridedGroup(
                    base=8, stride=16, count=12, width=width, kind="binary",
                    signed=signed, big_endian=big_endian)
                fn = pallas_tpu.build_fused_decode([g], data.shape[1])
                (values, valid), = fn(data)
                # numpy oracle
                from cobrix_tpu.ops import batch_np
                offs = 8 + 16 * np.arange(12)
                slab = data[:, offs[:, None] + np.arange(width)[None, :]]
                exp_v, exp_ok = batch_np.decode_binary(
                    slab, signed, big_endian)
                np.testing.assert_array_equal(np.asarray(valid), exp_ok)
                np.testing.assert_array_equal(
                    np.asarray(values)[exp_ok], exp_v[exp_ok])


def test_bcd_group_parity():
    rng = np.random.default_rng(8)
    data = rng.integers(0, 256, size=(32, 128), dtype=np.uint8)
    # make some valid BCD fields
    for i in range(0, 32, 2):
        for k in range(10):
            data[i, 4 + 8 * k:4 + 8 * k + 3] = [0x12, 0x34, 0x5C]
    for width in (2, 3, 4, 5):
        g = pallas_tpu.StridedGroup(base=4, stride=8, count=10, width=width,
                                    kind="bcd")
        fn = pallas_tpu.build_fused_decode([g], data.shape[1])
        (values, valid), = fn(data)
        from cobrix_tpu.ops import batch_np
        offs = 4 + 8 * np.arange(10)
        slab = data[:, offs[:, None] + np.arange(width)[None, :]]
        exp_v, exp_ok = batch_np.decode_bcd(slab)
        np.testing.assert_array_equal(np.asarray(valid), exp_ok)
        np.testing.assert_array_equal(np.asarray(values)[exp_ok], exp_v[exp_ok])


def test_tail_field_region_past_record_end():
    """A strided group whose last field ends at the row boundary must not
    read out of bounds (the wrapper pads the row)."""
    data = np.full((5, 20), 0x00, dtype=np.uint8)
    data[:, 16:20] = 0x01
    g = pallas_tpu.StridedGroup(base=16, stride=0, count=1, width=4,
                                kind="binary", signed=False, big_endian=True)
    fn = pallas_tpu.build_fused_decode([g], data.shape[1])
    (values, valid), = fn(data)
    assert np.asarray(values).tolist() == [[0x01010101]] * 5


class TestColumnarPallasBackend:
    """End-to-end: ColumnarDecoder(backend='pallas') == backend='numpy' on
    the exp3 wide-segment profile (2000-element COMP + COMP-3 OCCURS)."""

    @pytest.fixture(scope="class")
    def copybook(self):
        return parse_copybook(EXP3_COPYBOOK)

    def test_exp3_wide_segment_parity(self, copybook):
        # frame the RDW stream on host and keep the wide 'C' records
        raw = generate_exp3(60, seed=11)
        records, pos = [], 0
        while pos < len(raw):
            length = raw[pos + 2] | (raw[pos + 3] << 8)
            records.append(raw[pos + 4:pos + 4 + length])
            pos += 4 + length
        wide = [r for r in records if len(r) > 1000]
        assert len(wide) >= 10
        arr = np.frombuffer(b"".join(wide), dtype=np.uint8).reshape(
            len(wide), -1)
        dec_p = ColumnarDecoder(copybook, backend="pallas")
        dec_n = ColumnarDecoder(copybook, backend="numpy")
        # the wide numeric groups must actually take the fused kernel
        assert sum(1 for g in dec_p.kernel_groups
                   if _pallas_group_spec(g) is not None) >= 2
        out_p = dec_p.decode(arr)
        out_n = dec_n.decode(arr)
        for c in dec_p.plan.columns:
            for i in range(arr.shape[0]):
                assert out_p.value(c.index, i) == out_n.value(c.index, i), \
                    f"column {c.name} record {i}"
