"""cobrix_tpu.serve — the multi-tenant streaming serving tier.

Turns the library into a deployable service: long-lived scan servers
streaming Arrow record batches as the pipelined engine assembles them
(first-batch latency), with per-tenant admission control, weighted fair
queueing, shared warm cache planes, and `/metrics` + `/healthz`
endpoints. See the README's "Serving tier" section and
examples/serving_app.py for the horizontal-scale recipe.

    server:  srv = ScanServer(server_options={"cache_dir": "/cache"})
             srv.start()
    client:  for batch in stream_scan(srv.address, "s3://bucket/f.dat",
                                      copybook_contents=BOOK,
                                      tenant="etl"): ...
"""
from .admission import AdmissionController, AdmissionRejected, TenantQuota
from .client import ScanStream, connect, fetch_table, stream_scan
from .flight import flight_available
from .http import ObsHttpServer
from .protocol import ProtocolError, ServeError
from .server import ScanServer
from .session import OrderedBatchEmitter, ScanRequest, ScanSession

__all__ = [
    "AdmissionController",
    "AdmissionRejected",
    "TenantQuota",
    "ScanStream",
    "connect",
    "fetch_table",
    "stream_scan",
    "flight_available",
    "ObsHttpServer",
    "ProtocolError",
    "ServeError",
    "ScanServer",
    "OrderedBatchEmitter",
    "ScanRequest",
    "ScanSession",
]
