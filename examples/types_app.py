"""Fixed-length type-variety read (reference SparkTypesApp.scala:46-60):
generate the exp1 profile (TestDataGen6TypeVariety layout) and read it
into Arrow with the columnar kernels."""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cobrix_tpu import read_cobol
from cobrix_tpu.testing.generators import EXP1_COPYBOOK, generate_exp1


def main():
    data = generate_exp1(1000, seed=100)
    with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
        f.write(data.tobytes())
        path = f.name
    try:
        result = read_cobol(path, copybook_contents=EXP1_COPYBOOK,
                            schema_retention_policy="collapse_root")
        table = result.to_arrow()
    finally:
        os.unlink(path)
    print(f"{table.num_rows} rows x {table.num_columns} columns")
    print(table.slice(0, 3).to_pandas().iloc[:, :8])


if __name__ == "__main__":
    main()
