"""Regression pins for bugs found in code review.

Each test encodes the observable contract that was broken:
1. hierarchical copybooks through the default (columnar) backend,
2. PIC P (scale_factor) fields on the columnar path,
3. pedantic mode + debug_ignore_file_size,
4. occurs_mappings passed as a Python dict,
5. sparse-index record numbering across skipped header records.
"""
import json
import os
import tempfile

import numpy as np
import pytest

from cobrix_tpu import parse_copybook, read_cobol
from cobrix_tpu.plan.compiler import Codec, compile_plan
from cobrix_tpu.reader.columnar import ColumnarDecoder
from cobrix_tpu.reader.extractors import extract_record
from cobrix_tpu.testing.generators import ebcdic_encode


def _write(tmp, name, data: bytes) -> str:
    p = os.path.join(tmp, name)
    with open(p, "wb") as f:
        f.write(data)
    return p


def _rdw(length: int) -> bytes:
    """Big-endian RDW: length in bytes [0..1]."""
    return length.to_bytes(2, "big") + bytes([0, 0])


HIER_COPYBOOK = """
       01 RECORD.
          05 SEG-ID    PIC X(1).
          05 COMPANY REDEFINES SEG-ID-DATA.
             10 NAME   PIC X(5).
          05 CONTACT REDEFINES COMPANY.
             10 PHONE  PIC X(5).
"""


def test_hierarchical_default_backend_matches_host():
    """segment-children reads must produce nested rows on every backend."""
    copybook = """
       01 RECORD.
          05 SEG-ID    PIC X(1).
          05 COMPANY.
             10 NAME   PIC X(5).
          05 CONTACT REDEFINES COMPANY.
             10 PHONE  PIC X(5).
"""
    recs = [("C", "ACME "), ("P", "12345"), ("P", "67890"), ("C", "GLOBX")]
    payload = b"".join(
        _rdw(6) + ebcdic_encode(sid + body) for sid, body in recs)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "h.bin", payload)
        kwargs = dict(
            copybook_contents=copybook,
            is_record_sequence=True,
            is_rdw_big_endian="true",
            segment_field="SEG-ID",
            **{"redefine-segment-id-map:0": "COMPANY => C",
               "redefine-segment-id-map:1": "CONTACT => P",
               "segment-children:0": "COMPANY => CONTACT"})
        host = read_cobol(path, backend="host", **kwargs)
        default = read_cobol(path, backend="numpy", **kwargs)
        assert host.to_json_lines() == default.to_json_lines()
        assert len(host) == 2  # two root records with nested children


def test_scale_factor_display_columnar_matches_host():
    copybook = """
       01 REC.
          05 A PIC SVP(2)9(3).
          05 B PIC 9(3)P(2).
          05 C PIC S9(3)PP COMP.
"""
    cb = parse_copybook(copybook)
    plan = compile_plan(cb)
    codecs = {c.name: c.codec for c in plan.columns}
    # PIC P fields are vectorized since round 3: the digit-count-dependent
    # exponent rides the per-value dot_scale plane (columnar._dyn_scale)
    assert codecs["A"] is Codec.DISPLAY_NUM
    assert codecs["B"] is Codec.DISPLAY_NUM
    assert codecs["C"] is Codec.BINARY
    rows_data = [ebcdic_encode("012345") + (77).to_bytes(2, "big"),
                 ebcdic_encode("900001") + (0x8000).to_bytes(2, "big")]
    data = np.frombuffer(b"".join(rows_data), dtype=np.uint8).reshape(2, -1)
    dec = ColumnarDecoder(cb, backend="numpy")
    got = dec.decode(data).to_rows()
    want = [extract_record(cb.ast, bytes(r)) for r in rows_data]
    assert got == want


def test_pedantic_accepts_debug_ignore_file_size():
    copybook = """
       01 REC.
          05 A PIC X(4).
"""
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "d.bin", ebcdic_encode("ABCDEFG"))  # 7 bytes, rs=4
        out = read_cobol(path, copybook_contents=copybook,
                         pedantic="true", debug_ignore_file_size="true")
        assert len(out) == 1  # trailing partial record dropped
        with pytest.raises(ValueError, match="Redundant or unrecognized"):
            read_cobol(path, copybook_contents=copybook,
                       pedantic="true", no_such_option="1",
                       debug_ignore_file_size="true")


def test_occurs_mappings_accepts_python_dict():
    copybook = """
       01 REC.
          05 KIND  PIC X(1).
          05 ITEMS OCCURS 0 TO 3 TIMES DEPENDING ON KIND.
             10 V PIC X(1).
"""
    mapping = {"ITEMS": {"A": 1, "B": 3}}
    data = ebcdic_encode("AX--") + ebcdic_encode("BXYZ")
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "o.bin", data)
        for occ in (mapping, json.dumps(mapping)):
            out = read_cobol(path, copybook_contents=copybook,
                             occurs_mappings=occ)
            rows = out.to_rows()
            assert len(rows[0][0][1]) == 1
            assert len(rows[1][0][1]) == 3


def test_index_counts_invalid_records_like_reference():
    """The index generator numbers file-header (invalid) records while the
    record reader skips them without numbering — both mirror the reference
    (IndexGenerator.scala:117-120 vs VRLRecordReader.scala:151-186), so the
    Record_Id shift after a file header on indexed reads is intentional."""
    from cobrix_tpu.reader.parameters import ReaderParameters
    from cobrix_tpu.reader.stream import MemoryStream
    from cobrix_tpu.reader.var_len_reader import VarLenReader

    copybook = """
       01 REC.
          05 A PIC X(4).
"""
    header = b"HEADER"  # 6 bytes: > RDW size, so the tail is an invalid record
    payload = header + b"".join(
        _rdw(4) + ebcdic_encode(f"R{i:03d}") for i in range(10))
    params = ReaderParameters(is_record_sequence=True, is_rdw_big_endian=True,
                              file_start_offset=6,
                              input_split_records=3,
                              is_index_generation_needed=True)
    reader = VarLenReader(copybook, params)
    index = reader.generate_index(MemoryStream(payload), file_id=0)
    # the header region counts as record 0, so splits land one valid record
    # early: entries at generator-count 3, 6, 9 == valid records R2, R5, R8
    assert [e.record_index for e in index] == [0, 3, 6, 9]
    whole = list(reader.iter_rows(MemoryStream(payload), file_id=0))
    assert len(whole) == 10


def test_var_occurs_shifts_following_root_group():
    """A variable OCCURS at the tail of one 01-level root shifts every
    sibling root; such layouts must leave the static columnar plan
    (review regression: per-root dynamic-layout detection missed it)."""
    from cobrix_tpu import read_cobol

    copybook = """
       01 A.
          05 CNT PIC 9.
          05 ARR PIC X OCCURS 0 TO 5 TIMES DEPENDING ON CNT.
       01 B.
          05 F PIC X(3).
"""
    records = b"2xyQQQ" + b"0ZZZ" + b"5abcdeWWW"
    import tempfile, os
    path = tempfile.mktemp(suffix=".bin")
    with open(path, "wb") as f:
        f.write(records)
    try:
        res = read_cobol(path, copybook_contents=copybook, encoding="ascii",
                         variable_size_occurs="true")
        rows = res.to_rows()
    finally:
        os.unlink(path)
    assert rows == [
        [(2, ["x", "y"]), ("QQQ",)],
        [(0, []), ("ZZZ",)],
        [(5, ["a", "b", "c", "d", "e"]), ("WWW",)],
    ]


# -- advisor round-1 pins ---------------------------------------------------

SEG_FIXED_COPYBOOK = """
       01 RECORD.
          05 SEG-ID    PIC X(1).
          05 COMPANY.
             10 NAME   PIC X(5).
          05 CONTACT REDEFINES COMPANY.
             10 PHONE  PIC X(5).
"""


def _seg_fixed_file(tmp):
    recs = [("C", "ACME "), ("P", "12345"), ("C", "GLOBX"), ("P", "67890")]
    payload = b"".join(ebcdic_encode(sid + body) for sid, body in recs)
    return _write(tmp, "seg.bin", payload)


def test_fixed_length_read_ignores_segment_filter():
    """Reference parity: FixedLenNestedRowIterator has no segment filter
    (FixedLenNestedRowIterator.scala:63-71); a plain fixed-length read with
    segment_id_filter emits ALL records."""
    with tempfile.TemporaryDirectory() as tmp:
        path = _seg_fixed_file(tmp)
        res = read_cobol(path, copybook_contents=SEG_FIXED_COPYBOOK,
                         segment_field="SEG-ID", segment_filter="C",
                         **{"redefine-segment-id-map:1": "COMPANY => C",
                            "redefine-segment-id-map:2": "CONTACT => P"})
        assert len(res) == 4  # filter NOT applied on the fixed-length path
        host = read_cobol(path, copybook_contents=SEG_FIXED_COPYBOOK,
                          backend="host",
                          segment_field="SEG-ID", segment_filter="C",
                          **{"redefine-segment-id-map:1": "COMPANY => C",
                             "redefine-segment-id-map:2": "CONTACT => P"})
        assert host.to_rows() == res.to_rows()


def test_generate_record_id_routes_fixed_file_through_varlen_reader():
    """Reference parity: generate_record_id alone makes variableLengthParams
    Some(...), so the varlen reader handles the read and the segment filter
    IS honored (CobolParametersParser.parseVariableLengthParameters)."""
    with tempfile.TemporaryDirectory() as tmp:
        path = _seg_fixed_file(tmp)
        res = read_cobol(path, copybook_contents=SEG_FIXED_COPYBOOK,
                         generate_record_id="true",
                         segment_field="SEG-ID", segment_filter="C",
                         **{"redefine-segment-id-map:1": "COMPANY => C",
                            "redefine-segment-id-map:2": "CONTACT => P"})
        rows = res.to_rows()
        assert len(rows) == 2  # varlen iterator honors the filter
        # Record_Id keeps the by-position numbering of unfiltered records
        assert [r[1] for r in rows] == [0, 2]


def test_stream_chunks_rejects_file_offsets():
    from cobrix_tpu.streaming import CobolStreamer

    streamer = CobolStreamer("       01 R.\n          05 F PIC X(4).\n",
                             file_start_offset="4")
    with pytest.raises(ValueError, match="stream_chunks"):
        list(streamer.stream_chunks([b"HEADabcd"]))


def test_record_length_override_with_generate_record_id():
    """The varlen route taken by generate_record_id must honor the
    record_length override (review finding: FixedLengthHeaderParser was
    built from copybook.record_size only)."""
    copybook = "       01 R.\n          05 F PIC X(4).\n"
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "r.bin", ebcdic_encode("ABCDxxEFGHxxIJKLxx"))
        res = read_cobol(path, copybook_contents=copybook,
                         record_length="6", generate_record_id="true")
        assert [r[2:] for r in res.to_rows()] == [
            [("ABCD",)], [("EFGH",)], [("IJKL",)]]
        assert [r[1] for r in res.to_rows()] == [0, 1, 2]


def test_generate_record_id_drops_trailing_partial_record():
    """Reference parity pin: the varlen reader (fixed-length header parser)
    silently drops a trailing partial record, while the plain fixed path
    raises a divisibility error (CobolScanners.scala:88 vs
    RecordHeaderParserFixedLen.scala:22-52)."""
    copybook = "       01 R.\n          05 F PIC X(4).\n"
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "p.bin", ebcdic_encode("ABCDEFGHXY"))
        with pytest.raises(ValueError, match="does not divide"):
            read_cobol(path, copybook_contents=copybook)
        res = read_cobol(path, copybook_contents=copybook,
                         generate_record_id="true")
        assert [r[2:] for r in res.to_rows()] == [[("ABCD",)], [("EFGH",)]]


def _rdw_rec(payload: bytes) -> bytes:
    """Little-endian RDW header + payload (is_rdw_big_endian default
    false). Distinct from `_rdw(n)` above, which builds the header only."""
    n = len(payload)
    return bytes([0, 0, n & 0xFF, n >> 8]) + payload


def test_decode_once_wide_decimal_garbage_rows_stay_null():
    """Decode-once multisegment batches decode every record through the
    full (all-redefines) plan; rows of OTHER segments produce garbage at a
    redefine's offsets. A wide (precision>18) decimal column must keep
    those hidden rows as None in the Arrow fallback — review finding: the
    values_hi fallback dropped the relevance mask and pa.array raised
    ArrowInvalid when a garbage magnitude outran decimal128(38)."""
    copybook = """
       01 R.
          05 SEG-ID      PIC X(1).
          05 A-SEG.
             10 WIDE     PIC S9(38) COMP.
          05 B-SEG REDEFINES A-SEG.
             10 TXT      PIC X(16).
    """
    a_payload = ebcdic_encode("A") + (10**37).to_bytes(16, "big", signed=True)
    # 0xFF bytes form a negative/huge 128-bit pattern beyond 38 digits
    b_payload = ebcdic_encode("B") + b"\x7f" + b"\xff" * 15
    raw = _rdw_rec(a_payload) + _rdw_rec(b_payload)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "wide.bin", raw)
        res = read_cobol(path, copybook_contents=copybook,
                         is_record_sequence="true",
                         segment_field="SEG-ID",
                         redefine_segment_id_map="A-SEG => A",
                         **{"redefine_segment_id_map:1": "B-SEG => B"})
        tbl = res.to_arrow()
        col = tbl.column("R").to_pylist()
        assert col[0]["A_SEG"]["WIDE"] == 10**37
        assert col[0]["B_SEG"] is None
        assert col[1]["A_SEG"] is None
        assert col[1]["B_SEG"]["TXT"] is not None


def test_full_width_string_column_uses_native_arrow_kernel():
    """Review finding: the native string kernel's 3x-UTF-8 overflow guard
    fired on any width>8 column whose final rows had no trailing spaces,
    silently dropping the one-pass path for exactly the fully-populated
    columns it was built for. All-ASCII full-width output must fit."""
    from cobrix_tpu import native

    n, w = 100, 10
    batch = np.full((n, w), 0xC1, dtype=np.uint8)  # EBCDIC 'A', full width
    from cobrix_tpu.encoding.codepages import code_page_lut_u16
    lut = code_page_lut_u16("common")
    res = native.string_cols_arrow_packed(
        batch, np.asarray([0]), np.asarray([w]), lut, native.TRIM_BOTH)
    if res is None:
        pytest.skip("native library unavailable")
    assert res[0] is not None, "full-width ASCII output must not overflow"
    offsets, data = res[0]
    assert offsets[-1] == n * w
    assert data[:w].tobytes() == b"A" * w


def test_decode_once_hidden_rows_with_non_ascii_garbage():
    """Review finding: garbage >0x7F code points in rows hidden by a null
    parent struct crashed to_arrow with ArrowInvalid when the column fell
    back to the code-point-matrix path. Hidden rows must be blanked."""
    copybook = """
       01 R.
          05 SEG-ID      PIC X(1).
          05 A-SEG.
             10 TXT      PIC X(20).
          05 B-SEG REDEFINES A-SEG.
             10 NUM      PIC S9(4) COMP.
    """
    a_payload = ebcdic_encode("A") + ebcdic_encode("HELLO", 20)
    # B record: bytes at TXT's offsets map to non-ASCII cp037 characters
    b_payload = ebcdic_encode("B") + b"\x42" * 20  # 0x42 -> a-circumflex
    raw = _rdw_rec(a_payload) + _rdw_rec(b_payload)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "na.bin", raw)
        res = read_cobol(path, copybook_contents=copybook,
                         is_record_sequence="true",
                         ebcdic_code_page="cp037",
                         segment_field="SEG-ID",
                         redefine_segment_id_map="A-SEG => A",
                         **{"redefine_segment_id_map:1": "B-SEG => B"})
        tbl = res.to_arrow()
        col = tbl.column("R").to_pylist()
        assert col[0]["A_SEG"]["TXT"] == "HELLO"
        assert col[1]["A_SEG"] is None


def test_hierarchical_odo_dependee_outside_segment_uses_row_path():
    """Round-4 advisor (high): the columnar hierarchical Arrow assembly
    resolved DEPENDING ON counts from each record's OWN bytes, but the
    oracle (reference RecordExtractors depend_fields) carries the dependee
    value registered from the parent/root record across child records.
    Shapes where a depending array under a segment redefine names a
    dependee outside that redefine must bail to the row path."""
    copybook = """
       01 RECORD.
          05 SEG-ID    PIC X(1).
          05 COMPANY.
             10 CNT    PIC 9(1).
             10 NAME   PIC X(4).
          05 CONTACT REDEFINES COMPANY.
             10 ITEM   PIC X(1) OCCURS 4 DEPENDING ON CNT.
"""
    recs = [("C", "2ACME"), ("P", "WXYZ"), ("C", "1GLOB"), ("P", "QRST")]
    payload = b"".join(
        _rdw(1 + len(body)) + ebcdic_encode(sid + body)
        for sid, body in recs)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "odo.bin", payload)
        kwargs = dict(
            copybook_contents=copybook,
            is_record_sequence=True,
            is_rdw_big_endian="true",
            segment_field="SEG-ID",
            **{"redefine-segment-id-map:0": "COMPANY => C",
               "redefine-segment-id-map:1": "CONTACT => P",
               "segment-children:0": "COMPANY => CONTACT"})
        host = read_cobol(path, backend="host", **kwargs)
        default = read_cobol(path, backend="numpy", **kwargs)
        host_tbl = host.to_arrow().to_pylist()
        num_tbl = default.to_arrow().to_pylist()
        assert num_tbl == host_tbl
        # the parent's CNT governs each child's element count (2, then 1)
        items = [c["ITEM"] for row in num_tbl
                 for c in row["RECORD"]["COMPANY"]["CONTACT"]]
        assert [len(it) for it in items] == [2, 1]


def test_file_result_arrow_cache_keyed_on_schema():
    """Round-4 advisor (low): FileResult._arrow_cache ignored the
    output_schema argument — a second to_arrow() with a different schema
    silently returned the table built for the first. Now the cache
    remembers its schema; a different schema rebuilds via the row path."""
    import pyarrow as pa

    from cobrix_tpu.reader.result import FileResult
    from cobrix_tpu.reader.schema import Field, SimpleType, StructType

    class FakeSchema:
        def __init__(self, name):
            self.schema = StructType(
                [Field(name, SimpleType("integer"), nullable=True)])

    calls = []

    def factory(schema):
        calls.append(schema)
        return pa.table({"a": [7]})

    fr = FileResult(n_rows=1, arrow_factory=factory, rows=[[7]])
    s1, s2 = FakeSchema("a"), FakeSchema("b")
    t1 = fr.to_arrow(s1)
    assert fr.to_arrow(s1) is t1            # same schema object: cached
    assert calls == [s1]
    t2 = fr.to_arrow(s2)                    # different schema: NOT the
    assert t2 is not t1                     # stale cached table
    assert t2.column_names == ["b"]


def test_arrow_string_cache_keyed_on_masks():
    """Round-4 advisor (low): DecodedBatch._arrow_str_cache was keyed only
    by kernel-group id — rendering one batch under two different
    row-visibility mask sets served the first render's trimmed buffers to
    the second."""
    from cobrix_tpu import native

    if not native.available():
        pytest.skip("native string kernel unavailable")
    copybook = parse_copybook("""
       01 R.
          05 TXT      PIC X(5).
""")
    data = ebcdic_encode("HELLO") + ebcdic_encode("WORLD")
    dec = ColumnarDecoder(copybook)
    spec = next(c for c in dec.plan.columns if c.name == "TXT")

    def render(mask):
        batch = dec.decode(data)
        return batch, batch.string_arrow_buffers(
            spec, relevant_of=lambda c: mask)

    only_first = np.array([True, False])
    batch, buf1 = render(only_first)
    assert buf1 is not None
    offsets, _ = buf1
    assert offsets[2] == offsets[1]  # hidden row renders empty
    # SAME batch, different mask: must rebuild, not serve stale buffers
    buf2 = batch.string_arrow_buffers(spec, relevant_of=lambda c: None)
    offsets2, data2 = buf2
    assert bytes(data2[offsets2[1]:offsets2[2]]) == b"WORLD"


def test_odo_shared_prefix_dependee_follows_root_record(monkeypatch):
    """Counter in the shared record prefix, DEPENDING ON array inside a
    segment redefine: the oracle registers the dependee while walking the
    ROOT record (extract_hierarchical_record walks prefix fields only for
    the root), so a child record's element count follows the ROOT's CNT —
    not the child's own overlapping bytes. The shape must bail to the row
    path (the columnar build would read each record's own bytes)."""
    import cobrix_tpu.reader.hierarchical_arrow as ha

    copybook = """
       01 RECORD.
          05 SEG-ID    PIC X(1).
          05 CNT       PIC 9(1).
          05 COMPANY.
             10 NAME   PIC X(4).
          05 CONTACT REDEFINES COMPANY.
             10 ITEM   PIC X(1) OCCURS 4 DEPENDING ON CNT.
"""
    # root carries CNT=2; the child's own prefix byte says 4 — the oracle
    # must produce 2 items (root's value), not 4
    recs = [("C", "2ACME"), ("P", "4WXYZ"), ("C", "3GLOB"), ("P", "1QRST")]
    payload = b"".join(
        _rdw(1 + len(body)) + ebcdic_encode(sid + body)
        for sid, body in recs)
    results = []
    orig = ha.hierarchical_table

    def spy(*args, **kw):
        out = orig(*args, **kw)
        results.append(out)
        return out

    monkeypatch.setattr(ha, "hierarchical_table", spy)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "odo2.bin", payload)
        kwargs = dict(
            copybook_contents=copybook,
            is_record_sequence=True,
            is_rdw_big_endian="true",
            segment_field="SEG-ID",
            **{"redefine-segment-id-map:0": "COMPANY => C",
               "redefine-segment-id-map:1": "CONTACT => P",
               "segment-children:0": "COMPANY => CONTACT"})
        host = read_cobol(path, backend="host", **kwargs)
        default = read_cobol(path, backend="numpy", **kwargs)
        num_tbl = default.to_arrow().to_pylist()
        assert num_tbl == host.to_arrow().to_pylist()
        assert results and results[-1] is None  # bailed to the row path
        items = [c["ITEM"] for row in num_tbl
                 for c in row["RECORD"]["COMPANY"]["CONTACT"]]
        assert [len(it) for it in items] == [2, 3]


def test_odo_same_segment_dependee_keeps_columnar_path(monkeypatch):
    """Dependee declared INSIDE the same segment redefine as its array:
    both paths read each record's own bytes — the columnar hierarchical
    assembly must NOT bail."""
    import cobrix_tpu.reader.hierarchical_arrow as ha

    copybook = """
       01 RECORD.
          05 SEG-ID    PIC X(1).
          05 COMPANY.
             10 NAME   PIC X(5).
          05 CONTACT REDEFINES COMPANY.
             10 CNT    PIC 9(1).
             10 ITEM   PIC X(1) OCCURS 4 DEPENDING ON CNT.
"""
    recs = [("C", "ACME "), ("P", "2WXYZ"), ("C", "GLOBX"), ("P", "3QRST")]
    payload = b"".join(
        _rdw(1 + len(body)) + ebcdic_encode(sid + body)
        for sid, body in recs)
    results = []
    orig = ha.hierarchical_table

    def spy(*args, **kw):
        out = orig(*args, **kw)
        results.append(out)
        return out

    monkeypatch.setattr(ha, "hierarchical_table", spy)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "odo3.bin", payload)
        kwargs = dict(
            copybook_contents=copybook,
            is_record_sequence=True,
            is_rdw_big_endian="true",
            segment_field="SEG-ID",
            **{"redefine-segment-id-map:0": "COMPANY => C",
               "redefine-segment-id-map:1": "CONTACT => P",
               "segment-children:0": "COMPANY => CONTACT"})
        host = read_cobol(path, backend="host", **kwargs)
        default = read_cobol(path, backend="numpy", **kwargs)
        num_tbl = default.to_arrow().to_pylist()
        assert num_tbl == host.to_arrow().to_pylist()
        assert results and results[-1] is not None  # columnar path engaged
        items = [c["ITEM"] for row in num_tbl
                 for c in row["RECORD"]["COMPANY"]["CONTACT"]]
        assert [len(it) for it in items] == [2, 3]


def test_masked_decode_never_masks_dependee_columns():
    """Review finding: a DEPENDING ON counter inside a segment redefine is
    read by the oracle's walk on EVERY record (registered from whatever
    overlay bytes are there) — segment-masked decode must leave dependee
    columns unmasked or the numpy hierarchical paths diverge from host."""
    copybook = """
       01 RECORD.
          05 SEG-ID    PIC X(1).
          05 COMPANY.
             10 NAME   PIC X(5).
          05 CONTACT REDEFINES COMPANY.
             10 CNT    PIC 9(5).
          05 TAIL     PIC X(1) OCCURS 4 DEPENDING ON CNT.
"""
    recs = [("C", "ACME ", "AB"), ("P", "00002", "XY"),
            ("C", "GLOBX", "CD"), ("P", "00001", "Z")]
    payload = b"".join(
        _rdw(1 + 5 + len(tail)) + ebcdic_encode(sid + body + tail)
        for sid, body, tail in recs)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write(tmp, "dep.bin", payload)
        kwargs = dict(
            copybook_contents=copybook,
            is_record_sequence=True,
            is_rdw_big_endian="true",
            segment_field="SEG-ID",
            variable_size_occurs="true",
            **{"redefine-segment-id-map:0": "COMPANY => C",
               "redefine-segment-id-map:1": "CONTACT => P",
               "segment-children:0": "COMPANY => CONTACT"})
        host = read_cobol(path, backend="host", **kwargs)
        default = read_cobol(path, backend="numpy", **kwargs)
        assert default.to_json_lines() == host.to_json_lines()
