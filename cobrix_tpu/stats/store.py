"""Persisted file profiles: the profiling pass runs once per file
version.

Mirrors the sparse-index store's contract (io/index_store.py) under a
sibling root, ``<cache_dir>/stats/``, plane="stats":

* keyed by the file's **content fingerprint** plus a **configuration
  fingerprint** covering everything that shapes what the profiler
  decodes — the copybook parse fingerprint and every framing parameter.
  Unlike the index store the split-grid knobs are deliberately
  EXCLUDED: the skip algorithm (stats/skip.py) reasons about byte-range
  coverage, so a profile collected on the canonical stats grid serves a
  scan planned on any other record-aligned grid.
* atomic writes, CRC-stamped payloads, quarantine + a
  ``cobrix_cache_corruption_total{plane="stats"}`` count on corruption,
  and a clean (uncounted) miss on format or key mismatch. A corrupt or
  stale entry can therefore never cause a wrong skip — the consumer
  simply sees "no profile" and scans everything.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Optional

from ..utils.atomic import write_atomic
from ..io.integrity import (
    note_corruption,
    quarantine,
    stamp_json_payload,
    sweep_cache_root,
    verify_json_payload,
)
from .profile import PROFILE_FORMAT, FileProfile

_logger = logging.getLogger(__name__)

# bump when the envelope layout changes: old files become misses
# (PROFILE_FORMAT covers the inner profile payload separately)
_FORMAT = 1

# crash-consistency sweep once per root per process
_SWEPT_LOCK = threading.Lock()
_SWEPT_ROOTS: set = set()


def stats_config_fingerprint(copybook_fingerprint, params) -> str:
    """Digest of every input that shapes what the profiler decodes for
    one configuration. The index store's enumeration minus the
    split-grid knobs (input_split_records/input_split_size_mb and the
    stats grid itself): profiles are grid-independent by design, and
    filter/select/pipeline knobs never change decoded values."""
    seg = params.multisegment
    token = repr((
        _FORMAT,
        copybook_fingerprint,
        params.is_record_sequence,
        params.is_rdw_big_endian,
        params.is_rdw_part_of_record_length,
        params.rdw_adjustment,
        params.record_length_override,
        params.length_field_name,
        params.is_text,
        params.variable_size_occurs,
        params.record_extractor,
        params.re_additional_info,
        params.record_header_parser,
        params.rhp_additional_info,
        params.start_offset,
        params.end_offset,
        params.file_start_offset,
        params.file_end_offset,
        params.record_error_policy,
        params.resync_window_bytes,
        (seg.segment_id_field, tuple(seg.segment_level_ids),
         tuple(sorted(seg.field_parent_map.items())),
         tuple(sorted(seg.segment_id_redefine_map.items())))
        if seg else None,
    ))
    return hashlib.sha256(token.encode("utf-8", "replace")).hexdigest()


def local_fingerprint(path: str) -> Optional[str]:
    """The ``local:<size>:<mtime_ns>`` content fingerprint for the
    CURRENT on-disk version of a local file, or None when it cannot be
    stat'd — matches ByteRangeSource.fingerprint() for local files."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return f"local:{st.st_size}:{st.st_mtime_ns}"


class StatsStore:
    def __init__(self, cache_dir: str):
        self.root = os.path.join(cache_dir, "stats")
        self.quarantine_root = os.path.join(cache_dir, "quarantine")
        os.makedirs(self.root, exist_ok=True)
        with _SWEPT_LOCK:
            swept = self.root in _SWEPT_ROOTS
            _SWEPT_ROOTS.add(self.root)
        if not swept:
            sweep_cache_root(self.root)

    def _path(self, url: str, config_fp: str) -> str:
        h = hashlib.sha256(
            f"{url}\x00{config_fp}".encode("utf-8", "replace"))
        return os.path.join(self.root, h.hexdigest()[:40] + ".json")

    def _corrupt(self, path: str, detail: str) -> None:
        quarantine(path, self.quarantine_root)
        note_corruption("stats", path, detail)

    def load(self, url: str, fingerprint: str,
             config_fp: str) -> Optional[FileProfile]:
        """The persisted profile for this (url, file version, config) —
        or None (miss: absent, stale fingerprint, corrupt — corrupt
        payloads are additionally quarantined and counted). A miss is
        always safe: the scan falls back to reading every chunk."""
        path = self._path(url, config_fp)
        try:
            with open(path, encoding="utf-8") as f:
                raw = f.read()
        except OSError:
            return None
        except UnicodeDecodeError:
            self._corrupt(path, "non-UTF-8 payload bytes")
            return None
        try:
            payload = json.loads(raw)
        except ValueError:
            # not even JSON: a torn write or foreign bytes, not a stale
            # entry — wrong data wearing this key's name
            self._corrupt(path, "undecodable JSON payload")
            return None
        if not isinstance(payload, dict) \
                or payload.get("format") != _FORMAT:
            return None  # older/newer format: a clean miss
        if not verify_json_payload(payload):
            # structurally valid JSON whose checksum disagrees: the
            # classic bit-flip that WOULD have skipped chunks that
            # actually carry matching records
            self._corrupt(path, "payload checksum mismatch")
            return None
        if (payload.get("url") != url
                or payload.get("fingerprint") != fingerprint
                or payload.get("config") != config_fp):
            return None
        doc = payload.get("profile")
        if not isinstance(doc, dict) \
                or doc.get("profile_format") != PROFILE_FORMAT:
            return None  # inner-format bump: a clean miss
        try:
            return FileProfile.from_payload(doc)
        except (KeyError, TypeError, ValueError):
            self._corrupt(path, "profile payload failed to deserialize")
            return None

    def save_for_local_path(self, path: str, config_fp: str,
                            profile: FileProfile) -> bool:
        """Persist `profile` for the CURRENT on-disk version of a local
        file. False when the file cannot be stat'd (vanished between
        profiling and save)."""
        fingerprint = local_fingerprint(path)
        if fingerprint is None:
            return False
        self.save(path, fingerprint, config_fp, profile)
        return True

    def save(self, url: str, fingerprint: str, config_fp: str,
             profile: FileProfile) -> None:
        """Persist one file version's profile (atomic; best-effort — a
        full disk degrades to re-profiling, never to a failed read)."""
        payload = stamp_json_payload({
            "format": _FORMAT,
            "url": url,
            "fingerprint": fingerprint,
            "config": config_fp,
            "profile": profile.to_payload(),
        })
        path = self._path(url, config_fp)
        try:
            write_atomic(path, json.dumps(payload))
        except OSError as exc:
            _logger.warning("stats save failed for %s: %s", url, exc)
