"""Serving tier tests (cobrix_tpu.serve): the multi-tenant streaming
scan server end to end through real sockets.

The matrix: streamed ≡ one-shot parity (rows/schema/diagnostics
metadata) for fixed and variable-length inputs; concurrent multi-tenant
scans with quota rejection and tenant isolation; mid-stream server-side
faults (ChaosSource) surfacing as structured client errors — never a
hang; warm-cache re-scans proving the shared block/index planes from
the client-visible trailer; `/metrics` + `/healthz` scrape format; live
progress frames over the wire; and the bridge shim's client-side
timeouts. Everything sits under `hard_timeout` so a protocol bug fails
loud instead of wedging CI.
"""
import json
import os
import tempfile
import threading
import time
import urllib.error
import urllib.request
import uuid

import pytest

from cobrix_tpu import read_cobol
from cobrix_tpu.bridge import BridgeServer, read_remote
from cobrix_tpu.obs.progress import ScanProgress
from cobrix_tpu.reader.stream import RetryPolicy
from cobrix_tpu.serve import (
    AdmissionController,
    AdmissionRejected,
    ScanServer,
    ServeError,
    TenantQuota,
    fetch_table,
    flight_available,
    stream_scan,
)
from cobrix_tpu.testing.faults import register_chaos_backend
from cobrix_tpu.testing.generators import (
    EXP1_COPYBOOK,
    EXP2_COPYBOOK,
    generate_exp1,
    generate_exp2,
)

from util import hard_timeout

# multi-chunk on purpose: ~3 MB of fixed records against a 1 MB chunk
# size, so streaming yields many batches and first-batch latency is a
# real fraction of the scan
FIXED_RECORDS = 20_000
FIXED_OPTS = dict(copybook_contents=EXP1_COPYBOOK, chunk_size_mb="1",
                  pipeline_workers="2")

EXP2_OPTS = dict(copybook_contents=EXP2_COPYBOOK, is_record_sequence="true",
                 segment_field="SEGMENT-ID",
                 redefine_segment_id_map="STATIC-DETAILS => C",
                 **{"redefine_segment_id_map:1": "CONTACTS => P"})


@pytest.fixture(scope="module")
def fixed_file():
    path = tempfile.mktemp(suffix=".dat")
    with open(path, "wb") as f:
        f.write(generate_exp1(FIXED_RECORDS, seed=5).tobytes())
    yield path
    os.unlink(path)


@pytest.fixture(scope="module")
def vrl_file():
    path = tempfile.mktemp(suffix=".dat")
    with open(path, "wb") as f:
        f.write(generate_exp2(600, seed=11))
    yield path
    os.unlink(path)


@pytest.fixture()
def server():
    srv = ScanServer().start()
    yield srv
    srv.stop()


def http_get(srv, path):
    host, port = srv.http_address
    try:
        with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                    timeout=10) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as err:  # non-2xx still has a body
        return err.code, dict(err.headers), err.read()


# -- streamed ≡ one-shot parity ------------------------------------------


def test_streamed_matches_one_shot_fixed(server, fixed_file):
    with hard_timeout(180, "fixed stream parity"):
        # iterating surface: incremental batches, client memory O(batch)
        # (batches are NOT retained by the stream — collect our own)
        batches = []
        with stream_scan(server.address, fixed_file,
                         **FIXED_OPTS) as stream:
            for batch in stream:
                batches.append(batch)
            summary = stream.summary
            assert stream._batches == []  # iterate-only keeps nothing
            with pytest.raises(RuntimeError, match="already partially"):
                stream.table()  # iterate OR collect, never both
        local = read_cobol(fixed_file, **FIXED_OPTS).to_arrow()
        assert len(batches) > 1  # incremental, not one blob
        assert sum(b.num_rows for b in batches) == local.num_rows
        # collecting surface: table() drives a fresh stream
        with stream_scan(server.address, fixed_file,
                         **FIXED_OPTS) as stream:
            remote = stream.table()
        assert remote.schema == local.schema  # includes field metadata
        assert remote.schema.metadata == local.schema.metadata
        assert remote.equals(local)
        assert summary["rows"] == local.num_rows
        assert summary["bytes"] > 0


def test_streamed_matches_one_shot_var_len(server, vrl_file):
    with hard_timeout(180, "VRL stream parity"):
        opts = dict(EXP2_OPTS, pipeline_workers="2")
        remote = fetch_table(server.address, vrl_file, **opts)
        local = read_cobol(vrl_file, **opts).to_arrow()
        assert remote.schema == local.schema
        assert remote.schema.metadata == local.schema.metadata
        assert remote.to_pylist() == local.to_pylist()


def test_streamed_diagnostics_metadata_round_trips(server, vrl_file):
    """A scan that ledgers errors ships the ReadDiagnostics JSON in the
    trailer, and the assembled table carries it byte-identically."""
    with hard_timeout(180, "diagnostics parity"):
        # corrupt a copy mid-file so permissive mode ledgers records
        raw = bytearray(open(vrl_file, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        path = tempfile.mktemp(suffix=".dat")
        with open(path, "wb") as f:
            f.write(raw)
        try:
            opts = dict(EXP2_OPTS, record_error_policy="permissive")
            remote = fetch_table(server.address, path, **opts)
            local = read_cobol(path, **opts).to_arrow()
            key = b"cobrix_tpu.read_diagnostics"
            assert remote.schema.metadata.get(key) \
                == local.schema.metadata.get(key)
        finally:
            os.unlink(path)


def test_max_records_caps_stream(server, fixed_file):
    with hard_timeout(120, "max_records"):
        t = fetch_table(server.address, fixed_file, max_records=7,
                        **FIXED_OPTS)
        assert t.num_rows == 7


def test_empty_result_is_a_valid_stream(server, fixed_file):
    with hard_timeout(120, "empty stream"):
        t = fetch_table(server.address, fixed_file, max_records=0,
                        **FIXED_OPTS)
        assert t.num_rows == 0
        assert len(t.schema) > 0  # schema still travels


# -- multi-tenant admission ----------------------------------------------


def test_quota_rejection_keeps_other_tenants_running(fixed_file):
    """Two tenants with quota 1 each: tenant A's second concurrent scan
    is REJECTED with a structured error while tenant B's scan still
    completes; stopping the server leaks no threads."""
    baseline = threading.active_count()
    srv = ScanServer(
        default_quota=TenantQuota(max_concurrent=1, max_queued=0)).start()
    try:
        with hard_timeout(180, "quota rejection"):
            first_batch = threading.Event()
            outcome = {}

            def tenant_a_scan():
                with stream_scan(srv.address, fixed_file, tenant="a",
                                 **FIXED_OPTS) as s:
                    it = iter(s)
                    next(it)
                    first_batch.set()
                    time.sleep(0.8)  # hold the quota slot
                    for _ in it:
                        pass
                    outcome["a1"] = s.summary["rows"]

            holder = threading.Thread(target=tenant_a_scan)
            holder.start()
            assert first_batch.wait(60)
            with pytest.raises(ServeError) as err:
                fetch_table(srv.address, fixed_file, tenant="a",
                            **FIXED_OPTS)
            assert err.value.code == "rejected"
            assert "retry" in str(err.value)
            # tenant B is untouched by A's quota exhaustion
            t = fetch_table(srv.address, fixed_file, tenant="b",
                            **FIXED_OPTS)
            assert t.num_rows == FIXED_RECORDS
            holder.join()
            assert outcome["a1"] == FIXED_RECORDS
    finally:
        srv.stop()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        leftover = [t.name for t in threading.enumerate()
                    if t.name.startswith("cobrix-serve")]
        if not leftover and threading.active_count() <= baseline:
            break
        time.sleep(0.05)
    assert not leftover
    assert threading.active_count() <= baseline


def test_admission_weighted_fair_share_drains_heavier_tenant_faster():
    """Unit-level: with weight 2 vs 1 and one global slot, the heavy
    tenant's queue drains about twice as fast — its last grant lands
    before the light tenant's."""
    with hard_timeout(60, "fair share"):
        ctl = AdmissionController(
            quotas={"heavy": TenantQuota(weight=2.0, max_queued=16),
                    "light": TenantQuota(weight=1.0, max_queued=16)},
            max_concurrent_scans=1, queue_timeout_s=30.0)
        hold = ctl.admit("light")
        order = []
        lock = threading.Lock()

        def waiter(tenant):
            ticket = ctl.admit(tenant)
            with lock:
                order.append(tenant)
            ctl.release(ticket)

        threads = []
        for i in range(4):
            for tenant in ("heavy", "light"):
                t = threading.Thread(target=waiter, args=(tenant,))
                t.start()
                threads.append(t)
        time.sleep(0.3)  # everyone queued behind the held slot
        ctl.release(hold)
        for t in threads:
            t.join(30)
        assert len(order) == 8
        last_heavy = max(i for i, t in enumerate(order) if t == "heavy")
        last_light = max(i for i, t in enumerate(order) if t == "light")
        assert last_heavy < last_light, order


def test_admission_queue_timeout_rejects():
    with hard_timeout(60, "queue timeout"):
        ctl = AdmissionController(max_concurrent_scans=1,
                                  queue_timeout_s=0.2)
        hold = ctl.admit("t")
        t0 = time.monotonic()
        with pytest.raises(AdmissionRejected) as err:
            ctl.admit("t")
        assert err.value.reason == "queue_timeout"
        assert time.monotonic() - t0 < 5.0
        ctl.release(hold)
        snap = ctl.snapshot()
        assert snap["active_scans"] == 0 and snap["queued_scans"] == 0


def test_server_owned_options_are_rejected(server, fixed_file):
    with hard_timeout(60, "server-owned options"):
        with pytest.raises(ServeError) as err:
            fetch_table(server.address, fixed_file,
                        cache_dir="/tmp/evil", **FIXED_OPTS)
        assert err.value.code == "protocol"
        assert "server-owned" in str(err.value)


# -- faults: structured errors, never hangs ------------------------------


def test_mid_stream_fault_surfaces_as_client_error(server, fixed_file):
    """A storage fault mid-scan (ChaosSource, retries exhausted) must
    reach the client as a ServeError while iterating — the pre-serve
    bridge left the peer blocked in a read here."""
    with hard_timeout(120, "mid-stream fault"):
        scheme = f"chaos{uuid.uuid4().hex[:8]}"
        data = open(fixed_file, "rb").read()
        register_chaos_backend(scheme, data, fail_every=3)
        with pytest.raises(ServeError) as err:
            with stream_scan(server.address, f"{scheme}://input",
                             io_retry_attempts="1",
                             **FIXED_OPTS) as stream:
                for _ in stream:
                    pass
        assert err.value.code == "scan_error"
        assert "injected fault" in str(err.value)


def test_scan_error_before_first_batch_is_structured(server, fixed_file):
    with hard_timeout(60, "pre-stream error"):
        with pytest.raises(ServeError) as err:
            fetch_table(server.address, fixed_file,
                        copybook_contents="       01 R.\n"
                                          "          05 F PIC Q.\n")
        assert err.value.code == "scan_error"
        assert "CopybookSyntaxError" in str(err.value)
        # and the handler survives for the next request
        t = fetch_table(server.address, fixed_file, max_records=1,
                        **FIXED_OPTS)
        assert t.num_rows == 1


def test_stalled_server_read_times_out_client_side(server, fixed_file):
    """A server that produces nothing for longer than the client's read
    timeout surfaces as an OSError/timeout, not an indefinite block."""
    with hard_timeout(120, "client read timeout"):
        scheme = f"slow{uuid.uuid4().hex[:8]}"
        register_chaos_backend(scheme, open(fixed_file, "rb").read(),
                               latency_s=2.0)
        with pytest.raises((OSError, ServeError)):
            with stream_scan(server.address, f"{scheme}://input",
                             read_timeout_s=0.5, **FIXED_OPTS) as stream:
                for _ in stream:
                    pass


def test_bridge_connect_timeout_fails_fast():
    """read_remote against nothing listening raises promptly under its
    RetryPolicy instead of hanging (the satellite fix)."""
    with hard_timeout(60, "bridge connect timeout"):
        import socket

        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        dead = probe.getsockname()
        probe.close()  # nothing listens here now
        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            read_remote(dead, ["/no/such"],
                        connect_retry=RetryPolicy(max_attempts=2,
                                                  base_delay=0.05,
                                                  max_delay=0.1,
                                                  deadline=2.0))
        assert time.monotonic() - t0 < 30.0


def test_bridge_mid_scan_fault_is_a_bridge_error(fixed_file):
    """The compat shim keeps the historical 'bridge error: ...' message
    for scan failures, including MID-stream ones."""
    with hard_timeout(120, "bridge mid-scan fault"):
        srv = BridgeServer().start()
        try:
            scheme = f"bchaos{uuid.uuid4().hex[:8]}"
            register_chaos_backend(scheme, open(fixed_file, "rb").read(),
                                   fail_every=3)
            with pytest.raises(RuntimeError, match="bridge error"):
                read_remote(srv.address, [f"{scheme}://input"],
                            io_retry_attempts="1", **FIXED_OPTS)
        finally:
            srv.stop()


# -- shared warm planes --------------------------------------------------


def test_warm_second_scan_hits_shared_caches(vrl_file, tmp_path):
    """Scan the same remote VRL file twice through one server pinned to
    a `cache_dir`: the trailer's io metrics must show the second scan
    riding the block cache AND the sparse-index store — asserted purely
    client-side, no server shell access."""
    fsspec = pytest.importorskip("fsspec")
    with hard_timeout(180, "warm cache"):
        bucket = f"/serve{uuid.uuid4().hex[:12]}"
        fs = fsspec.filesystem("memory")
        with fs.open(f"{bucket}/data.dat", "wb") as f:
            f.write(open(vrl_file, "rb").read())
        url = f"memory:/{bucket}/data.dat"
        srv = ScanServer(
            server_options={"cache_dir": str(tmp_path / "cache")}).start()
        try:
            def scan_io():
                with stream_scan(srv.address, url, **EXP2_OPTS) as s:
                    rows = sum(b.num_rows for b in s)
                    return rows, s.summary["metrics"]["io"]

            cold_rows, cold_io = scan_io()
            warm_rows, warm_io = scan_io()
            assert cold_rows == warm_rows == 600
            assert cold_io["bytes_fetched"] > 0
            assert warm_io["bytes_fetched"] == 0  # network never touched
            assert warm_io["block_hits"] >= 1
            assert warm_io["index_hits"] >= 1  # no re-index pass
        finally:
            srv.stop()


# -- observability endpoints + progress frames ---------------------------


def test_metrics_and_healthz_scrape(server, fixed_file):
    with hard_timeout(120, "scrape"):
        fetch_table(server.address, fixed_file, tenant="scrape-tenant",
                    max_records=5, **FIXED_OPTS)
        status, headers, body = http_get(server, "/metrics")
        text = body.decode()
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert "# HELP cobrix_serve_scans_admitted_total" in text
        assert "# TYPE cobrix_serve_scans_admitted_total counter" in text
        assert 'cobrix_serve_scans_admitted_total{' \
               'tenant="scrape-tenant"}' in text
        assert 'outcome="ok"' in text
        assert "cobrix_serve_first_batch_seconds_bucket" in text
        assert 'cobrix_serve_streamed_bytes_total{' \
               'tenant="scrape-tenant"}' in text

        status, headers, body = http_get(server, "/healthz")
        doc = json.loads(body)
        assert status == 200
        assert headers["Content-Type"] == "application/json"
        assert doc["status"] == "ok"
        assert doc["active_scans"] == 0
        assert "max_concurrent_scans" in doc

        status, _, _ = http_get(server, "/nope")
        assert status == 404


def test_rejection_metrics_carry_reason(fixed_file):
    with hard_timeout(120, "rejection metrics"):
        srv = ScanServer(default_quota=TenantQuota(max_concurrent=1,
                                                   max_queued=0)).start()
        try:
            gate = threading.Event()

            def holder():
                with stream_scan(srv.address, fixed_file, tenant="q",
                                 **FIXED_OPTS) as s:
                    it = iter(s)
                    next(it)
                    gate.set()
                    time.sleep(0.5)
                    for _ in it:
                        pass

            t = threading.Thread(target=holder)
            t.start()
            assert gate.wait(60)
            with pytest.raises(ServeError):
                fetch_table(srv.address, fixed_file, tenant="q",
                            **FIXED_OPTS)
            t.join()
            _, _, body = http_get(srv, "/metrics")
            assert 'cobrix_serve_scans_rejected_total{tenant="q",' \
                   'reason="queue_full"}' in body.decode()
        finally:
            srv.stop()


def test_progress_frames_stream_live(server, fixed_file):
    """Opt-in progress frames arrive as ScanProgress snapshots: bytes
    monotonic, a final done=True, all while batches stream."""
    with hard_timeout(120, "progress frames"):
        snaps = []
        with stream_scan(server.address, fixed_file,
                         progress_callback=snaps.append,
                         progress_interval_s="0",
                         **FIXED_OPTS) as stream:
            batches = sum(1 for _ in stream)
        assert batches > 1
        assert snaps, "no progress frames arrived"
        assert all(isinstance(s, ScanProgress) for s in snaps)
        done_bytes = [s.bytes_done for s in snaps]
        assert done_bytes == sorted(done_bytes)
        assert snaps[-1].done is True
        assert snaps[-1].chunks_done == snaps[-1].chunks_total > 1


def test_progress_frames_absent_unless_requested(server, fixed_file):
    with hard_timeout(120, "no progress by default"):
        with stream_scan(server.address, fixed_file, max_records=5,
                         **FIXED_OPTS) as stream:
            list(stream)
            # the trailer parsed cleanly with no progress callback and
            # no 'P' frames were requested; nothing to assert beyond a
            # clean summary
            assert stream.summary["rows"] == 5


# -- optional flight front-end -------------------------------------------


@pytest.mark.skipif(not flight_available(),
                    reason="pyarrow.flight not importable")
def test_flight_front_end_streams_same_rows(fixed_file):
    import pyarrow.flight as flight

    from cobrix_tpu.serve.flight import FlightScanServer

    with hard_timeout(180, "flight front-end"):
        srv = FlightScanServer().start()
        try:
            client = flight.connect(f"grpc://127.0.0.1:{srv.port}")
            ticket = flight.Ticket(json.dumps(
                {"tenant": "fl", "files": [fixed_file],
                 "options": dict(FIXED_OPTS)}).encode())
            table = client.do_get(ticket).read_all()
            local = read_cobol(fixed_file, **FIXED_OPTS).to_arrow()
            assert table.num_rows == local.num_rows
            assert table.schema.names == local.schema.names
            with pytest.raises(flight.FlightError):
                client.do_get(flight.Ticket(b"not json"))
        finally:
            srv.stop()


# -- failed-chunk gaps vs the reorder buffer + byte gate ------------------


def test_executor_signals_failed_chunk_under_partial():
    """A terminally-failed chunk (partial policy) fires the executor's
    on_chunk_failed tap — the signal OrderedBatchEmitter needs to know
    a gap is permanent."""
    from cobrix_tpu.engine.pipeline import PipelineExecutor
    from cobrix_tpu.reader.parameters import ShardErrorPolicy

    def proc(x):
        if x == 1:
            raise ValueError("poison chunk 1")
        return x

    with hard_timeout(60, "failed-chunk signal"):
        ex = PipelineExecutor(2, error_policy=ShardErrorPolicy.PARTIAL)
        failed = []
        ex.on_chunk_failed = failed.append
        out = ex.run([((lambda i=i: i), proc) for i in range(3)])
        assert out == [0, None, 2]
        assert failed == [1]


def test_gap_blocked_emitter_drains_on_failed_chunk_signal():
    """Post-gap tables buffered against the byte gate must drain as
    soon as the gap is declared permanent — NOT stall out the
    byte-wait timeout and fail a healthy chunk."""
    import pyarrow as pa

    from cobrix_tpu.serve.session import OrderedBatchEmitter

    with hard_timeout(60, "gap drain"):
        t = pa.table({"v": list(range(1000))})  # ~8 KB
        budget = int(t.nbytes * 2.5)  # fits 2 buffered tables, not 3
        ctl = AdmissionController(
            default_quota=TenantQuota(max_inflight_bytes=budget),
            byte_wait_timeout_s=20.0)
        written = []
        em = OrderedBatchEmitter(written.append, "t", controller=ctl)
        em.emit(0, t)             # flushes straight through
        em.emit(2, t)             # gap at 1: buffered + charged
        em.emit(3, t)             # buffered + charged (budget now full)

        blocked_done = threading.Event()

        def emit_blocked():
            em.emit(4, t)         # over budget: blocks on the gate
            blocked_done.set()

        worker = threading.Thread(target=emit_blocked, daemon=True)
        worker.start()
        time.sleep(0.6)           # let it actually block
        assert not blocked_done.is_set()
        t0 = time.monotonic()
        em.emit(1, None)          # chunk 1 failed: the gap is permanent
        assert blocked_done.wait(10), \
            "gate-blocked emit never drained after the failure signal"
        assert time.monotonic() - t0 < 10  # not the 20s no-drain window
        em.finish()
        assert len(written) == 4  # 0,2,3,4 in order; 1 skipped
        assert ctl.inflight_bytes("t") == 0


def test_batch_callback_delivers_none_for_failed_chunks(fixed_file):
    """read_cobol parity inside ONE partial-policy scan with injected
    chunk failures: every chunk index arrives exactly once (table or
    None), and the delivered tables concatenate to that same read's
    to_arrow()."""
    import pyarrow as pa

    from cobrix_tpu.reader.stream import (ByteRangeSource,
                                          register_stream_backend)

    with hard_timeout(180, "partial batch_callback"):
        payload = open(fixed_file, "rb").read()
        # permanently poison one byte window inside chunk 1 (1 MB
        # chunks): every read touching it fails, across retries too, so
        # exactly that chunk fails terminally under the partial policy
        poison = (1_200_000, 1_300_000)

        class _PoisonSource(ByteRangeSource):
            def __init__(self, name):
                self._name = name

            def size(self):
                return len(payload)

            def read(self, offset, n):
                if offset < poison[1] and offset + n > poison[0]:
                    raise IOError(f"poisoned range {poison}")
                return payload[offset:offset + n]

            def fingerprint(self):
                return "poison-fixture"

            @property
            def name(self):
                return self._name

        scheme = f"poison{uuid.uuid4().hex[:8]}"
        register_stream_backend(scheme, _PoisonSource)
        got = {}

        def on_batch(i, table):
            got[i] = table

        data = read_cobol(f"{scheme}://input", batch_callback=on_batch,
                          shard_error_policy="partial",
                          io_retry_attempts="1", **FIXED_OPTS)
        table = data.to_arrow()
        failures = (data.diagnostics.shard_failures
                    if data.diagnostics else []) or []
        nones = {i for i, tb in got.items() if tb is None}
        assert nones, "the poisoned range produced no chunk failure"
        assert len(nones) == len(failures)
        # the poisoned window sits inside the failed chunk's byte range
        assert any(f.offset_from <= 1_200_000 < (f.offset_to
                   if f.offset_to != -1 else float("inf"))
                   for f in failures), failures
        delivered = [got[i] for i in sorted(got) if got[i] is not None]
        assert pa.concat_tables(delivered).replace_schema_metadata(None) \
            .equals(table.replace_schema_metadata(None))


# -- concurrent multi-tenant ObsContext isolation (PR 8 satellite) -------


def test_concurrent_tenant_obs_isolation(server):
    """Two SIMULTANEOUS streamed scans from different tenants must not
    cross-contaminate trace spans, field costs, or IoStats — the PR 4
    per-read isolation guarantee extended through serve/session.py.

    Each tenant scans a DIFFERENT-SIZED memory:// input with tracing
    and attribution on; any leakage between the two concurrent
    ObsContexts would show up as a wrong per-field value count, a
    wrong remote-byte total, or a foreign span in the merged trace."""
    fsspec = pytest.importorskip("fsspec")
    fs = fsspec.filesystem("memory")
    sizes = {"tenant-a": 2500, "tenant-b": 900}
    urls = {}
    raw_bytes = {}
    for tenant, n in sizes.items():
        payload = generate_exp1(n, seed=len(tenant)).tobytes()
        url = f"memory://iso-{uuid.uuid4().hex}/{tenant}.dat"
        with fs.open(url.replace("memory://", "/"), "wb") as f:
            f.write(payload)
        urls[tenant] = url
        raw_bytes[tenant] = len(payload)

    barrier = threading.Barrier(len(sizes))
    results = {}
    errors = {}

    def scan(tenant):
        try:
            barrier.wait(30)
            with stream_scan(server.address, urls[tenant],
                             tenant=tenant, trace=True,
                             field_costs="true", io_block_mb="0.125",
                             **FIXED_OPTS) as s:
                rows = sum(b.num_rows for b in s)
                results[tenant] = {
                    "rows": rows,
                    "summary": s.summary,
                    "trace": s.chrome_trace(),
                    "trace_id": s.trace_id,
                }
        except Exception as exc:  # pragma: no cover - assertion below
            errors[tenant] = exc

    with hard_timeout(180, "tenant obs isolation"):
        threads = [threading.Thread(target=scan, args=(t,))
                   for t in sizes]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors, errors
    assert results["tenant-a"]["trace_id"] != \
        results["tenant-b"]["trace_id"]
    for tenant, n in sizes.items():
        res = results[tenant]
        assert res["rows"] == n
        m = res["summary"]["metrics"]
        # bytes: each scan accounted exactly its own input
        assert m["bytes_read"] == raw_bytes[tenant]
        # IoStats: the remote plane charged this read ONLY its own
        # fetched bytes (block-aligned, so slightly above raw; a leaked
        # context would at least add the OTHER tenant's whole input)
        assert m["io"] is not None
        fetched = m["io"]["bytes_fetched"]
        assert raw_bytes[tenant] <= fetched < raw_bytes[tenant] * 1.2
        # field costs: every attributed field saw exactly this scan's
        # record count — a foreign chunk would inflate it
        fc = m["field_costs"]
        assert fc, "attribution was on"
        assert {v["values"] for v in fc.values()} == {n}
        # trace spans: the merged artifact's root args carry THIS
        # request's identity and record count, and every tagged span
        # agrees on the trace_id
        events = res["trace"]["traceEvents"]
        tagged = {e["args"]["trace_id"] for e in events
                  if (e.get("args") or {}).get("trace_id")}
        assert tagged == {res["trace_id"]}
        roots = [e["args"] for e in events
                 if (e.get("args") or {}).get("records") is not None]
        assert roots and roots[0]["records"] == n
        assert roots[0]["tenant"] == tenant


# -- overload shedding (memory watermark -> degrade -> shed) -------------


@pytest.fixture()
def fake_pressure():
    """Install a process-wide memory monitor driven by a FAKE rss so
    the watermark crossings are deterministic (no gigabyte balloons in
    CI); always uninstalled after."""
    from cobrix_tpu.utils import pressure

    rss = {"value": 0}
    monitor = pressure.set_process_budget(
        1000, degrade_fraction=0.5, shed_fraction=0.9, interval_s=0.0,
        rss_fn=lambda: rss["value"])
    try:
        yield rss, monitor
    finally:
        pressure.set_process_budget(0)


def test_shed_rejects_new_scans_structured(server, fixed_file,
                                           fake_pressure):
    """Past the shed watermark: a structured `overloaded` rejection (no
    SLO burn — it audits as 'rejected'), and scans admitted BEFORE the
    spike still complete."""
    rss, _ = fake_pressure
    with hard_timeout(120, "shed rejection"):
        # a healthy tenant's scan admitted before the pressure spike
        gate = threading.Event()
        done = {}

        def healthy():
            with stream_scan(server.address, fixed_file,
                             tenant="healthy", **FIXED_OPTS) as s:
                it = iter(s)
                first = next(it)
                gate.set()
                done["rows"] = first.num_rows + sum(b.num_rows
                                                    for b in it)

        t = threading.Thread(target=healthy)
        t.start()
        assert gate.wait(60)
        rss["value"] = 950  # past the 90% shed watermark
        with pytest.raises(ServeError) as err:
            fetch_table(server.address, fixed_file, tenant="latecomer",
                        **FIXED_OPTS)
        assert err.value.code == "rejected"
        assert "memory budget" in str(err.value)
        t.join(60)
        # the already-admitted scan finished whole despite the spike
        assert done["rows"] == FIXED_RECORDS
        # the rejection is counted with its own reason
        from cobrix_tpu.obs.metrics import serve_metrics

        assert serve_metrics()["rejected"].value(
            tenant="latecomer", reason="overloaded") >= 1
        # ... and recedes with the pressure
        rss["value"] = 100
        t2 = fetch_table(server.address, fixed_file, tenant="latecomer",
                         max_records=5, **FIXED_OPTS)
        assert t2.num_rows == 5


def test_degrade_halves_io_knobs_and_reports(server, fixed_file,
                                             fake_pressure):
    """Between the degrade and shed watermarks scans still run (and
    parity holds) — with halved read-ahead, flagged on the trailer and
    counted per tenant."""
    rss, _ = fake_pressure
    with hard_timeout(120, "degraded scan"):
        local = read_cobol(fixed_file, **FIXED_OPTS).to_arrow()
        rss["value"] = 700  # between 50% degrade and 90% shed
        with stream_scan(server.address, fixed_file, tenant="squeezed",
                         **FIXED_OPTS) as s:
            t = s.table()
            summary = s.summary
        assert t.equals(local)
        assert summary.get("degraded") is True
        from cobrix_tpu.obs.metrics import serve_metrics

        assert serve_metrics()["degraded"].value(tenant="squeezed") >= 1


def test_degraded_pipeline_shrinks_inflight_window(tmp_path,
                                                   fake_pressure):
    """The engine-side degrade: under pressure the pipeline holds new
    chunks until the in-flight window drops under half, and reports
    it."""
    rss, _ = fake_pressure
    with hard_timeout(120, "pipeline degrade"):
        path = str(tmp_path / "fixed.dat")
        with open(path, "wb") as f:
            f.write(generate_exp1(8000, seed=3).tobytes())
        rss["value"] = 700
        out = read_cobol(path, copybook_contents=EXP1_COPYBOOK,
                         chunk_size_mb="0.5", pipeline_workers="2")
        clean = read_cobol(path, copybook_contents=EXP1_COPYBOOK)
        assert out.to_arrow().equals(clean.to_arrow())
        assert out.metrics.pipeline.get("pressure_degrades", 0) >= 1


def test_queued_scans_shed_lowest_weight_first(fixed_file,
                                               fake_pressure):
    """Under shed pressure the QUEUE drains by eviction: lowest-weight
    tenants' waiters get the structured rejection, higher-weight ones
    keep their place."""
    rss, _ = fake_pressure
    srv = ScanServer(
        max_concurrent_scans=1,
        quotas={"gold": TenantQuota(max_concurrent=1, weight=4.0),
                "bronze": TenantQuota(max_concurrent=1, weight=1.0)},
        queue_timeout_s=30.0).start()
    try:
        with hard_timeout(120, "weighted shed"):
            gate = threading.Event()
            results = {}

            def holder():
                with stream_scan(srv.address, fixed_file, tenant="gold",
                                 **FIXED_OPTS) as s:
                    it = iter(s)
                    next(it)
                    gate.set()
                    time.sleep(1.0)  # hold the only global slot
                    for _ in it:
                        pass
                results["holder"] = "done"

            def waiter(name, tenant):
                try:
                    fetch_table(srv.address, fixed_file, tenant=tenant,
                                max_records=5, **FIXED_OPTS)
                    results[name] = "ok"
                except ServeError as exc:
                    results[name] = str(exc)

            threads = [threading.Thread(target=holder)]
            threads[0].start()
            assert gate.wait(60)
            for name, tenant in (("bronze_w", "bronze"),
                                 ("gold_w", "gold")):
                th = threading.Thread(target=waiter,
                                      args=(name, tenant))
                threads.append(th)
                th.start()
            time.sleep(0.5)  # both queued behind the held slot
            rss["value"] = 950  # spike: shedding evicts bronze first
            # a new arrival triggers the shed sweep and is itself
            # rejected
            with pytest.raises(ServeError):
                fetch_table(srv.address, fixed_file, tenant="probe",
                            max_records=1, **FIXED_OPTS)
            rss["value"] = 100  # recede before the holder releases
            for th in threads:
                th.join(90)
            assert results.get("holder") == "done"
            assert "shed under memory pressure" in results["bronze_w"]
            assert results.get("gold_w") == "ok", results
    finally:
        srv.stop()


def test_server_budget_uninstalled_on_stop(fixed_file):
    """A stopped server's memory budget must not keep throttling the
    process (review-caught: the global watermark outlived the
    server)."""
    from cobrix_tpu.utils.pressure import process_pressure

    srv = ScanServer(memory_budget_mb=1.0).start()
    try:
        assert process_pressure() is not None
        with pytest.raises(ServeError):  # 1 MB budget: sheds instantly
            fetch_table(srv.address, fixed_file, max_records=1,
                        **FIXED_OPTS)
    finally:
        srv.stop()
    assert process_pressure() is None


# -- servecheck smoke (the chunk x workers grid stays behind `slow`) -----


def test_servecheck_quick():
    """The full tool in quick mode: parity, first-batch latency, quota,
    scrape, AND the request-scoped obs section (merged trace, audit
    request_ids, /debug, chaos-slow flight dump)."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "tools/servecheck.py", "--mb", "3"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "request-scoped obs" in proc.stdout


@pytest.mark.slow
def test_servecheck_sweep():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "tools/servecheck.py", "--mb", "6", "--sweep"],
        capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 0, proc.stdout + proc.stderr
