"""Ported reference regression tier (source/regression/Test01-Test11).

Each test pins the behavior of the corresponding reference spec with the
same copybooks, the same handcrafted bytes, and the same expected values
(JSON goldens compared through the Spark-toJSON-compatible renderer).
Test12 lives in test_indexed_scan.py.
"""
import json
import os

import pytest

from cobrix_tpu import read_cobol

BE = "big"


def _write(tmp_path, name, data: bytes) -> str:
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


def _json(out) -> str:
    return "[" + ",".join(out.to_json_lines()) + "]"


def _rdw(payload: bytes) -> bytes:
    return bytes([0, 0, len(payload), 0]) + payload


# -- Test01RecordIdSequence -------------------------------------------------

T1_COPYBOOK = """      01  R.
                03 I        PIC 9(1).
                03 D        PIC 9(1).
"""


@pytest.fixture
def t1_file(tmp_path):
    data = _rdw(bytes([0xF0, 0xF0]))
    for i in range(1, 10):
        data += _rdw(bytes([0xF1, 0xF0 + i]))
    return _write(tmp_path, "recorddata.dat", data)


def test_01_record_id_sequence(t1_file):
    """Record_Ids stay consistent across the indexed scan and survive
    segment filtering (Test01RecordIdSequence.scala)."""
    base = dict(copybook_contents=T1_COPYBOOK, generate_record_id="true",
                input_split_records="5", is_xcom="true",
                schema_retention_policy="collapse_root")
    rows = read_cobol(t1_file, **base).to_dicts()
    assert [r["Record_Id"] for r in rows] == list(range(10))
    assert [r["I"] for r in rows] == [0] + [1] * 9
    assert [r["D"] for r in rows] == list(range(10))

    rows = read_cobol(t1_file, segment_field="I", segment_filter="1",
                      **base).to_dicts()
    assert [r["Record_Id"] for r in rows] == list(range(1, 10))
    assert [r["D"] for r in rows] == list(range(1, 10))

    rows = read_cobol(t1_file, segment_field="I", segment_filter="1",
                      segment_id_root="1", segment_id_prefix="i",
                      **base).to_dicts()
    assert [r["Record_Id"] for r in rows] == list(range(1, 10))
    assert [r["Seg_Id0"] for r in rows] == [f"i_0_{i}" for i in range(1, 10)]


# -- Test02SparseIndexGenerator ---------------------------------------------

def test_02_sparse_index_generator(tmp_path):
    """Split counts and record counts for header/no-header/header-only
    variable-length files (Test02SparseIndexGenerator.scala)."""
    with_header = _rdw(bytes([0xF0]))
    for i in range(1, 10):
        with_header += _rdw(bytes([0xF1, 0xF0 + i]))
    no_header = b"".join(_rdw(bytes([0xF1, 0xF0 + i]))
                         for i in range(1, 10))
    header_only = _rdw(bytes([0xF0]))

    base = dict(copybook_contents=T1_COPYBOOK, generate_record_id="true",
                input_split_records="5", is_xcom="true")
    out = read_cobol(_write(tmp_path, "h.dat", with_header), **base)
    assert len(out) == 10
    assert len(out._results) == 2  # two index splits

    out = read_cobol(_write(tmp_path, "nh.dat", no_header), **base)
    assert len(out) == 9
    assert len(out._results) == 2

    out = read_cobol(_write(tmp_path, "ho.dat", header_only), **base)
    assert len(out) == 1

    # root-boundary splits: with a segment root, splits only land at roots
    out = read_cobol(_write(tmp_path, "h2.dat", with_header),
                     segment_field="I", segment_filter="1",
                     segment_id_root="1", **base)
    assert len(out) == 9


# -- Test03IbmFloats --------------------------------------------------------

T3_COPYBOOK = """       01  R.
                03 F       COMP-1.
                03 D       COMP-2.
"""

T3_CASES = [
    ("IBM", bytes([0x43, 0x14, 0x2E, 0xFC]),
     bytes([0x43, 0x14, 0x2E, 0xFC, 0xCA, 0xF7, 0x09, 0xB7]),
     5.045883, 322.936717),
    ("IBM_little_endian", bytes([0xFC, 0x2E, 0x14, 0x43]),
     bytes([0xB7, 0x09, 0xF7, 0xCA, 0xFC, 0x2E, 0x14, 0x43]),
     5.045883, 322.936717),
    ("IEEE754", bytes([0x40, 0x49, 0x0F, 0xDA]),
     bytes([0x40, 0x09, 0x21, 0xFB, 0x54, 0x44, 0x2E, 0xEA]),
     3.1415925, 3.14159265359),
    ("IEEE754_little_endian", bytes([0xDA, 0x0F, 0x49, 0x40]),
     bytes([0xEA, 0x2E, 0x44, 0x54, 0xFB, 0x21, 0x09, 0x40]),
     3.1415925, 3.14159265359),
]


@pytest.mark.parametrize("fmt,fbytes,dbytes,f_exp,d_exp", T3_CASES)
def test_03_ibm_and_ieee_floats(tmp_path, fmt, fbytes, dbytes, f_exp, d_exp):
    data = _rdw(fbytes + dbytes) * 10
    path = _write(tmp_path, f"fp_{fmt}.dat", data)
    rows = read_cobol(path, copybook_contents=T3_COPYBOOK,
                      generate_record_id="true", is_xcom="true",
                      schema_retention_policy="collapse_root",
                      floating_point_format=fmt).to_dicts()
    assert len(rows) == 10
    assert abs(rows[0]["F"] - f_exp) < 0.00001
    assert abs(rows[0]["D"] - d_exp) < 0.0000000001


# -- Test04VarcharFields ----------------------------------------------------

T4_COPYBOOK = """      01  R.
                03 N     PIC X(1).
                03 V     PIC X(10).
"""


def test_04_varchar_tail_fields(tmp_path):
    """Truncated trailing varchar fields decode the available bytes
    (Test04VarcharFields.scala)."""
    recs = [bytes([0xF0]) + bytes([0xF1, 0xF2, 0xF3, 0xF4, 0xF5, 0xF6,
                                   0xF7, 0xF8, 0xF9, 0xF0]),
            bytes([0xF1]) + bytes([0xF2, 0xF3, 0xF4, 0xF5, 0xF6, 0xF7,
                                   0xF8, 0x40, 0x40, 0x40]),
            bytes([0xF2]) + bytes([0xF2, 0xF3, 0xF4, 0xF5, 0xF6, 0xF7,
                                   0xF8, 0x40, 0x40]),
            bytes([0xF3]) + bytes([0xF1, 0xF2, 0xF3]),
            bytes([0xF4]) + bytes([0xF1]),
            bytes([0xF5])]
    data = b"".join(_rdw(r) for r in recs)
    path = _write(tmp_path, "varchar.dat", data)
    base = dict(copybook_contents=T4_COPYBOOK, generate_record_id="true",
                is_xcom="true", schema_retention_policy="collapse_root")
    rows = read_cobol(path, **base).to_dicts()
    assert [r["N"] for r in rows] == ["0", "1", "2", "3", "4", "5"]
    assert [r["V"] for r in rows] == ["1234567890", "2345678", "2345678",
                                      "123", "1", ""]
    # trimming off keeps the partial bytes verbatim
    rows = read_cobol(path, string_trimming_policy="none", **base).to_dicts()
    assert rows[1]["V"] == "2345678   "
    assert rows[3]["V"] == "123"
    assert rows[5]["V"] == ""


# -- Test05CommaDecimals ----------------------------------------------------

def test_05_comma_decimals(tmp_path):
    """PIC +999,99 — comma as the decimal separator
    (Test05CommaDecimals.scala)."""
    copybook = """      01  R.
                03 N     PIC +999,99 USAGE DISPLAY.
"""
    recs = [bytes([0x4E, 0xF1, 0xF1, 0xF2, 0x6B, 0xF3, 0xF4]),
            bytes([0x40, 0x60, 0xF2, 0xF3, 0x6B, 0xF4, 0xF5]),
            bytes([0x4E, 0xF0, 0xF0, 0xF5, 0x6B, 0xF0, 0xF0])]
    path = _write(tmp_path, "comma.dat", b"".join(recs))
    out = read_cobol(path, copybook_contents=copybook,
                     schema_retention_policy="collapse_root")
    assert _json(out) == '[{"N":112.34},{"N":-23.45},{"N":5.00}]'


def test_05b_fixed_length_var_occurs(tmp_path):
    """variable_size_occurs on the fixed-length ASCII path shortens
    records to the actual OCCURS count
    (Test05FixedLengthVarOccurs.scala)."""
    copybook = """      01  RECORD.
              02 COUNT PIC 9(4).
              02 GROUP OCCURS 0 TO 5 TIMES DEPENDING ON COUNT.
                  03 TEXT   PIC X(3).
                  03 FIELD  PIC 9.
"""
    text = "   5ABC1ABC2ABC3ABC4ABC5   5DEF1DEF2DEF3DEF4DEF5"
    path = _write(tmp_path, "varocc.dat", text.encode())
    rows = read_cobol(path, copybook_contents=copybook,
                      schema_retention_policy="collapse_root",
                      variable_size_occurs="true",
                      encoding="ascii").to_dicts()
    assert len(rows) == 2
    assert rows[0]["COUNT"] == 5
    assert [g[0] for g in rows[0]["GROUP"]] == ["ABC"] * 5
    assert [g[1] for g in rows[1]["GROUP"]] == [1, 2, 3, 4, 5]


# -- Test06EmptySegmentIds --------------------------------------------------

T6_COPYBOOK = """         01  ENTITY.
           05  SEGMENT-ID           PIC X(1).
           05  SEG1.
              10  A                 PIC X(1).
           05  SEG2 REDEFINES SEG1.
              10  B                 PIC X(1).
           05  SEG3 REDEFINES SEG1.
              10  E                 PIC X(1).
"""


def test_06_empty_segment_ids(tmp_path):
    recs = [bytes([0xC1, 0x81]), bytes([0xC2, 0x82]), bytes([0x40, 0x85])]
    path = _write(tmp_path, "seg.dat", b"".join(_rdw(r) for r in recs))
    base = dict(copybook_contents=T6_COPYBOOK, pedantic="true",
                is_record_sequence="true",
                schema_retention_policy="collapse_root",
                segment_field="SEGMENT_ID")
    out = read_cobol(path, **{**base,
                              "redefine_segment_id_map:1": "SEG1 => A",
                              "redefine-segment-id-map:2": "SEG2 => B",
                              "redefine-segment-id-map:3": "SEG3 => "})
    assert _json(out) == (
        '[{"SEGMENT_ID":"A","SEG1":{"A":"a"}},'
        '{"SEGMENT_ID":"B","SEG2":{"B":"b"}},'
        '{"SEGMENT_ID":"","SEG3":{"E":"e"}}]')

    recs.append(bytes([0xC4, 0x84]))
    path = _write(tmp_path, "seg2.dat", b"".join(_rdw(r) for r in recs))
    out = read_cobol(path, **{**base,
                              "redefine_segment_id_map:1": "SEG1 => A",
                              "redefine-segment-id-map:2": "SEG2 => B",
                              "redefine-segment-id-map:3": "SEG3 => ,D"})
    assert _json(out) == (
        '[{"SEGMENT_ID":"A","SEG1":{"A":"a"}},'
        '{"SEGMENT_ID":"B","SEG2":{"B":"b"}},'
        '{"SEGMENT_ID":"","SEG3":{"E":"e"}},'
        '{"SEGMENT_ID":"D","SEG3":{"E":"d"}}]')


# -- Test07IgnoreHiddenFiles ------------------------------------------------

def test_07_hidden_files_ignored(tmp_path):
    copybook = """      01  R.
                03 A     PIC X(2).
"""
    d = tmp_path / "data"
    nested = d / "nested"
    nested.mkdir(parents=True)
    (d / "a.dat").write_bytes(bytes([0xF1, 0xF2, 0xF3, 0xF4]))
    (d / ".hidden").write_bytes(b"\xF1")           # non-divisible, hidden
    (d / "_hidden2").write_bytes(b"\xF1")
    (nested / ".hidden3").write_bytes(b"\xF1")
    rows = read_cobol(str(d), copybook_contents=copybook,
                      schema_retention_policy="collapse_root").to_dicts()
    assert [r["A"] for r in rows] == ["12", "34"]


# -- Test08InputFileName ----------------------------------------------------

def test_08_input_file_name_and_offsets(tmp_path):
    copybook = """      01  R.
                03 A     PIC X(1).
                03 B     PIC X(2).
"""
    data = (bytes([0, 0, 0, 0])
            + bytes([0xF0, 0xF1, 0xF2, 0xF3, 0xF4, 0xF5, 0xF6, 0xF7, 0xF8])
            + bytes([0, 0, 0, 0, 0]))
    path = _write(tmp_path, "bin_file.dat", data)
    out = read_cobol(path, copybook_contents=copybook,
                     with_input_file_name_col="file",
                     file_start_offset="4", file_end_offset="5",
                     schema_retention_policy="collapse_root")
    rows = out.to_dicts()
    assert len(rows) == 3
    assert all(r["file"].endswith("bin_file.dat") for r in rows)
    assert [r["A"] for r in rows] == ["0", "3", "6"]

    # the reference rejects the column on a plain fixed-length read
    # (its test name says retention policy; the rule is variable-length)
    with pytest.raises(ValueError, match="with_input_file_name_col"):
        read_cobol(path, copybook_contents=copybook,
                   with_input_file_name_col="file",
                   schema_retention_policy="collapse_root")


# -- Test09PrimitiveOccurs --------------------------------------------------

def test_09_primitive_occurs(tmp_path):
    copybook = """      01  R.
           05  CNT    PIC 9(1).
           05  A      PIC 9(2) OCCURS 0 TO 5 DEPENDING ON CNT.
"""
    data = bytes([0xF0,
                  0xF1, 0xF2, 0xF3,
                  0xF3, 0xF2, 0xF3, 0xF0, 0xF1, 0xF5, 0xF6,
                  0xF5, 0xF1, 0xF2, 0xF3, 0xF4, 0xF5, 0xF6, 0xF7, 0xF8,
                  0xF9, 0xF0])
    path = _write(tmp_path, "occurs.dat", data)
    out = read_cobol(path, copybook_contents=copybook, pedantic="true",
                     schema_retention_policy="collapse_root",
                     variable_size_occurs="true")
    assert _json(out) == ('[{"CNT":0,"A":[]},{"CNT":1,"A":[23]},'
                          '{"CNT":3,"A":[23,1,56]},'
                          '{"CNT":5,"A":[12,34,56,78,90]}]')

    out = read_cobol(path, copybook_contents=copybook, pedantic="true",
                     schema_retention_policy="collapse_root",
                     variable_size_occurs="true", debug="true")
    assert _json(out) == (
        '[{"CNT":0,"CNT_debug":"F0","A":[],"A_debug":[]},'
        '{"CNT":1,"CNT_debug":"F1","A":[23],"A_debug":["F2F3"]},'
        '{"CNT":3,"CNT_debug":"F3","A":[23,1,56],'
        '"A_debug":["F2F3","F0F1","F5F6"]},'
        '{"CNT":5,"CNT_debug":"F5","A":[12,34,56,78,90],'
        '"A_debug":["F1F2","F3F4","F5F6","F7F8","F9F0"]}]')


# -- Test10DeepSegmentRedefines ---------------------------------------------

def test_10_deep_segment_redefines(tmp_path):
    copybook = """         01  ENTITY.
        02 NESTED1.
           03 NESTED2.
              05  ID                      PIC X(1).
           03 NESTED3.
              04 NESTED4.
                 05  SEG1.
                    10  A                 PIC X(1).
                 05  SEG2 REDEFINES SEG1.
                    10  B                 PIC X(1).
                 05  SEG3 REDEFINES SEG1.
                    10  C                 PIC X(1).
"""
    recs = [bytes([0xC1, 0x81]), bytes([0xC2, 0x82]),
            bytes([0xC3, 0x83]), bytes([0xC4, 0x84])]
    path = _write(tmp_path, "deep.dat", b"".join(_rdw(r) for r in recs))
    out = read_cobol(path, copybook_contents=copybook, pedantic="true",
                     is_record_sequence="true",
                     schema_retention_policy="collapse_root",
                     segment_field="ID",
                     **{"redefine_segment_id_map:1": "SEG1 => A",
                        "redefine-segment-id-map:2": "SEG2 => B",
                        "redefine-segment-id-map:3": "SEG3 => C"})
    assert _json(out) == (
        '[{"NESTED1":{"NESTED2":{"ID":"A"},"NESTED3":{"NESTED4":'
        '{"SEG1":{"A":"a"}}}}},'
        '{"NESTED1":{"NESTED2":{"ID":"B"},"NESTED3":{"NESTED4":'
        '{"SEG2":{"B":"b"}}}}},'
        '{"NESTED1":{"NESTED2":{"ID":"C"},"NESTED3":{"NESTED4":'
        '{"SEG3":{"C":"c"}}}}},'
        '{"NESTED1":{"NESTED2":{"ID":"D"},"NESTED3":{"NESTED4":{}}}}]')


# -- Test11NoCopybookErrMsg -------------------------------------------------

def test_11_copybook_option_errors(tmp_path):
    copybook = """      01  R.
                03 A     PIC X(1).
                03 B     PIC X(2).
"""
    path = _write(tmp_path, "data.dat", bytes([0xF0, 0xF1, 0xF2]))
    out = read_cobol(path, copybook_contents=copybook,
                     schema_retention_policy="collapse_root")
    assert len(out) == 1

    with pytest.raises(Exception, match="COPYBOOK"):
        read_cobol(path)
    with pytest.raises(Exception, match="copybook"):
        read_cobol(path, copybook="dummy", copybook_contents=copybook)
    with pytest.raises(Exception):
        read_cobol(path, copybook=str(tmp_path))  # a dir, not a file
